"""The autoscale decision function: (snapshot, config, seed) → Decision.

Deterministic by construction — no wall clock, no randomness, no tier
access. Time enters only through each snapshot's ``t`` (the tier's
injectable clock) and the controller's record of when it last acted, which
is itself derived from prior snapshots' ``t``: replay the same snapshot
sequence against the same config and the identical decision sequence falls
out. The chaos smoke and tests lean on this to assert exact logs.

**Hysteresis semantics** (the knob table in README's "Elastic fleet"):

* **scale-up** fires when the fast window's worst burn rate crosses
  ``scale_up_burn`` AND the slow window confirms at ``confirm_burn`` —
  the classic SRE multi-window guard: a 5-minute spike alone pages nobody
  and scales nothing unless the hour agrees the budget is actually
  burning. Bounded by ``max_replicas`` and ``up_cooldown_s``.
* **scale-down** fires when the fast burn is at or under
  ``scale_down_burn`` AND nothing is in flight — capacity leaves only
  when idle enough that removing a replica cannot create the breach that
  re-adds it. Bounded by ``min_replicas`` and ``down_cooldown_s``
  (measured from the last scale event in EITHER direction, so a fresh
  scale-up is never immediately unwound).
* the gap between ``scale_up_burn`` and ``scale_down_burn`` is the
  hysteresis band: inside it the fleet holds.

``dry_run`` evaluates and logs every rule identically but stamps the
decision non-actionable — the operator's rehearsal mode
(``iwae-serve --autoscale-dry-run``).

Every decision appends one structured record to :attr:`log` (inputs, rule,
action, cooldown state) and publishes ``fleet/*`` gauges/counters to the
registry, so the loop's reasoning is on the same Prometheus page as the
burn rates it read.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from iwae_replication_project_tpu.serving.fleet.signals import SignalSnapshot
from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

__all__ = ["AutoscaleConfig", "AutoscaleController", "Decision",
           "choose_victim"]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The control loop's knobs (frozen: one immutable policy per loop).

    Defaults are deliberately conservative: scale up only on a confirmed
    burn ≥ 1 (budget burning faster than it refills), scale down only
    when idle, and wait much longer to shrink than to grow."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: fast-window worst burn at/above which the fleet grows
    scale_up_burn: float = 1.0
    #: slow-window confirmation for scale-up (0 = fast window alone)
    confirm_burn: float = 0.0
    #: fast-window worst burn at/below which an idle fleet shrinks
    scale_down_burn: float = 0.25
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    #: window labels (must match the SLOMonitor's — DEFAULT_WINDOWS)
    fast_window: str = "5m"
    slow_window: str = "1h"
    #: evaluate + log decisions but never actuate
    dry_run: bool = False
    #: deterministic tie-break salt: victim choice among equally-loaded
    #: replicas and planner placement order both derive from it — NEVER
    #: from request traffic, so reruns replay exactly
    seed: int = 0
    #: seconds between control ticks (the lifecycle thread's period)
    interval_s: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.scale_down_burn > self.scale_up_burn:
            raise ValueError(
                f"scale_down_burn ({self.scale_down_burn}) above "
                f"scale_up_burn ({self.scale_up_burn}) would make the "
                f"fleet flap: the band between them is the hysteresis")
        for name in ("up_cooldown_s", "down_cooldown_s", "interval_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")


@dataclasses.dataclass(frozen=True)
class Decision:
    """One control tick's outcome (also the decision log's record shape).

    ``action`` is ``"up"``, ``"down"``, or ``"hold"``; ``target`` the
    desired live-replica count after actuation (equal to ``replicas`` on
    hold); ``victim`` the stable index scale-down should drain (None
    otherwise); ``rule`` names the clause that decided (the log's grep
    key); ``dry_run`` marks a decision that must not be actuated."""

    action: str
    target: int
    replicas: int
    rule: str
    reason: str
    t: float
    victim: Optional[int] = None
    dry_run: bool = False

    def record(self, snapshot: SignalSnapshot,
               config: AutoscaleConfig) -> dict:
        """The structured log entry: decision + the inputs it was a pure
        function of (enough to replay the tick)."""
        return {
            "t": self.t, "action": self.action, "rule": self.rule,
            "reason": self.reason, "replicas": self.replicas,
            "target": self.target, "victim": self.victim,
            "dry_run": self.dry_run,
            "inputs": {
                "burn_fast": snapshot.burn(config.fast_window),
                "burn_slow": snapshot.burn(config.slow_window),
                "requests_fast": snapshot.requests_in(config.fast_window),
                "outstanding": snapshot.outstanding,
                "draining": snapshot.draining,
                "unhealthy": snapshot.unhealthy,
            },
        }


def choose_victim(live_indices: Sequence[int], inflight: Sequence[int],
                  seed: int = 0) -> Optional[int]:
    """Which replica a scale-down drains: the least-loaded, youngest-first
    (highest stable index — the most recently joined replica has the
    coldest affinity groups, so removing it disturbs the fewest warm
    paths). Among candidates tied on both, ``seed`` rotates the pick —
    a deterministic salt, not randomness. None when no candidate."""
    if not live_indices:
        return None
    pairs = sorted(zip(live_indices, inflight),
                   key=lambda p: (p[1], -p[0]))
    best = [i for i, load in pairs if load == pairs[0][1]]
    return best[seed % len(best)]


class AutoscaleController:
    """Holds the config, the cooldown state, and the decision log.

    :meth:`decide` is the loop's brain; it never actuates — the
    :class:`~.lifecycle.FleetManager` (or a dry-run operator) owns that.
    ``registry`` is where the ``fleet/*`` instruments land (pass the tier
    router's registry so they share its Prometheus page)."""

    def __init__(self, config: AutoscaleConfig,
                 registry: Optional[MetricRegistry] = None):
        self.config = config
        self.registry = registry if registry is not None else MetricRegistry()
        self.log: List[dict] = []
        #: t of the last actuated scale event per direction (None = never);
        #: derived purely from decided snapshots' t — replay-stable
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        for name in ("decisions", "scale_ups", "scale_downs", "holds"):
            self.registry.counter(f"fleet/{name}")
        self.registry.gauge("fleet/target_replicas").set(0)

    # -- the decision function ----------------------------------------------

    def decide(self, snap: SignalSnapshot) -> Decision:
        """One tick: reduce the snapshot to a Decision under the config's
        hysteresis/cooldown/bounds rules, append the structured record,
        publish the ``fleet/*`` instruments."""
        cfg = self.config
        fast = snap.burn(cfg.fast_window)
        slow = snap.burn(cfg.slow_window)
        n = snap.replicas
        d = self._decide(snap, cfg, fast, slow, n)
        if not d.dry_run:
            if d.action == "up":
                self._last_up = d.t
            elif d.action == "down":
                self._last_down = d.t
        self.log.append(d.record(snap, cfg))
        self._publish(d, fast, slow)
        return d

    def _decide(self, snap: SignalSnapshot, cfg: AutoscaleConfig,
                fast: float, slow: float, n: int) -> Decision:
        def mk(action, target, rule, reason, victim=None):
            return Decision(action=action, target=target, replicas=n,
                            rule=rule, reason=reason, t=snap.t,
                            victim=victim, dry_run=cfg.dry_run)

        if n == 0:
            # nothing live (mid-fault, or every replica draining): shape
            # changes now would race recovery — the probe loop owns this
            return mk("hold", n, "no-live-replicas",
                      "no live replica to scale against")
        breach = fast >= cfg.scale_up_burn and slow >= cfg.confirm_burn
        if breach:
            if n >= cfg.max_replicas:
                return mk("hold", n, "at-max",
                          f"burn {fast:.2f} breaches {cfg.scale_up_burn} "
                          f"but fleet is at max_replicas={cfg.max_replicas}")
            last = self._last_up
            if last is not None and snap.t - last < cfg.up_cooldown_s:
                return mk("hold", n, "up-cooldown",
                          f"burn {fast:.2f} breaches but last scale-up was "
                          f"{snap.t - last:.1f}s ago "
                          f"(< {cfg.up_cooldown_s}s)")
            return mk("up", n + 1, "burn-breach",
                      f"fast burn {fast:.2f} >= {cfg.scale_up_burn} with "
                      f"slow burn {slow:.2f} >= {cfg.confirm_burn}: grow "
                      f"{n} -> {n + 1}")
        idle = fast <= cfg.scale_down_burn and snap.outstanding == 0
        if idle and n > cfg.min_replicas:
            last_event = max((t for t in (self._last_up, self._last_down)
                              if t is not None), default=None)
            if last_event is not None and \
                    snap.t - last_event < cfg.down_cooldown_s:
                return mk("hold", n, "down-cooldown",
                          f"idle but last scale event was "
                          f"{snap.t - last_event:.1f}s ago "
                          f"(< {cfg.down_cooldown_s}s)")
            victim = choose_victim(snap.live_indices, snap.inflight,
                                   cfg.seed)
            return mk("down", n - 1, "idle",
                      f"fast burn {fast:.2f} <= {cfg.scale_down_burn} with "
                      f"0 outstanding: shrink {n} -> {n - 1} "
                      f"(drain r{victim})", victim=victim)
        if idle:
            return mk("hold", n, "at-min",
                      f"idle but fleet is at min_replicas={cfg.min_replicas}")
        return mk("hold", n, "in-band",
                  f"fast burn {fast:.2f} inside the hysteresis band "
                  f"({cfg.scale_down_burn}, {cfg.scale_up_burn})")

    # -- observability -------------------------------------------------------

    def _publish(self, d: Decision, fast: float, slow: float) -> None:
        reg = self.registry
        reg.counter("fleet/decisions").inc()
        reg.counter("fleet/scale_ups" if d.action == "up" else
                    "fleet/scale_downs" if d.action == "down" else
                    "fleet/holds").inc()
        reg.gauge("fleet/target_replicas").set(d.target)
        reg.gauge("fleet/burn_fast").set(fast)
        reg.gauge("fleet/burn_slow").set(slow)
        reg.gauge("fleet/dry_run").set(1 if d.dry_run else 0)
