"""Fleet actuation: turn Decisions into replica joins, drains, and plans.

:class:`FleetManager` is the only fleet module that touches a live tier.
It owns:

* **the control thread** — :meth:`start` runs :meth:`step` every
  ``config.interval_s`` (signals → decide → actuate → re-place) until
  :meth:`stop`; each step also works standalone, which is how the tests
  and the chaos smoke drive deterministic scale events;
* **warm scale-up** — ``replica_factory()`` builds a new engine over the
  SHARED params with the persistent XLA + autotune caches active, and the
  manager warms it (``engine.warmup()`` — every compile collapses to a
  cache-hit deserialize: the 0-fresh-compiles join the smoke pins) BEFORE
  :meth:`~..frontend.router.ReplicaRouter.add_replica` exposes it to
  traffic;
* **drain-based scale-down** — the decision's victim leaves through
  :meth:`~..frontend.router.ReplicaRouter.remove_replica`: intake stops,
  in-flight work finishes or reroutes with its original seeds, and only
  then does the replica leave the fleet. The stopped engine is retained
  in :attr:`retired` (the caller's teardown list), never abandoned;
* **placement** — after every shape change, :meth:`rebalance` re-plans
  model residency (:func:`~.planner.plan_placement` over the store's
  ``model_costs`` and budget), swaps the store's model pins to the new
  plan, and primes router affinity so each model's traffic favors its
  planned home. Placement moves warmth only — results are a pure
  function of (weights, payload, seed, k), and seeds were minted at
  admission.

A replica killed mid-scale-event (the PR 10 fault schedule's favorite
moment) is absorbed by the router's failure path: its in-flight work
reroutes with original seeds, the manager's step logs the actuation error
and the loop keeps ticking — scaling machinery must never turn one
replica's death into a fleet outage.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional, Sequence

from iwae_replication_project_tpu.serving.fleet.controller import (
    AutoscaleConfig,
    AutoscaleController,
    Decision,
    choose_victim,
)
from iwae_replication_project_tpu.serving.fleet.planner import (
    PlacementPlan,
    plan_placement,
)
from iwae_replication_project_tpu.serving.fleet.signals import (
    SignalSnapshot,
    local_signals,
)

__all__ = ["FleetManager"]


class FleetManager:
    """One autoscaled tier: tier + replica factory + controller + planner.

    ``tier`` is a running :class:`~..frontend.server.ServingTier` (only
    its ``router``, ``slo``, and ``clock`` are used, so router-only test
    rigs drive it too). ``replica_factory`` is a zero-arg callable
    returning a NEW engine sharing the fleet's params — the scale-up
    primitive. ``store`` defaults to the process executable store;
    ``affinity_ops`` are the op groups placement primes (the default-k
    group per op). ``warm_join=False`` skips the pre-join warmup (tests
    with fakes; production keeps it on — joining cold would serve the
    first requests at compile latency)."""

    def __init__(self, tier, replica_factory: Callable[[], object],
                 config: Optional[AutoscaleConfig] = None, *,
                 store=None,
                 affinity_ops: Sequence[str] = ("score",),
                 warm_join: bool = True,
                 warmup_ops: Optional[Sequence[str]] = None,
                 drain_timeout_s: float = 30.0,
                 clock: Optional[Callable[[], float]] = None):
        self.tier = tier
        self.router = tier.router
        self.config = config if config is not None else AutoscaleConfig()
        self._factory = replica_factory
        self._clock = clock if clock is not None \
            else getattr(tier, "clock", time.monotonic)
        self.controller = AutoscaleController(
            self.config, registry=self.router.registry)
        if store is None:
            from iwae_replication_project_tpu.utils.compile_cache import (
                executable_store)
            store = executable_store()
        self.store = store
        self.affinity_ops = tuple(affinity_ops)
        self.warm_join = bool(warm_join)
        #: ops the pre-join warmup compiles (None = the engine's default
        #: full set); smokes pin this to the op set the tier itself warmed
        #: so the 0-fresh-compiles join claim stays exact
        self.warmup_ops = tuple(warmup_ops) if warmup_ops is not None \
            else None
        self.drain_timeout_s = float(drain_timeout_s)
        #: engines retired by scale-down (already stopped; caller teardown)
        self.retired: List[object] = []
        #: placement + actuation-error records, same vein as controller.log
        self.placement_log: List[dict] = []
        self.plan: Optional[PlacementPlan] = None
        self._pins: List[object] = []
        # one actuation at a time: the loop thread and direct test calls
        # serialize here; the router/store locks are only ever taken
        # INSIDE this one (fleet -> router/store, never back — the lock
        # graph stays a tree)
        self._lock = threading.Lock()
        self._loop: Optional[threading.Thread] = None
        self._loop_stop = threading.Event()

    # -- one control tick ----------------------------------------------------

    def signals(self) -> SignalSnapshot:
        return local_signals(self.tier, clock=self._clock)

    def step(self) -> Decision:
        """signals → decide → actuate → re-place. Never raises for a
        failed actuation (a dead replica mid-scale-event is the router's
        to absorb) — the error lands in :attr:`placement_log` and the
        loop keeps its cadence."""
        with self._lock:
            snap = self.signals()
            decision = self.controller.decide(snap)
            if decision.dry_run or decision.action == "hold":
                return decision
            try:
                if decision.action == "up":
                    self._scale_up_locked()
                elif decision.action == "down":
                    self._scale_down_locked(decision.victim)
            except Exception as e:
                self.placement_log.append({
                    "t": snap.t, "event": "actuation-error",
                    "action": decision.action,
                    "error": f"{type(e).__name__}: {e}"})
            return decision

    # -- actuation ------------------------------------------------------------

    def scale_up(self) -> int:
        with self._lock:
            return self._scale_up_locked()

    def scale_down(self, victim: Optional[int] = None) -> int:
        with self._lock:
            return self._scale_down_locked(victim)

    def _scale_up_locked(self) -> int:
        engine = self._factory()
        start = getattr(engine, "start", None)
        if callable(start):
            start()
        if self.warm_join:
            warmup = getattr(engine, "warmup", None)
            if callable(warmup):
                # the warm join itself: over shared params with the
                # persistent caches active every compile here is a
                # deserialize — the replica meets traffic already warm
                if self.warmup_ops is not None:
                    warmup(ops=self.warmup_ops)
                else:
                    warmup()
        index = self.router.add_replica(engine)
        self._rebalance_locked("scale-up")
        return index

    def _scale_down_locked(self, victim: Optional[int]) -> int:
        if victim is None:
            states = [s for s in self.router.replica_states()
                      if s["healthy"] and not s["draining"]]
            victim = choose_victim([s["index"] for s in states],
                                   [s["inflight"] for s in states],
                                   self.config.seed)
        if victim is None:
            raise ValueError("no live replica to scale down")
        engine = self.router.remove_replica(victim, self.drain_timeout_s)
        self.retired.append(engine)
        self._rebalance_locked("scale-down")
        return victim

    # -- placement ------------------------------------------------------------

    def rebalance(self) -> PlacementPlan:
        with self._lock:
            return self._rebalance_locked("manual")

    def _rebalance_locked(self, cause: str) -> PlacementPlan:
        from iwae_replication_project_tpu.utils.compile_cache import (
            DEFAULT_MODEL)

        costs = {}
        model_costs = getattr(self.store, "model_costs", None)
        if callable(model_costs):
            costs = dict(model_costs())
        states = [s for s in self.router.replica_states()
                  if not s["draining"]]
        budget = getattr(self.store, "budget_bytes", None)
        budgets = {s["index"]: budget for s in states}
        universe = frozenset(costs)
        replica_models = {
            s["index"]: (frozenset(s["models"])
                         if s.get("models") is not None else universe)
            for s in states}
        plan = plan_placement(costs, budgets,
                              replica_models=replica_models,
                              seed=self.config.seed)
        # swap pins to the new plan: pin-before-release, so a model placed
        # in both plans never has a pinless window a concurrent budget
        # squeeze could evict through
        old_pins, self._pins = self._pins, []
        for model in plan.placed():
            self._pins.append(self.store.pin_model(model))
        for pin in old_pins:
            pin.release()
        # affinity priming: each placed model's default-k groups point at
        # its planned home (a hint — load imbalance still overrides)
        for model in plan.placed():
            home = plan.home_of(model)
            if home is None or model == DEFAULT_MODEL:
                continue
            for op in self.affinity_ops:
                self.router.prime_affinity(model, op, None, home)
        self.plan = plan
        self.placement_log.append({
            "t": self._clock(), "event": "rebalance", "cause": cause,
            **plan.record()})
        return plan

    # -- the control thread ----------------------------------------------------

    def start(self) -> "FleetManager":
        """Run :meth:`step` every ``config.interval_s`` until :meth:`stop`
        (idempotent; the thread is a daemon, like the tier monitor)."""
        if self._loop is not None:
            return self
        self._loop_stop.clear()

        def loop():
            while not self._loop_stop.wait(self.config.interval_s):
                self.step()

        self._loop = threading.Thread(target=loop,
                                      name="iwae-fleet-autoscaler",
                                      daemon=True)
        self._loop.start()
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop_stop.set()
            self._loop.join()
            self._loop = None

    # -- introspection ---------------------------------------------------------

    @property
    def decision_log(self) -> List[dict]:
        return self.controller.log

    def doc(self) -> dict:
        """One JSON-able status document (the smoke/bench artifact body)."""
        return {
            "config": dataclasses.asdict(self.config),
            "decisions": list(self.controller.log),
            "placements": list(self.placement_log),
            "replicas": self.router.replica_states(),
        }
