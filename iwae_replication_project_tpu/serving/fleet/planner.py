"""Cost-model placement: bin-pack models onto replica store budgets.

The executable store bills every resident program the ``peak_bytes`` of
its trace-time ``static_cost`` record (utils/compile_cache.py), and
:meth:`~...utils.compile_cache.ExecutableStore.model_costs` sums that per
model — what one replica pays in store budget to keep a model's working
set warm. This module turns those costs plus per-replica budgets into a
:class:`PlacementPlan`: which models live *resident* where.

The packing is deterministic first-fit-decreasing — models sorted by
(cost desc, name asc), replicas visited in stable-index order rotated by
the config seed — so the same (costs, budgets, capabilities, seed)
always yields the same plan, and the decision log's placement records
replay. A model that fits no budgeted replica goes to ``overflow``: it is
still SERVED (routing eligibility never depends on placement — the
store's LRU tiers handle its executables), it just isn't pinned resident
anywhere.

Applying a plan is the lifecycle's job: model-level store pins
(:meth:`~...utils.compile_cache.ExecutableStore.pin_model`) make the
placed working sets unevictable, and router affinity hints
(:meth:`~..frontend.router.ReplicaRouter.prime_affinity`) steer each
model's traffic to its planned home — both re-applied on every
fleet-shape change, neither affecting results (seeds were minted at
admission; placement only moves warmth).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple

__all__ = ["PlacementPlan", "plan_placement"]


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """One placement decision (immutable; logged verbatim).

    ``assignments`` maps each replica's stable index to the models planned
    resident there (sorted tuples throughout — the plan is its own
    canonical form); ``overflow`` lists models no budgeted replica could
    hold; ``costs`` echoes the cost model the packing used."""

    assignments: Tuple[Tuple[int, Tuple[str, ...]], ...]
    overflow: Tuple[str, ...]
    costs: Tuple[Tuple[str, int], ...]

    def models_for(self, index: int) -> Tuple[str, ...]:
        for i, models in self.assignments:
            if i == index:
                return models
        return ()

    def placed(self) -> Tuple[str, ...]:
        """Every model the plan made resident somewhere (sorted)."""
        return tuple(sorted({m for _, ms in self.assignments for m in ms}))

    def home_of(self, model: str) -> Optional[int]:
        """The replica index a model's traffic should favor (None when
        overflowed or unknown)."""
        for i, models in self.assignments:
            if model in models:
                return i
        return None

    def record(self) -> dict:
        """The decision-log entry shape."""
        return {"assignments": [[i, list(ms)] for i, ms in self.assignments],
                "overflow": list(self.overflow),
                "costs": {m: c for m, c in self.costs}}


def plan_placement(model_costs: Mapping[str, int],
                   replica_budgets: Mapping[int, Optional[int]],
                   *,
                   replica_models: Optional[Mapping[int, frozenset]] = None,
                   seed: int = 0) -> PlacementPlan:
    """First-fit-decreasing packing of ``model_costs`` onto
    ``replica_budgets``.

    ``replica_budgets`` maps stable replica index → store budget bytes
    (None = unbounded: everything offered fits). ``replica_models``
    optionally restricts which models a replica may host (its capability
    set — a replica is never planned to hold weights it doesn't have);
    absent, every replica may host every model. ``seed`` rotates the
    replica visiting order — the deterministic tie-break between replicas
    with equal remaining headroom, matching the controller's victim salt.
    """
    order = sorted(model_costs.items(), key=lambda kv: (-kv[1], kv[0]))
    indices = sorted(replica_budgets)
    if indices and seed:
        rot = seed % len(indices)
        indices = indices[rot:] + indices[:rot]
    remaining: Dict[int, Optional[float]] = {
        i: (None if replica_budgets[i] is None else float(replica_budgets[i]))
        for i in indices}
    placed: Dict[int, list] = {i: [] for i in indices}
    overflow = []
    for model, cost in order:
        home = None
        for i in indices:
            if replica_models is not None and \
                    model not in replica_models.get(i, frozenset()):
                continue
            room = remaining[i]
            if room is None or room >= cost:
                home = i
                break
        if home is None:
            overflow.append(model)
            continue
        placed[home].append(model)
        if remaining[home] is not None:
            remaining[home] -= cost
    return PlacementPlan(
        assignments=tuple(sorted((i, tuple(sorted(ms)))
                                 for i, ms in placed.items())),
        overflow=tuple(sorted(overflow)),
        costs=tuple(sorted((m, int(c)) for m, c in model_costs.items())),
    )
