"""Signal collection: one immutable snapshot per autoscaler tick.

The controller never touches the tier directly — it decides from a
:class:`SignalSnapshot`, a frozen value object built here. That split is
what makes the whole loop deterministic and replayable: feed the same
snapshot sequence to the same config and the same decisions fall out
(tests construct snapshots by hand; the decision log records enough of
each to reconstruct why).

Two constructors, one schema:

* :func:`local_signals` — read the tier in-process: the SLOMonitor's
  snapshot, the router's replica states and outstanding count, the
  executable store's residency scalars;
* :func:`wire_signals` — the same snapshot from a child tier's ``slo``
  control document (:meth:`~..frontend.remote.RemoteEngine.slo`), so a
  fleet-of-fleets parent scales children it only sees as JSON.

Both reduce the per-(model, op) burn-rate document with the SAME pure
functions (:func:`~...telemetry.slo.peak_burns` /
:func:`~...telemetry.slo.window_requests`) — a wire hop must not change
what the controller sees.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional, Tuple

from iwae_replication_project_tpu.telemetry.slo import (
    peak_burns,
    window_requests,
)

__all__ = ["SignalSnapshot", "local_signals", "wire_signals"]


@dataclasses.dataclass(frozen=True)
class SignalSnapshot:
    """Everything one autoscale decision is a function of (plus config).

    ``burns``/``requests`` are keyed by window label (``"5m"``/``"1h"``
    by default): the worst burn rate across every (model, op) class and
    both objectives, and the total trailing-window request count — the
    reductions :func:`~...telemetry.slo.peak_burns` and
    :func:`~...telemetry.slo.window_requests` define. ``t`` comes from
    the tier's (injectable) clock, so cooldown arithmetic is as testable
    as everything else."""

    t: float
    #: live replicas: healthy and not draining (what capacity decisions
    #: count); draining/unhealthy are context, not capacity
    replicas: int
    draining: int
    unhealthy: int
    outstanding: int
    burns: Dict[str, float]
    requests: Dict[str, int]
    #: store residency scalars ({} when unavailable): resident_bytes /
    #: budget_bytes / entries — the placement planner's context
    store: Dict[str, object]
    #: stable indices of the live replicas (victim selection input)
    live_indices: Tuple[int, ...] = ()
    #: per-live-replica inflight, aligned with live_indices
    inflight: Tuple[int, ...] = ()

    def burn(self, label: str) -> float:
        """Worst burn in window ``label`` (0.0 = no traffic observed)."""
        return float(self.burns.get(label, 0.0))

    def requests_in(self, label: str) -> int:
        return int(self.requests.get(label, 0))


def _from_parts(slo_snapshot: dict, replica_states, outstanding: int,
                store: Optional[dict], t: float) -> SignalSnapshot:
    live = [s for s in replica_states
            if s.get("healthy") and not s.get("draining")]
    return SignalSnapshot(
        t=float(t),
        replicas=len(live),
        draining=sum(1 for s in replica_states if s.get("draining")),
        unhealthy=sum(1 for s in replica_states
                      if not s.get("healthy") and not s.get("draining")),
        outstanding=int(outstanding),
        burns=peak_burns(slo_snapshot),
        requests=window_requests(slo_snapshot),
        store=dict(store) if store else {},
        live_indices=tuple(s["index"] for s in live),
        inflight=tuple(int(s.get("inflight", 0)) for s in live),
    )


def local_signals(tier, *,
                  clock: Optional[Callable[[], float]] = None,
                  ) -> SignalSnapshot:
    """Snapshot a local :class:`~..frontend.server.ServingTier`.

    A tier with SLO accounting disabled reads as zero burns (the
    controller then only ever scales on the explicit bounds) — missing
    signal must degrade to "hold", never crash the loop."""
    clk = clock if clock is not None else getattr(tier, "clock",
                                                  time.monotonic)
    slo = getattr(tier, "slo", None)
    snap = slo.snapshot() if slo is not None else {}
    store: Optional[dict] = None
    try:
        from iwae_replication_project_tpu.utils.compile_cache import (
            executable_store)
        store = executable_store().scalar_stats()
    except Exception:
        store = None
    return _from_parts(snap, tier.router.replica_states(),
                       tier.router.outstanding, store, clk())


def wire_signals(doc: dict, *, replica_states, outstanding: int = 0,
                 t: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 ) -> SignalSnapshot:
    """Snapshot from a child tier's ``slo`` control document.

    ``doc`` is what :meth:`~..frontend.remote.RemoteEngine.slo` returns
    (``{"enabled": ..., "slo": {...}}`` — the raw ``SLOMonitor.snapshot``
    shape is also accepted); ``replica_states`` come from the PARENT
    router (the parent scales its own proxies — the child's internal
    shape is the child's business)."""
    snap = doc.get("slo", doc) if isinstance(doc, dict) else {}
    return _from_parts(snap if isinstance(snap, dict) else {},
                       replica_states, outstanding, None,
                       t if t is not None else clock())
