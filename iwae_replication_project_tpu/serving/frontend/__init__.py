"""Network-facing serving tier: replica fleet, router, admission control.

This package is the public boundary of the serving stack — the layer that
turns N in-process :class:`~..engine.ServingEngine` replicas (one per
device, or per mesh slice; process-local threads on CPU CI) into one
network endpoint speaking JSON lines over TCP:

    client ──TCP──► ServingTier (server.py)
                      │  admission: global ceiling + per-client quotas
                      ▼
                    ReplicaRouter (router.py)
                      │  least-inflight + (op, k) affinity, failure reroute
                      ▼
                    ServingEngine replicas (…serving/engine.py)

Module map — one concern per file, every policy unit-testable with fakes:

* ``protocol.py`` — the wire format (JSON lines, typed error codes) and
  framing helpers; no sockets, no engines;
* ``quotas.py`` — per-client token-bucket admission (the quota state
  machine; injectable clock);
* ``router.py`` — replica selection (least-inflight with (op, k) bucket
  affinity), health/readiness (failure + stall detection, warm-probe
  re-admission), reroute-with-same-seed retries, graceful drain;
* ``server.py`` — the TCP front end: per-connection request loop,
  admission control, response completion callbacks, shutdown drain;
* ``client.py`` — the matching socket client (``iwae-serve --client``,
  smoke scripts, benches);
* ``remote.py`` — a running tier wrapped back into the engine surface
  (``RemoteEngine``), so a parent router composes fleets out of processes
  (the ``replica_scaling`` bench) and recursively out of fleets;
* ``retry.py`` — :class:`RetryPolicy`: exponential backoff with
  decorrelated jitter, per-code retryability, deadlines, ``retry_after_s``
  hints, and tail-latency hedging — the client half of the failure model,
  consumed by ``TierClient(retry=...)`` and ``RemoteEngine(retry=...)``.

Per-request semantics are unchanged from the single engine: requests are
scored with k-sample IWAE log p̂(x) (arXiv:1509.00519), seeds are minted at
tier admission in arrival order and carried through routing — so results
are bitwise identical to a direct single-engine run no matter how the fleet
routed, rerouted, or padded the work.

Observability rides the same path: every request carries a trace context
(telemetry/tracing.py — minted by the front end or accepted from the wire
``trace`` field) whose spans cover admission, router dispatch attempts,
RemoteEngine hops, and the engine pipeline stages, landing as one tree per
request in the tail-sampled flight recorder (``traces`` control op,
``/traces`` endpoint, ``iwae-trace`` CLI); the front end also feeds each
completion into the SLO burn-rate monitor (telemetry/slo.py), whose
``slo/*`` gauges share the tier registry's Prometheus page with
``router/*``.  Both are host-side metadata only — serving bits are
identical with them on or off.
"""

from iwae_replication_project_tpu.serving.frontend.client import TierClient
from iwae_replication_project_tpu.serving.frontend.protocol import (
    ERROR_CODES,
    error_code_for,
)
from iwae_replication_project_tpu.serving.frontend.quotas import (
    ClientQuotas,
    QuotaExceeded,
    QuotaPolicy,
)
from iwae_replication_project_tpu.serving.frontend.remote import RemoteEngine
from iwae_replication_project_tpu.serving.frontend.retry import RetryPolicy
from iwae_replication_project_tpu.serving.frontend.router import (
    ReplicaRouter,
    ReplicaUnavailable,
    TierOverloaded,
)
from iwae_replication_project_tpu.serving.frontend.server import ServingTier

__all__ = ["ServingTier", "ReplicaRouter", "TierClient", "RemoteEngine",
           "RetryPolicy", "ClientQuotas", "QuotaPolicy", "QuotaExceeded",
           "TierOverloaded", "ReplicaUnavailable", "ERROR_CODES",
           "error_code_for"]
