"""Socket client for the serving tier (``iwae-serve --client``).

One TCP connection speaking the JSON-lines protocol, with two calling
shapes:

* **blocking** — :meth:`TierClient.request` (and the ``score`` / ``encode``
  / ``decode`` sugar) sends one request and waits for its response;
* **pipelined** — :meth:`submit` writes a request and returns its id
  immediately; :meth:`drain` reads until every outstanding id has its
  response. The tier answers out of order (replicas finish when they
  finish), so both shapes demultiplex on the echoed ``id``.

Results come back as plain Python lists (the JSON payload, one entry per
row) and errors as :class:`TierError` carrying the typed protocol code —
the client performs no array conversion, so callers choose their own
container (and this module stays clean under the serving host-sync lint,
which covers serving/frontend/).

The client is intentionally single-threaded: reads happen on the calling
thread inside ``request``/``drain``. One client = one connection = one
in-order request stream; run several clients for concurrency (the bench
and smoke do).
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional

from iwae_replication_project_tpu.serving.frontend import protocol

__all__ = ["TierClient", "TierError"]


class TierError(RuntimeError):
    """A typed error response from the tier (``code`` is one of
    :data:`~.protocol.ERROR_CODES`)."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


class TierClient:
    """One JSON-lines connection to a :class:`~.server.ServingTier`."""

    def __init__(self, host: str, port: int, *,
                 client_id: Optional[str] = None,
                 timeout_s: Optional[float] = 60.0):
        self.client_id = client_id
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = protocol.LineReader(self._sock)
        self._next_id = 0
        #: id -> response, for replies read while waiting on another id
        self._responses: Dict[int, Dict[str, Any]] = {}

    # -- pipelined API ------------------------------------------------------

    def submit(self, op: str, x, k: Optional[int] = None,
               seed: Optional[int] = None) -> int:
        """Send one request without waiting; returns its wire id. ``seed``
        (single-row payloads only) is the fleet-composition hook — see
        protocol.py; ordinary callers leave it unset."""
        self._next_id += 1
        req_id = self._next_id
        req: Dict[str, Any] = {"id": req_id, "op": op, "x": x}
        if k is not None:
            req["k"] = k
        if seed is not None:
            req["seed"] = seed
        if self.client_id is not None:
            req["client"] = self.client_id
        self._sock.sendall(protocol.encode_line(req))
        return req_id

    def _read_one(self) -> Dict[str, Any]:
        line = self._reader.next_line()
        if line is None:
            raise ConnectionError("tier closed the connection")
        return protocol.decode_line(line)

    def wait(self, req_id: int) -> List[Any]:
        """Block until `req_id`'s response arrives (buffering others);
        returns the per-row result list or raises :class:`TierError`."""
        while req_id not in self._responses:
            resp = self._read_one()
            self._responses[resp.get("id")] = resp
        resp = self._responses.pop(req_id)
        if not resp.get("ok"):
            raise TierError(resp.get("error", "internal"),
                            resp.get("message", ""))
        return resp["result"]

    def drain(self, req_ids: List[int]) -> Dict[int, Dict[str, Any]]:
        """Collect the raw response objects for every id (errors included
        as objects, NOT raised — burst callers triage afterwards)."""
        want = set(req_ids)
        out: Dict[int, Dict[str, Any]] = {}
        for rid in list(want):
            if rid in self._responses:
                out[rid] = self._responses.pop(rid)
                want.discard(rid)
        while want:
            resp = self._read_one()
            rid = resp.get("id")
            if rid in want:
                out[rid] = resp
                want.discard(rid)
            else:
                self._responses[rid] = resp
        return out

    # -- blocking API -------------------------------------------------------

    def request(self, op: str, x, k: Optional[int] = None) -> List[Any]:
        return self.wait(self.submit(op, x, k=k))

    def score(self, x, k: Optional[int] = None) -> List[Any]:
        """Per-row k-sample IWAE log p̂(x) (list of floats)."""
        return self.request("score", x, k=k)

    def encode(self, x, k: Optional[int] = None) -> List[Any]:
        return self.request("encode", x, k=k)

    def decode(self, h) -> List[Any]:
        return self.request("decode", h)

    def _control(self, op: str) -> Dict[str, Any]:
        self._next_id += 1
        self._sock.sendall(protocol.encode_line(
            {"id": self._next_id, "op": op}))
        return self.wait(self._next_id)

    def info(self) -> Dict[str, Any]:
        """The tier's ``info`` control document (ops, row dims, buckets)."""
        return self._control("info")

    def stats(self) -> Dict[str, Any]:
        """The tier's live ``stats`` document (router counters/gauges,
        replica health, per-engine counters)."""
        return self._control("stats")

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "TierClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
