"""Socket client for the serving tier (``iwae-serve --client``).

One TCP connection speaking the JSON-lines protocol, with two calling
shapes:

* **blocking** — :meth:`TierClient.request` (and the ``score`` / ``encode``
  / ``decode`` sugar) sends one request and waits for its response;
* **pipelined** — :meth:`submit` writes a request and returns its id
  immediately; :meth:`drain` reads until every outstanding id has its
  response. The tier answers out of order (replicas finish when they
  finish), so both shapes demultiplex on the echoed ``id``.

Results come back as plain Python lists (the JSON payload, one entry per
row) and errors as :class:`TierError` carrying the typed protocol code —
the client performs no array conversion, so callers choose their own
container (and this module stays clean under the serving host-sync lint,
which covers serving/frontend/).

**Self-healing** (``retry=RetryPolicy(...)``): the blocking path becomes
the failure model's last hop — typed retryable errors back off (honoring
the response's ``retry_after_s`` hint) and resend; a dropped or garbled
connection reconnects first; and with ``hedge_after_s`` set, a request
unanswered past the hedge delay is re-sent on a second connection,
first response wins, the loser's connection is closed. Retrying is safe
because serving results are a pure function of (weights, payload, seed,
k): a caller that pins an explicit ``seed`` gets the bitwise-identical
result on any attempt, any replica, any connection — the chaos smoke's
parity proof. The pipelined API stays raw by design (ids are per
connection; a reconnect abandons unread pipelined responses), and
:attr:`retry_stats` counts retries/reconnects/hedges for smoke
accounting.

The client is intentionally single-threaded except during a hedge race:
reads happen on the calling thread inside ``request``/``drain``. One
client = one connection = one in-order request stream; run several
clients for concurrency (the bench and smoke do).
"""

from __future__ import annotations

import queue as _queue
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from iwae_replication_project_tpu.serving.frontend import protocol
from iwae_replication_project_tpu.serving.frontend.retry import RetryPolicy

__all__ = ["TierClient", "TierError"]


class TierError(RuntimeError):
    """A typed error response from the tier (``code`` is one of
    :data:`~.protocol.ERROR_CODES`; ``retry_after_s`` is the response's
    optional machine-readable back-off hint)."""

    def __init__(self, code: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.retry_after_s = retry_after_s


class TierClient:
    """One JSON-lines connection to a :class:`~.server.ServingTier`."""

    def __init__(self, host: str, port: int, *,
                 client_id: Optional[str] = None,
                 timeout_s: Optional[float] = 60.0,
                 retry: Optional[RetryPolicy] = None,
                 trace: bool = False, recorder=None):
        self.client_id = client_id
        self._host = host
        self._port = port
        self._timeout_s = timeout_s
        self._retry = retry
        # opt-in client-side tracing (telemetry/tracing.py): every request
        # roots a ``client/request`` span (retry/hedge attempts become
        # indexed children) and rides the wire ``trace`` field, so the
        # tier's tree hangs under the CLIENT's view of the request —
        # including the attempts the server never saw finish
        self._trace = bool(trace)
        if self._trace and recorder is None:
            from iwae_replication_project_tpu.telemetry.tracing import (
                get_recorder)
            recorder = get_recorder()
        self._recorder = recorder
        #: wire id -> open auto-minted root span (pipelined/no-retry path)
        self._spans: Dict[int, Any] = {}
        self._next_id = 0
        self._retry_streams = 0
        #: id -> response, for replies read while waiting on another id
        self._responses: Dict[int, Dict[str, Any]] = {}
        #: self-healing accounting (the chaos smoke's evidence)
        self.retry_stats = {"retries": 0, "reconnects": 0, "hedges": 0,
                            "hedge_wins": 0}
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[protocol.LineReader] = None
        self._closed = False
        self._connect()

    # -- connection lifecycle -----------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=self._timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = protocol.LineReader(self._sock)
        # wire ids are per connection: responses buffered from a previous
        # connection can never be matched again
        self._responses = {}

    def _disconnect(self) -> None:
        sock, self._sock, self._reader = self._sock, None, None
        # pipelined-mode root spans die with the connection: their wire ids
        # can never be answered again, so close them errored NOW (a trace
        # that waited for the recorder TTL would read as abandoned, and the
        # map would grow forever across reconnects — ids never repeat)
        spans, self._spans = self._spans, {}
        for sp in spans.values():
            sp.finish(error="connection")
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                # best-effort shutdown of a possibly already-dead socket;
                # close() below is the real teardown (waiver retired: the
                # leak pass proves _disconnect acquisition-free — the spans
                # above were finished before the socket teardown)
                pass
            sock.close()

    def _ensure_connected(self) -> None:
        if self._closed:
            # close() is final: a silent re-dial would turn use-after-close
            # into a leaked socket instead of an error
            raise ConnectionError("client is closed")
        if self._sock is None:
            self._connect()
            self.retry_stats["reconnects"] += 1

    # -- pipelined API ------------------------------------------------------

    def submit(self, op: str, x, k: Optional[int] = None,
               seed: Optional[int] = None,
               model: Optional[str] = None, trace=None,
               target_se: Optional[float] = None,
               ess_floor: Optional[float] = None) -> int:
        """Send one request without waiting; returns its wire id. ``seed``
        (single-row payloads only) pins the row's RNG stream — the
        fleet-composition AND retry-parity hook (see protocol.py);
        ordinary non-retrying callers leave it unset. ``model`` names the
        tenant whose weights must serve the request (a multi-model tier;
        unknown names come back as typed ``bad_request`` responses).
        ``trace`` (a :class:`~...telemetry.tracing.TraceContext`) attaches
        the request under an existing span; with client tracing on
        (``TierClient(trace=True)``) and no explicit context, each submit
        roots its own ``client/request`` span, closed when its response is
        read by :meth:`wait`/:meth:`drain`."""
        if self._sock is None:
            raise ConnectionError("client is disconnected (a prior "
                                  "connection failure); blocking requests "
                                  "under a RetryPolicy reconnect themselves")
        self._next_id += 1
        req_id = self._next_id
        req: Dict[str, Any] = {"id": req_id, "op": op, "x": x}
        if k is not None:
            req["k"] = k
        if target_se is not None:
            req["target_se"] = target_se
        if ess_floor is not None:
            req["ess_floor"] = ess_floor
        if seed is not None:
            req["seed"] = seed
        if model is not None:
            req["model"] = model
        if self.client_id is not None:
            req["client"] = self.client_id
        if trace is not None:
            req["trace"] = trace.wire()
        elif self._trace:
            from iwae_replication_project_tpu.telemetry.tracing import (
                start_span)
            span = start_span("client/request", recorder=self._recorder,
                              attrs={"op": op})
            self._spans[req_id] = span
            req["trace"] = span.ctx().wire()
        self._sock.sendall(protocol.encode_line(req))
        return req_id

    def _finish_span(self, req_id: int, resp: Dict[str, Any]) -> None:
        """Close the auto-minted root span of a pipelined request once its
        response has been read (error-coded when the tier said no)."""
        span = self._spans.pop(req_id, None)
        if span is not None:
            span.finish(error=None if resp.get("ok")
                        else resp.get("error", "internal"))

    def _read_one(self) -> Dict[str, Any]:
        line = self._reader.next_line()
        if line is None:
            raise ConnectionError("tier closed the connection")
        return protocol.decode_line(line)

    def wait(self, req_id: int) -> List[Any]:
        """Block until `req_id`'s response arrives (buffering others);
        returns the per-row result list or raises :class:`TierError`."""
        while req_id not in self._responses:
            resp = self._read_one()
            self._responses[resp.get("id")] = resp
        resp = self._responses.pop(req_id)
        self._finish_span(req_id, resp)
        if not resp.get("ok"):
            raise TierError(resp.get("error", "internal"),
                            resp.get("message", ""),
                            retry_after_s=resp.get("retry_after_s"))
        return resp["result"]

    def drain(self, req_ids: List[int]) -> Dict[int, Dict[str, Any]]:
        """Collect the raw response objects for every id (errors included
        as objects, NOT raised — burst callers triage afterwards)."""
        want = set(req_ids)
        out: Dict[int, Dict[str, Any]] = {}
        for rid in list(want):
            if rid in self._responses:
                out[rid] = self._responses.pop(rid)
                want.discard(rid)
        while want:
            resp = self._read_one()
            rid = resp.get("id")
            if rid in want:
                out[rid] = resp
                want.discard(rid)
            else:
                self._responses[rid] = resp
        for rid, resp in out.items():
            self._finish_span(rid, resp)
        return out

    # -- blocking API -------------------------------------------------------

    def request(self, op: str, x, k: Optional[int] = None,
                seed: Optional[int] = None,
                model: Optional[str] = None,
                target_se: Optional[float] = None,
                ess_floor: Optional[float] = None) -> List[Any]:
        if self._retry is None:
            return self.wait(self.submit(op, x, k=k, seed=seed, model=model,
                                         target_se=target_se,
                                         ess_floor=ess_floor))
        return self._request_retrying(op, x, k, seed, model,
                                      target_se, ess_floor)

    def score(self, x, k: Optional[int] = None,
              seed: Optional[int] = None,
              model: Optional[str] = None) -> List[Any]:
        """Per-row k-sample IWAE log p̂(x) (list of floats)."""
        return self.request("score", x, k=k, seed=seed, model=model)

    def score_adaptive(self, x, k: Optional[int] = None,
                       seed: Optional[int] = None,
                       model: Optional[str] = None, *,
                       target_se: Optional[float] = None,
                       ess_floor: Optional[float] = None) -> List[Any]:
        """Accuracy-targeted scoring: per-row ``[log_px, achieved_se,
        k_used]`` triples. ``k`` is the sample CAP (fleet ``k_max`` when
        unset); at least one of ``target_se`` / ``ess_floor`` must be a
        positive number (the typed-``bad_request`` contract otherwise).
        Retrying/hedging is as safe as for ``score``: results — k_used
        included — are a pure function of (weights, payload, seed, k,
        targets)."""
        return self.request("score_adaptive", x, k=k, seed=seed,
                            model=model, target_se=target_se,
                            ess_floor=ess_floor)

    def encode(self, x, k: Optional[int] = None,
               seed: Optional[int] = None,
               model: Optional[str] = None) -> List[Any]:
        return self.request("encode", x, k=k, seed=seed, model=model)

    def decode(self, h, seed: Optional[int] = None,
               model: Optional[str] = None) -> List[Any]:
        return self.request("decode", h, seed=seed, model=model)

    # -- retry/hedging machinery (blocking path only) -----------------------

    def _request_retrying(self, op: str, x, k: Optional[int],
                          seed: Optional[int],
                          model: Optional[str] = None,
                          target_se: Optional[float] = None,
                          ess_floor: Optional[float] = None) -> List[Any]:
        """The RetryPolicy loop: reconnect + resend across connection
        failures, back off and resend on typed retryable errors, give up
        at max_attempts or the overall deadline — whichever first. Raises
        the LAST failure unchanged (typed TierError, or the connection
        error) so callers keep the real diagnosis."""
        policy = self._retry
        self._retry_streams += 1
        backoff = policy.backoff(self._retry_streams)
        deadline = None if policy.deadline_s is None \
            else time.monotonic() + policy.deadline_s
        root = None
        if self._trace:
            from iwae_replication_project_tpu.telemetry.tracing import (
                start_span)
            root = start_span("client/request", recorder=self._recorder,
                              attrs={"op": op})
        last: Optional[BaseException] = None
        try:
            for attempt in range(1, policy.max_attempts + 1):
                hint = None
                # attempt-indexed child span: a retried request's tree
                # shows every send, including ones the tier never answered
                aspan = root.child(f"client/attempt-{attempt}") \
                    if root is not None else None
                try:
                    self._ensure_connected()
                    rid = self.submit(op, x, k=k, seed=seed, model=model,
                                      trace=(aspan.ctx() if aspan is not None
                                             else None),
                                      target_se=target_se,
                                      ess_floor=ess_floor)
                    out = self._await(rid, op, x, k, seed, model, deadline,
                                      span=aspan, target_se=target_se,
                                      ess_floor=ess_floor)
                    if aspan is not None:
                        aspan.finish()
                    if root is not None:
                        root.finish()
                    return out
                except TierError as e:
                    if aspan is not None:
                        aspan.finish(error=e.code)
                    if not policy.retryable(e.code) or (
                            e.code == "quota_exceeded"
                            and e.retry_after_s is None):
                        # a quota rejection WITHOUT a refill hint is the
                        # cost-above-burst case: no wait can ever admit it —
                        # the request must be split, not re-sent
                        raise
                    last, hint = e, e.retry_after_s
                except (OSError, protocol.ProtocolError) as e:
                    if aspan is not None:
                        aspan.finish(error="connection")
                    if self._closed:
                        raise   # use-after-close is an error, not a retry
                    # dropped (OSError/ConnectionError) or garbled
                    # (ProtocolError) connection: the stream is unusable —
                    # reconnect before the next attempt
                    self._disconnect()
                    if not policy.retry_connection_errors:
                        raise
                    last = e
                if attempt >= policy.max_attempts:
                    break
                sleep_s = backoff.next_delay(hint)
                if deadline is not None and \
                        time.monotonic() + sleep_s > deadline:
                    break
                self.retry_stats["retries"] += 1
                time.sleep(sleep_s)
            raise last
        finally:
            if root is not None:
                # idempotent: a no-op after the success-path finish; on any
                # raise this closes the root errored so the trace finalizes
                root.finish(error="failed")

    def _await(self, rid: int, op: str, x, k, seed, model,
               deadline: Optional[float], span=None,
               target_se: Optional[float] = None,
               ess_floor: Optional[float] = None) -> List[Any]:
        """Wait for `rid`, hedging to a second connection when the policy
        asks for it and the primary is slow. ``span`` is the attempt span
        a hedge records its ``client/hedge`` child under."""
        policy = self._retry
        if policy.hedge_after_s is None:
            return self.wait(rid)
        # phase 1: give the primary hedge_after_s to answer (socket
        # timeout — partial frames stay buffered in the LineReader)
        self._sock.settimeout(policy.hedge_after_s)
        try:
            return self.wait(rid)
        except socket.timeout:  # iwaelint: disable=swallowed-exception -- the timeout IS the hedge trigger: a slow (not dead) primary falls through to the two-connection race below; NOT retired by the leak-pass exemption (socket.timeout is not the OSError teardown shape, and the pending request/span stay live on purpose for the hedge to answer)
            pass
        finally:
            if self._sock is not None:
                self._sock.settimeout(self._timeout_s)
        # phase 2: second connection, same request, SAME seed (bitwise-
        # identical answer); two waiter threads race into one queue
        self.retry_stats["hedges"] += 1
        finished = set()
        primary_broken = False
        hedge = TierClient(self._host, self._port, client_id=self.client_id,
                           timeout_s=self._timeout_s)
        # the hedge span opens only once the dial succeeded: a refused dial
        # raises out of here with no orphaned open span (the attempt span's
        # error closure keeps the trace finalizable)
        hspan = span.child("client/hedge") if span is not None else None
        # everything past the hedge dial runs under the finally that closes
        # it: a submit that dies on a freshly-reset connection must not
        # leak the hedge socket (nor skip the primary cleanup decision)
        try:
            hrid = hedge.submit(op, x, k=k, seed=seed, model=model,
                                trace=(hspan.ctx() if hspan is not None
                                       else None),
                                target_se=target_se, ess_floor=ess_floor)
            results: "_queue.Queue" = _queue.Queue()

            def waiter(tag: str, cli: "TierClient", r: int) -> None:
                try:
                    results.put((tag, None, cli.wait(r)))
                except BaseException as e:
                    results.put((tag, e, None))

            for tag, cli, r in (("primary", self, rid),
                                ("hedge", hedge, hrid)):
                threading.Thread(target=waiter, args=(tag, cli, r),
                                 daemon=True).start()
            tag, err, value = self._race(results, deadline)
            finished.add(tag)
            self._finish_hedge_span(hspan, tag, err)
            primary_broken |= tag == "primary" and \
                isinstance(err, (OSError, protocol.ProtocolError))
            if err is None:
                if tag == "hedge":
                    self.retry_stats["hedge_wins"] += 1
                return value
            # the first finisher failed; the slower leg may still win —
            # wait it out within the deadline, else surface the error
            tag2, err2, value2 = self._race(results, deadline)
            finished.add(tag2)
            self._finish_hedge_span(hspan, tag2, err2)
            primary_broken |= tag2 == "primary" and \
                isinstance(err2, (OSError, protocol.ProtocolError))
            if err2 is None:
                if tag2 == "hedge":
                    self.retry_stats["hedge_wins"] += 1
                return value2
            raise err
        finally:
            # first-wins cancellation: the hedge connection is throwaway
            # (closing it unblocks its waiter; the tier's write to a closed
            # socket is dropped server-side), and the primary is abandoned
            # too when a waiter may still be blocked on it — or when its
            # stream broke. It reconnects lazily on the next request.
            if hspan is not None and "hedge" not in finished:
                # the race ended before the hedge leg reported: close its
                # span so the trace can finalize (the tier-side subtree
                # still lands — the tier answers even a vanished client)
                hspan.finish(error="abandoned")
            hedge.close()
            if "primary" not in finished or primary_broken:
                self._disconnect()

    @staticmethod
    def _finish_hedge_span(hspan, tag: str, err) -> None:
        if hspan is None or tag != "hedge":
            return
        hspan.finish(error=None if err is None else (
            err.code if isinstance(err, TierError) else "connection"))

    @staticmethod
    def _race(results: "_queue.Queue", deadline: Optional[float]):
        timeout = None if deadline is None \
            else max(0.0, deadline - time.monotonic())
        try:
            return results.get(timeout=timeout)
        except _queue.Empty:
            raise TierError(
                "timeout", "request deadline passed while hedging "
                "(neither connection answered)") from None

    # -- control ops --------------------------------------------------------

    def _control(self, op: str, **fields) -> Dict[str, Any]:
        self._ensure_connected()
        self._next_id += 1
        req: Dict[str, Any] = {"id": self._next_id, "op": op}
        req.update({k: v for k, v in fields.items() if v is not None})
        self._sock.sendall(protocol.encode_line(req))
        return self.wait(self._next_id)

    def info(self) -> Dict[str, Any]:
        """The tier's ``info`` control document (ops, row dims, buckets)."""
        return self._control("info")

    def stats(self) -> Dict[str, Any]:
        """The tier's live ``stats`` document (router counters/gauges,
        replica health, per-engine counters)."""
        return self._control("stats")

    def slo(self) -> Dict[str, Any]:
        """The tier's ``slo`` control document: ``{"enabled": bool,
        "slo": {per-(model, op) burn rates}}`` — the autoscaler's wire
        signal (:func:`~..fleet.signals.wire_signals` consumes it)."""
        return self._control("slo")

    def submit_job(self, x, *, job_op: str = "score",
                   k: Optional[int] = None,
                   target_se: Optional[float] = None,
                   ess_floor: Optional[float] = None,
                   seed: Optional[int] = None,
                   model: Optional[str] = None,
                   checkpoint_dir: Optional[str] = None,
                   checkpoint_every: Optional[int] = None,
                   resume: Optional[bool] = None) -> Dict[str, Any]:
        """Admit one bulk offline job (``submit_job`` wire op, jobs.py):
        every row of ``x`` scored through ``job_op`` in the background,
        below interactive traffic. Returns the job's initial status doc
        (``doc["job"]`` is the id for :meth:`job_status`)."""
        return self._control("submit_job", x=x, job_op=job_op, k=k,
                             target_se=target_se, ess_floor=ess_floor,
                             seed=seed, model=model,
                             client=self.client_id,
                             checkpoint_dir=checkpoint_dir,
                             checkpoint_every=checkpoint_every,
                             resume=resume)

    def job_status(self, job: str,
                   results: Optional[bool] = None) -> Dict[str, Any]:
        """One job's typed status doc (``results=True`` includes the
        per-row results collected so far — None for unfinished rows)."""
        return self._control("job_status", job=job, results=results)

    def traces(self, limit: Optional[int] = None,
               trace_id: Optional[str] = None,
               fmt: Optional[str] = None) -> Dict[str, Any]:
        """The tier's flight-recorder dump (``traces`` control op): raw
        trace documents + recorder stats, or — ``fmt="chrome"`` — one
        Chrome trace-event JSON object (what ``iwae-trace`` writes)."""
        return self._control("traces", limit=limit, trace_id=trace_id,
                             format=fmt)

    def close(self) -> None:
        self._closed = True
        self._disconnect()

    def __enter__(self) -> "TierClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
