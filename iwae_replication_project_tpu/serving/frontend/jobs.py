"""The bulk offline lane: dataset-sized scoring jobs below interactive
traffic.

A *job* is one wire document (``{"op": "submit_job", ...}``) naming an
entire dataset to score — thousands of rows through ``score`` or
``score_adaptive`` — that would be abusive as interactive traffic: the
tier's admission ceiling exists to bound interactive latency, and a client
that pumped 50k rows through it would starve every latency-sensitive
request behind its queue. The job lane inverts the priority:

* **background admission** — the manager's pump thread submits job rows
  through the SAME router as interactive traffic, but only while the
  tier-wide outstanding count sits below a configured ``headroom`` (a
  fraction of ``max_outstanding``). Interactive requests go straight to
  the router and push the count up; the pump then stops submitting until
  the burst drains. Bulk work harvests idle fleet capacity and yields it
  back within one small chunk (the smoke pins the interactive p50 bound).
* **existing quota machinery** — every submitted chunk is admitted through
  the tier's per-(client, model) token buckets first, so a tenant's bulk
  job spends the same budget as its interactive traffic would
  (``QuotaExceeded`` pauses the pump for the refill interval; it never
  fails the job).
* **deterministic rows** — row ``i`` is submitted with seed
  ``(job_seed + i) mod 2**31``, so each row's result is a pure function of
  (weights, row, job_seed, i, k, targets) — bitwise independent of pump
  pacing, chunk boundaries, routing, and of how often the job was
  interrupted (the serving determinism contract, extended to jobs).
* **checkpoint + resume** — every ``checkpoint_every`` completed-prefix
  rows the pump writes ``<dir>/<n>/progress.json`` (the completed prefix
  of results) and seals it with the PR-10 integrity manifest machinery
  (:func:`~...utils.checkpoint.write_manifest`); resubmitting the same job
  doc with ``"resume": true`` verifies the newest intact step
  (:func:`verify_checkpoint` — a truncated/corrupt step falls back to the
  previous one), restores its prefix WITHOUT resubmitting those rows, and
  continues; per-row seed determinism makes the resumed tail bitwise equal
  the uninterrupted run. A resume against a checkpoint written by a
  *different* job doc (other op/k/targets/seed/payload) is a typed
  ``bad_request`` — never a silent splice of two datasets.

``{"op": "job_status", "job": "<id>"}`` is the typed status op: state,
row counts, checkpoint progress, the first row error if any, and — with
``"results": true`` — the per-row results collected so far.

This module is transport-side plumbing like server.py: no jax/numpy at
import time (the manifest helpers are imported lazily at checkpoint time),
fully exercisable with fake engines over localhost sockets.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from iwae_replication_project_tpu.serving.buckets import (
    validate_adaptive_target,
    validate_k,
)
from iwae_replication_project_tpu.serving.frontend.quotas import QuotaExceeded

__all__ = ["BulkJobManager", "BulkJob"]


def _rows_digest(rows: List[Any]) -> str:
    """Identity of a job's payload for the resume-mismatch guard (the
    checkpoint stores this digest, never the rows themselves)."""
    h = hashlib.sha256()
    h.update(json.dumps(rows, separators=(",", ":")).encode("utf-8"))
    return h.hexdigest()


class BulkJob:
    """One admitted bulk job's mutable state (guarded by the manager's
    lock). ``identity`` is the canonical doc the resume guard compares —
    everything that determines the results bitwise."""

    __slots__ = ("job_id", "op", "rows", "k", "target_se", "ess_floor",
                 "seed", "model", "client", "ckpt_dir", "ckpt_every",
                 "state", "results", "next_row", "completed", "prefix",
                 "checkpointed", "error", "t_submit", "t_done")

    def __init__(self, job_id: str, *, op: str, rows: List[Any],
                 k: Optional[int], target_se: Optional[float],
                 ess_floor: Optional[float], seed: int,
                 model: Optional[str], client: Optional[str],
                 ckpt_dir: Optional[str], ckpt_every: int):
        self.job_id = job_id
        self.op = op
        self.rows = rows
        self.k = k
        self.target_se = target_se
        self.ess_floor = ess_floor
        self.seed = int(seed)
        self.model = model
        self.client = client
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = int(ckpt_every)
        self.state = "running"
        self.results: List[Any] = [None] * len(rows)
        self.next_row = 0          # first row not yet submitted
        self.completed = 0         # rows with a result, any order
        self.prefix = 0            # longest completed prefix (checkpointable)
        self.checkpointed = 0      # prefix length of the newest checkpoint
        self.error: Optional[str] = None
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None

    def identity(self) -> Dict[str, Any]:
        return {"op": self.op, "k": self.k, "target_se": self.target_se,
                "ess_floor": self.ess_floor, "seed": self.seed,
                "model": self.model, "n_rows": len(self.rows),
                "rows_sha256": _rows_digest(self.rows)}

    def row_seed(self, i: int) -> int:
        # the job determinism contract: row i's RNG stream is a pure
        # function of (job_seed, i) — resume, pacing, and routing can
        # never change it
        return (self.seed + i) % (2 ** 31)

    def status_doc(self, include_results: bool = False) -> Dict[str, Any]:
        doc = {"job": self.job_id, "state": self.state, "op": self.op,
               "rows": len(self.rows), "submitted": self.next_row,
               "completed": self.completed, "prefix": self.prefix,
               "checkpointed": self.checkpointed, "error": self.error}
        if include_results:
            doc["results"] = list(self.results)
        return doc


class BulkJobManager:
    """The tier's background lane: admits job docs, pumps their rows
    through the replica router while the fleet has idle headroom, and
    checkpoints completed prefixes via the PR-10 manifest machinery.

    ``router`` is the tier's :class:`~.router.ReplicaRouter`; ``admit`` /
    ``refund`` are the tier's quota hooks (``(client, cost, model)``), so
    bulk rows meter through the same per-(client, model) buckets as
    interactive traffic. ``headroom`` caps the tier-wide outstanding count
    the pump will fill up to (bulk never submits while
    ``router.outstanding >= headroom`` — that capacity belongs to latency
    traffic); ``chunk`` bounds one pump tick's submission burst, which is
    also the yield granularity to an arriving interactive burst.
    """

    #: how long the pump sleeps when there is no headroom / no work
    POLL_S = 0.01

    def __init__(self, router, *, admit: Callable[..., None],
                 refund: Callable[..., None], headroom: int,
                 chunk: int = 32, registry=None, clock=time.monotonic):
        self._router = router
        self._admit = admit
        self._refund = refund
        self.headroom = max(1, int(headroom))
        self.chunk = max(1, int(chunk))
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._jobs: "Dict[str, BulkJob]" = {}
        self._order: List[str] = []    # FIFO among running jobs
        self._next_id = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: pump pause until this clock time (quota refill back-off)
        self._pause_until = 0.0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._pump_loop,
                                        name="iwae-tier-jobs", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop the pump (already-submitted rows complete during the
        router's drain; unsubmitted rows simply stay unsubmitted — that is
        the interruption the checkpoint/resume contract exists for)."""
        self._stop.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join()

    # -- wire ops -----------------------------------------------------------

    def submit(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Admit one ``submit_job`` doc; returns the initial status doc.
        Malformed docs raise ``ValueError`` (the server maps it to a typed
        ``bad_request`` response)."""
        op = obj.get("job_op", "score")
        if not isinstance(op, str) or not self._router.serves_op(op):
            raise ValueError(
                f"'job_op' must name an op this fleet serves, got {op!r}")
        rows = obj.get("x")
        if not isinstance(rows, (list, tuple)) or len(rows) == 0 or \
                not isinstance(rows[0], (list, tuple)):
            raise ValueError(
                "'x' must be a non-empty list of rows for a bulk job")
        rows = [list(r) for r in rows]
        k = obj.get("k")
        if k is not None:
            k = validate_k(k, 2 ** 31 - 1)
        target_se = obj.get("target_se")
        ess_floor = obj.get("ess_floor")
        if target_se is not None or ess_floor is not None:
            # the ONE shared validator, at the job boundary too (the
            # router re-validates per row with the fleet's real k_max)
            validate_adaptive_target(target_se, ess_floor,
                                     k if k is not None else 2 ** 31 - 1,
                                     2 ** 31 - 1)
        seed = obj.get("seed", 0)
        if not isinstance(seed, int) or isinstance(seed, bool) or \
                not 0 <= seed < 2 ** 31:
            raise ValueError(
                f"job 'seed' must be an integer in [0, 2**31), got {seed!r}")
        model = self._router.resolve_model(obj.get("model"))
        client = obj.get("client")
        if client is not None and not isinstance(client, str):
            raise ValueError(f"'client' must be a string, got "
                             f"{type(client).__name__}")
        ckpt_dir = obj.get("checkpoint_dir")
        if ckpt_dir is not None and not isinstance(ckpt_dir, str):
            raise ValueError("'checkpoint_dir' must be a path string")
        ckpt_every = obj.get("checkpoint_every", 256)
        if not isinstance(ckpt_every, int) or isinstance(ckpt_every, bool) \
                or ckpt_every < 1:
            raise ValueError(
                f"'checkpoint_every' must be a positive integer, "
                f"got {ckpt_every!r}")
        with self._lock:
            self._next_id += 1
            job_id = f"job-{self._next_id}"
        job = BulkJob(job_id, op=op, rows=rows, k=k, target_se=target_se,
                      ess_floor=ess_floor, seed=seed, model=model,
                      client=client, ckpt_dir=ckpt_dir,
                      ckpt_every=ckpt_every)
        if obj.get("resume"):
            if ckpt_dir is None:
                raise ValueError(
                    "'resume' needs a 'checkpoint_dir' to resume from")
            self._restore(job)
        with self._lock:
            self._jobs[job_id] = job
            self._order.append(job_id)
        self._count("jobs/submitted")
        self._wake.set()
        return job.status_doc()

    def status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        job_id = obj.get("job")
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise ValueError(f"unknown job {job_id!r} (this tier knows "
                             f"{sorted(self._jobs)})")
        with self._lock:
            return job.status_doc(include_results=bool(obj.get("results")))

    def jobs_doc(self) -> List[Dict[str, Any]]:
        """Every known job's status (the stats document's jobs section)."""
        with self._lock:
            return [self._jobs[j].status_doc() for j in self._order]

    # -- the pump -----------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(n)

    def _runnable(self) -> Optional[BulkJob]:
        with self._lock:
            for job_id in self._order:
                job = self._jobs[job_id]
                if job.state == "running" and job.next_row < len(job.rows):
                    return job
        return None

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            self._checkpoint_due()
            job = self._runnable()
            if job is None:
                self._wake.wait(timeout=0.05)
                with self._lock:
                    self._wake.clear()
                continue
            with self._lock:
                pause_until = self._pause_until
            if self._clock() < pause_until:
                time.sleep(self.POLL_S)
                continue
            # the yield gate: bulk only fills capacity below `headroom`;
            # an interactive burst raises the outstanding count and the
            # pump stops submitting until it drains back down
            free = self.headroom - self._router.outstanding
            if free <= 0:
                time.sleep(self.POLL_S)
                continue
            self._submit_chunk(job, min(free, self.chunk))

    def _submit_chunk(self, job: BulkJob, n: int) -> None:
        with self._lock:
            start = job.next_row
            n = min(n, len(job.rows) - start)
            if n <= 0 or job.state != "running":
                return
        try:
            self._admit(job.client, n, model=job.model)
        except QuotaExceeded as e:
            # the job lane never fails on quota: it waits out the refill
            # (the hint is exact — quotas.py computes it) and tries again
            backoff = self._clock() + \
                max(self.POLL_S, float(getattr(e, "retry_after_s", None)
                                       or 0.05))
            with self._lock:
                self._pause_until = backoff
            return
        submitted = 0
        kw: Dict[str, Any] = {}
        if job.target_se is not None:
            kw["target_se"] = job.target_se
        if job.ess_floor is not None:
            kw["ess_floor"] = job.ess_floor
        try:
            for i in range(start, start + n):
                fut = self._router.submit(
                    job.op, job.rows[i], k=job.k, seed=job.row_seed(i),
                    model=job.model, **kw)
                with self._lock:
                    job.next_row = i + 1
                submitted += 1
                fut.add_done_callback(
                    lambda f, j=job, idx=i: self._row_done(j, idx, f))
        except Exception as e:
            # a shed/ceiling rejection mid-chunk: refund the unsubmitted
            # remainder (quota meters served work) and back off — the rows
            # stay queued in the job, not lost
            if submitted < n:
                self._refund(job.client, n - submitted, model=job.model)
            backoff = self._clock() + \
                max(self.POLL_S, float(getattr(e, "retry_after_s", None)
                                       or 0.05))
            with self._lock:
                self._pause_until = backoff

    def _row_done(self, job: BulkJob, i: int, fut) -> None:
        # the callback fires after resolution, so exception()/result() are
        # non-blocking here — fetched BEFORE the lock regardless, so the
        # critical section provably never waits on a future
        exc = fut.exception()
        r = None if exc is not None else fut.result()
        with self._lock:
            if exc is not None:
                if job.state == "running":
                    job.state = "failed"
                    job.error = f"row {i}: {type(exc).__name__}: {exc}"
                    job.t_done = self._clock()
                return
            job.results[i] = r.tolist() if hasattr(r, "tolist") else r
            job.completed += 1
            while job.prefix < len(job.rows) and \
                    job.results[job.prefix] is not None:
                job.prefix += 1
            if job.state == "running" and job.completed == len(job.rows):
                job.state = "done"
                job.t_done = self._clock()
        self._count("jobs/rows_completed")
        self._wake.set()

    # -- checkpoint / resume (PR-10 manifest machinery) ---------------------

    def _checkpoint_due(self) -> None:
        """Write checkpoints for jobs whose completed prefix advanced past
        the cadence (or just finished). Runs on the pump thread: file IO
        and hashing never block a router completion callback."""
        with self._lock:
            due = [j for j in self._jobs.values()
                   if j.ckpt_dir is not None and j.prefix > j.checkpointed
                   and (j.prefix - j.checkpointed >= j.ckpt_every
                        or j.state == "done")]
        for job in due:
            try:
                self._write_checkpoint(job)
            except OSError as e:
                with self._lock:
                    job.error = f"checkpoint write failed: {e}"

    def _write_checkpoint(self, job: BulkJob) -> None:
        # the manifest helpers live in utils/checkpoint.py, which imports
        # jax at module scope — deferred so the frontend stays jax-free at
        # import time (the tier's fake-engine tests never checkpoint)
        from iwae_replication_project_tpu.utils.checkpoint import (
            write_manifest)

        with self._lock:
            prefix = job.prefix
            payload = {"job": job.identity(), "done": prefix,
                       "results": job.results[:prefix]}
        step_dir = os.path.join(os.path.abspath(job.ckpt_dir), str(prefix))
        os.makedirs(step_dir, exist_ok=True)
        tmp = os.path.join(step_dir, "progress.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"))
            f.write("\n")
        os.replace(tmp, os.path.join(step_dir, "progress.json"))
        # seal the step with the same (size, sha256) manifest training
        # checkpoints carry; resume verifies before trusting it
        write_manifest(job.ckpt_dir, prefix)
        with self._lock:
            job.checkpointed = prefix
            stale = [s for s in self._step_list(job.ckpt_dir)
                     if s != prefix]
        # retain only the newest sealed step plus its predecessor (the
        # fallback verify_checkpoint walks to when the newest is torn)
        for s in sorted(stale, reverse=True)[1:]:
            self._drop_step(job.ckpt_dir, s)
        self._count("jobs/checkpoints")

    @staticmethod
    def _step_list(ckpt_dir: str) -> List[int]:
        root = os.path.abspath(ckpt_dir)
        if not os.path.isdir(root):
            return []
        return sorted(int(d) for d in os.listdir(root)
                      if d.isdigit() and
                      os.path.isfile(os.path.join(root, d, "progress.json")))

    @staticmethod
    def _drop_step(ckpt_dir: str, step: int) -> None:
        import shutil
        root = os.path.abspath(ckpt_dir)
        shutil.rmtree(os.path.join(root, str(step)), ignore_errors=True)
        try:
            os.remove(os.path.join(root, "manifests", f"{step}.json"))
        except OSError:
            pass

    def _restore(self, job: BulkJob) -> None:
        """Load the newest intact checkpoint into `job` (prefix results +
        resume point). Torn steps fall back to the previous sealed one; a
        checkpoint written by a different job doc is a ValueError (typed
        ``bad_request`` at the wire)."""
        from iwae_replication_project_tpu.utils.checkpoint import (
            verify_checkpoint)

        for step in sorted(self._step_list(job.ckpt_dir), reverse=True):
            problem = verify_checkpoint(job.ckpt_dir, step)
            if problem is not None:
                continue   # torn/corrupt step: fall back to the previous
            path = os.path.join(os.path.abspath(job.ckpt_dir), str(step),
                                "progress.json")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    payload = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if payload.get("job") != job.identity():
                raise ValueError(
                    f"checkpoint at {job.ckpt_dir!r} was written by a "
                    f"different job (op/k/targets/seed/payload differ); "
                    f"refusing to resume")
            done = int(payload.get("done", 0))
            results = payload.get("results", [])
            if done != len(results) or done > len(job.rows):
                continue   # internally inconsistent: fall back
            for i in range(done):
                job.results[i] = results[i]
            job.next_row = done
            job.completed = done
            job.prefix = done
            job.checkpointed = done
            if done == len(job.rows):
                job.state = "done"
                job.t_done = self._clock()
            self._count("jobs/resumed")
            return
        # nothing intact to resume from: a fresh start IS the contract
        # (first run of a job that will checkpoint into this directory)
