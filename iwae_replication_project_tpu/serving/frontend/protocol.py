"""The serving tier's wire format: JSON lines over TCP, typed errors.

One request per ``\\n``-terminated UTF-8 line, one response line per
request; responses may arrive **out of order** (the tier completes them as
replicas finish), so callers match on the echoed ``id``.

Request object::

    {"op": "score" | "encode" | "decode" | "score_adaptive",  # required
     "x": [..row..] | [[..rows..]],          # required payload
     "k": 50,                                # optional (score/encode only;
                                             #  the k CAP for adaptive ops)
     "target_se": 0.1,                       # adaptive ops only: stop when
     "ess_floor": 64,                        #  SE <= target / ESS >= floor
     "id": <any JSON value>,                 # optional, echoed verbatim
     "client": "tenant-a",                   # optional quota principal
     "model": "table1-iwae-1l-k50",          # optional tenant model
     "trace": "<tid>[/<span>]",              # optional trace context
     "seed": 17}                             # optional, single-row only

``target_se`` / ``ess_floor`` are the adaptive accuracy contract
(``score_adaptive``): at least one must be set (a finite positive number),
``k`` becomes the sample CAP (defaulting to the fleet's ``k_max``), and
each row's result is the triple ``[log_px, achieved_se, k_used]``. The
shared validator (buckets.validate_adaptive_target) runs at the wire, the
router, and the engine — a malformed target is a typed ``bad_request``
*response* at every depth and the connection survives. Targets on a
non-adaptive op are likewise ``bad_request``.

``model`` names WHICH zoo model's weights must serve the request on a
multi-model tier (``iwae-serve --models``): the router classifies it onto
replicas holding that model, quotas meter per (client, model), and a model
the fleet does not declare is a typed ``bad_request`` — never a silent
answer from the wrong weights. Absent, the tier's ``default_model``
serves (the ``info`` doc names it, plus a per-model capability table under
``models``).

``trace`` is the request's distributed-tracing context
(telemetry/tracing.py): ``"<trace-id>"`` or
``"<trace-id>/<parent-span-id>"``, each part 1-64 chars of
``[A-Za-z0-9_.:-]``.  Absent, a tracing-enabled front end mints a fresh
trace; present, the request's spans join the caller's tree (the
fleet-of-fleets hook — a parent tier's RemoteEngine hop span parents the
child tier's request span).  A malformed or oversized ``trace`` is a typed
``bad_request`` *response* and the connection survives, like every other
field.  Tracing is host-side metadata only: it never reaches seeds,
payloads, or program shapes, so results are bitwise independent of it.

``seed`` is the fleet-composition hook: serving results are a pure function
of (weights, payload, seed, k), so a PARENT router that mints its own seeds
— :class:`~.remote.RemoteEngine` proxying this tier as one replica of a
bigger fleet — gets results that are bitwise independent of which process
served the request. It applies to single-row payloads only (one seed names
one row's RNG stream; a multi-row request with ``seed`` is a
``bad_request``) and ordinary clients never need it: the tier seeds
requests itself, in admission order.

``{"op": "info"}`` is answered directly by the front end (ops, per-op row
dims, default k, bucket ladder, replica count) — clients use it to size
payloads — and ``{"op": "stats"}`` likewise returns the live router
counters/gauges plus each replica engine's counter snapshot (what the
bench's zero-recompile proof and the smoke's failure accounting read over
the wire). ``{"op": "traces"}`` dumps the tier's flight recorder
(telemetry/tracing.py): optional ``limit`` (most recent N), ``trace_id``
(one trace), and ``format`` (``"raw"`` trace documents, the default, or
``"chrome"`` for a Chrome trace-event JSON object — what the
``iwae-trace`` CLI fetches). Control ops are never routed, quota'd, or
counted against the ceiling.

Response object::

    {"id": ..., "ok": true,  "result": [..per-row results..]}
    {"id": ..., "ok": false, "error": "<code>", "message": "...",
     "retry_after_s": 0.25}                    # optional, machine-readable

``retry_after_s`` is the back-off hint for retryable rejections:
``quota_exceeded`` carries the client's exact token-refill wait (computed
by quotas.py), ``overloaded`` the tier's configured shed hint — so a
:class:`~.retry.RetryPolicy` sleeps precisely instead of guessing. Absent
on errors where waiting cannot help (``bad_request``, a cost above the
quota burst).

Error codes (``ERROR_CODES``) are the tier's failure model, one code per
admission/serving outcome — a rejected request is a typed *response*, never
a dropped connection:

* ``bad_request``   — malformed JSON, unknown op, wrong payload shape;
* ``overloaded``    — global ceiling hit or every replica's queue shed
  (:class:`~..batcher.EngineOverloaded` /
  :class:`~.router.TierOverloaded`): back off and retry;
* ``quota_exceeded``— the client's token bucket ran dry
  (:class:`~.quotas.QuotaExceeded`): retry after the refill interval;
* ``timeout``       — the request expired in a replica queue
  (:class:`~..batcher.RequestTimeout`);
* ``unavailable``   — no healthy replica, or the tier is draining
  (:class:`~.router.ReplicaUnavailable`);
* ``internal``      — anything else (the replica raised; the request was
  retried on other replicas first — see router.py).

This module is pure data plumbing: no sockets, no engines, no numpy — so
the protocol is testable byte-for-byte and both the server and the client
share one implementation of framing and error taxonomy.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

#: the typed error taxonomy (see module docstring)
ERROR_CODES = ("bad_request", "overloaded", "quota_exceeded", "timeout",
               "unavailable", "internal")

#: protocol ops the front end answers itself (never routed to a replica):
#: capability/info, counters, retained traces, and the SLO burn-rate
#: document (``slo`` — the scaling signal a fleet-of-fleets parent reads
#: over the wire instead of scraping Prometheus text)
CONTROL_OPS = ("info", "stats", "traces", "slo")

#: the bulk offline lane's ops (jobs.py), answered synchronously like
#: control ops — the job's ROWS are pumped through the router in the
#: background, below interactive traffic:
#:
#: ``{"op": "submit_job", "job_op": "score_adaptive", "x": [[..rows..]],
#:    "k": 5000, "target_se": 0.1, "seed": 7, "client": "tenant-a",
#:    "checkpoint_dir": "/path", "checkpoint_every": 256,
#:    "resume": false}``  ->  the job's initial status document
#: ``{"op": "job_status", "job": "job-1", "results": true}``  ->  state,
#:    row counts, checkpoint progress, optionally per-row results
#:
#: Row ``i`` runs under seed ``(seed + i) mod 2**31``, so job results are
#: bitwise independent of pump pacing and interruption; checkpoints are
#: sealed with the training-checkpoint manifest machinery and ``resume``
#: restores the newest intact prefix without resubmitting it. Malformed
#: job docs are typed ``bad_request`` responses; job ops are never quota'd
#: themselves (each submitted row chunk is, through the same per-(client,
#: model) buckets as interactive traffic).
JOB_OPS = ("submit_job", "job_status")

#: max accepted request line (bytes) — a framing bound, not a row bound:
#: 64 MiB comfortably fits a max_batch x 784-float payload and stops a
#: malformed client from ballooning server memory with one endless line
MAX_LINE_BYTES = 64 * 1024 * 1024


class ProtocolError(ValueError):
    """Malformed frame or request object (maps to ``bad_request``)."""


def encode_line(obj: Dict[str, Any]) -> bytes:
    """One protocol object as a framed wire line."""
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode("utf-8")


def decode_line(line: bytes) -> Dict[str, Any]:
    """Parse one framed line into a protocol object (dict)."""
    try:
        obj = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"malformed JSON line: {e}") from None
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"protocol objects are JSON objects, got {type(obj).__name__}")
    return obj


def ok_response(req_id: Any, result) -> Dict[str, Any]:
    return {"id": req_id, "ok": True, "result": result}


def error_response(req_id: Any, code: str, message: str,
                   retry_after_s: Optional[float] = None) -> Dict[str, Any]:
    if code not in ERROR_CODES:
        code = "internal"
    resp = {"id": req_id, "ok": False, "error": code, "message": message}
    if retry_after_s is not None:
        # machine-readable back-off hint (module docstring): only ever a
        # non-negative float, so clients can trust it as a sleep argument
        resp["retry_after_s"] = max(0.0, float(retry_after_s))
    return resp


def error_code_for(exc: BaseException) -> str:
    """Map an exception from admission/routing/serving onto the typed code
    the client sees. Import-local to keep this module dependency-light."""
    from iwae_replication_project_tpu.serving.batcher import (
        EngineOverloaded, RequestTimeout)
    from iwae_replication_project_tpu.serving.frontend.quotas import (
        QuotaExceeded)
    from iwae_replication_project_tpu.serving.frontend.router import (
        ReplicaUnavailable, TierOverloaded)

    if isinstance(exc, QuotaExceeded):
        return "quota_exceeded"
    if isinstance(exc, (TierOverloaded, EngineOverloaded)):
        return "overloaded"
    if isinstance(exc, RequestTimeout):
        return "timeout"
    if isinstance(exc, ReplicaUnavailable):
        return "unavailable"
    if isinstance(exc, (ProtocolError, ValueError, KeyError, TypeError)):
        return "bad_request"
    return "internal"


class LineReader:
    """Buffered ``\\n``-framed reader over a socket-like object.

    ``next_line()`` returns one complete line (without the terminator) or
    None on clean EOF; a line exceeding MAX_LINE_BYTES or a mid-line EOF
    raises :class:`ProtocolError`.
    """

    def __init__(self, sock, max_line_bytes: int = MAX_LINE_BYTES):
        self._sock = sock
        self._buf = bytearray()
        self._max = max_line_bytes

    def next_line(self) -> Optional[bytes]:
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = bytes(self._buf[:nl])
                del self._buf[:nl + 1]
                return line
            if len(self._buf) > self._max:
                raise ProtocolError(
                    f"request line exceeds {self._max} bytes")
            chunk = self._sock.recv(65536)
            if not chunk:
                if self._buf:
                    raise ProtocolError("connection closed mid-line")
                return None
            self._buf.extend(chunk)
