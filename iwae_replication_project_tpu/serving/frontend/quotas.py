"""Per-(client, model) admission quotas: the token-bucket state machine.

Each (client id, model) principal — the request's ``client`` field (absent
= the shared ``"anonymous"`` principal) crossed with its ``model`` field
(absent = the unlabeled default) — owns one token bucket refilled
continuously at ``rate`` tokens/sec up to ``burst`` capacity; admitting a
request costs one token per payload row. The model axis makes quotas
multi-tenant: one client's traffic against model A cannot exhaust its —
or anyone's — budget for model B, so a zoo-serving tier degrades per
(tenant, model) rather than globally. A dry bucket rejects with
:class:`QuotaExceeded` — which the front end turns into a typed
``quota_exceeded`` response, NOT a dropped connection — and rejection
never consumes tokens, so a throttled client recovers after exactly
``cost / rate`` seconds of restraint.

The quota layer sits ABOVE the router on purpose: client identity is an
admission-time concern only. Once admitted, a request carries no client
field anywhere near the engines, so per-client state can never leak into an
AOT program signature (pinned by tests/test_frontend.py's multi-client
zero-recompile test).

Pure data structure + one lock; the clock is injectable so every policy
transition (refill, burst clamp, reject) is unit-testable with a fake
clock, exactly like serving/batcher.py's MicroBatcher.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

#: the principal charged when a request carries no ``client`` field
DEFAULT_CLIENT = "anonymous"


class QuotaExceeded(RuntimeError):
    """The client's token bucket cannot cover the request; retry later.

    ``retry_after_s`` is the exact wait until the bucket covers the cost
    (None when no wait can help — a cost above burst capacity). The front
    end surfaces it as the response's machine-readable ``retry_after_s``
    field and :class:`~.retry.RetryPolicy` honors it as a back-off floor.
    """

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class QuotaPolicy:
    """Token-bucket parameters shared by every client principal.

    ``rate`` tokens/sec refill, ``burst`` bucket capacity (also the largest
    single admissible request, in rows). A new client starts with a full
    bucket — the first burst is free, sustained traffic pays ``rate``.
    """

    rate: float
    burst: float

    def __post_init__(self):
        if self.rate <= 0 or self.burst <= 0:
            raise ValueError(
                f"quota rate and burst must be > 0, got {self}")


class ClientQuotas:
    """Thread-safe per-client token buckets under one policy.

    ``policy=None`` disables quotas entirely (every admit succeeds and no
    state is kept) — the default, so the tier without quota flags behaves
    exactly like the bare engine stack.
    """

    def __init__(self, policy: Optional[QuotaPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._policy = policy
        self._clock = clock
        self._lock = threading.Lock()
        #: (client, model) -> [tokens, last_refill_time]; guarded by _lock
        self._buckets: Dict[tuple, List[float]] = {}

    @property
    def enabled(self) -> bool:
        return self._policy is not None

    @staticmethod
    def _principal(client: Optional[str],
                   model: Optional[str]) -> tuple:
        """The bucket key: (client, model) — model=None is the unlabeled
        default lane, distinct from every named model's lane."""
        return (client or DEFAULT_CLIENT, model)

    def _refilled(self, principal: tuple, now: float) -> List[float]:
        """The principal's bucket, refilled to `now` (caller holds _lock)."""
        p = self._policy
        b = self._buckets.get(principal)
        if b is None:
            b = self._buckets.setdefault(principal, [p.burst, now])
        else:
            b[0] = min(p.burst, b[0] + (now - b[1]) * p.rate)
            b[1] = now
        return b

    def admit(self, client: Optional[str], cost: float,
              model: Optional[str] = None) -> None:
        """Charge `cost` tokens to the (client, model) principal or raise
        :class:`QuotaExceeded`.

        A rejected request consumes nothing. A cost above ``burst`` can
        never be admitted and says so explicitly — the client must split
        the request rather than retry forever.
        """
        if self._policy is None:
            return
        principal = self._principal(client, model)
        if cost > self._policy.burst:
            raise QuotaExceeded(
                f"request cost {cost:g} rows exceeds the per-client burst "
                f"capacity {self._policy.burst:g} — split the request")
        with self._lock:
            b = self._refilled(principal, self._clock())
            if b[0] < cost:
                wait = (cost - b[0]) / self._policy.rate
                lane = f" (model {model!r})" if model is not None else ""
                raise QuotaExceeded(
                    f"client {principal[0]!r}{lane} quota exhausted "
                    f"({b[0]:.2f}/{self._policy.burst:g} tokens, cost "
                    f"{cost:g}); retry in ~{wait:.2f}s",
                    retry_after_s=wait)
            b[0] -= cost

    def refund(self, client: Optional[str], cost: float,
               model: Optional[str] = None) -> None:
        """Return `cost` tokens to the (client, model) principal (clamped
        at burst): the undo for an :meth:`admit` whose request the tier
        then failed to serve — a typed routing rejection (ceiling,
        fleet-wide shed, draining) must not burn the client's budget, or
        sustained overload would stack ``quota_exceeded`` on top of
        ``overloaded`` and break the documented cost/rate recovery
        accounting."""
        if self._policy is None:
            return
        with self._lock:
            b = self._refilled(self._principal(client, model), self._clock())
            b[0] = min(self._policy.burst, b[0] + cost)

    def tokens(self, client: Optional[str],
               model: Optional[str] = None) -> Optional[float]:
        """Current refilled token balance of one (client, model) principal
        (None when quotas are off) — introspection for tests and the
        tier's snapshot."""
        if self._policy is None:
            return None
        with self._lock:
            return self._refilled(self._principal(client, model),
                                  self._clock())[0]

    def clients(self) -> List[str]:
        """Distinct client ids with live buckets (any model lane)."""
        with self._lock:
            return sorted({c for c, _m in self._buckets})
