"""``RemoteEngine``: a serving tier as one replica of a bigger fleet.

The proxy presents the engine surface the replica router dispatches over —
``submit(op, row, k=, seed=)`` returning a Future, ``stop()``,
``row_dims``, ``k`` — backed by ONE JSON-lines TCP connection to a running
:class:`~.server.ServingTier`. A parent :class:`~.router.ReplicaRouter`
over N RemoteEngines therefore composes fleets out of *processes* (each
child tier owns its own device, CPU pin, and XLA runtime — the
``bench.py --serving`` ``replica_scaling`` sweep builds exactly this), and
recursively out of fleets: protocol.py's explicit ``seed`` field exists so
the parent's admission-order seeds ride through to the leaf engines and
results stay bitwise independent of which process served each request.

Failure semantics map back onto the engine exception taxonomy the router
already speaks:

* a typed ``overloaded`` response completes the future with
  :class:`~..batcher.EngineOverloaded` (the router tries peers without
  declaring the replica dead — an async shed means *full*, not *failed*);
* ``timeout`` becomes :class:`~..batcher.RequestTimeout` (per-request
  outcome, no reroute);
* a lost connection fails every outstanding future with
  :class:`~.router.ReplicaUnavailable` and poisons the proxy — subsequent
  submits raise synchronously, so the parent marks the replica unhealthy.

**Reconnection** is opt-in via ``retry=RetryPolicy(...)``: a poisoned
proxy re-dials the child tier on the next ``submit`` (the parent's warm
probes are exactly that — one real request through the replica), rate-
limited by the policy's decorrelated backoff so a down child is not
hammered. Without a policy the poison is permanent — the pre-retry
semantics, pinned by tests/test_frontend.py — and either way the futures
that were in flight when the connection died stay failed (typed): the
parent reroutes or the end client retries; the proxy never resends them
itself. ``close()`` is final: no policy reconnects a proxy its owner shut
down.

Ops and payload dims are validated locally against the child tier's
``info`` document (fetched at connect time), so malformed requests raise
``ValueError`` synchronously like the in-process engine instead of
surfacing as a ``bad_request`` future failure that would smear the replica.

One lock guards the socket write side + the pending-future map; the reader
thread completes futures strictly outside it (completion callbacks — the
parent router's — re-enter :meth:`submit`). Reader threads are generation-
tagged: a thread whose socket belongs to a superseded connection can
neither poison the proxy nor complete a live future.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    RequestTimeout,
    complete_future as _complete,
)
from iwae_replication_project_tpu.serving.buckets import validate_model
from iwae_replication_project_tpu.serving.faults import (
    SITE_REMOTE_SEND,
    fault_point,
)
from iwae_replication_project_tpu.serving.frontend import protocol
from iwae_replication_project_tpu.serving.frontend.retry import RetryPolicy
from iwae_replication_project_tpu.serving.frontend.router import (
    ReplicaUnavailable,
)

__all__ = ["RemoteEngine"]

#: typed response code -> the engine exception the router dispatches on
_CODE_EXC = {
    "overloaded": EngineOverloaded,
    "timeout": RequestTimeout,
    "unavailable": ReplicaUnavailable,
    "bad_request": ValueError,
}


class RemoteEngine:
    """The engine surface over one TCP connection to a serving tier."""

    #: capability bit for a parent router: the proxy accepts
    #: ``submit(trace=)`` — the hop is recorded as a ``remote/hop`` span
    #: and the context rides the wire's ``trace`` field, so the child
    #: tier's request span joins the SAME tree (fleet-of-fleets tracing)
    traces = True

    def __init__(self, host: str, port: int, *,
                 connect_timeout_s: float = 30.0,
                 retry: Optional[RetryPolicy] = None):
        self._addr = (host, port)
        self._connect_timeout_s = connect_timeout_s
        self._retry = retry
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        #: wire id -> Future for every in-flight request (guarded by _lock)
        self._pending: Dict[int, Future] = {}
        #: wire id -> open remote/hop Span for traced requests (guarded by
        #: _lock beside _pending; finished outside it)
        self._spans: Dict[int, Any] = {}
        self._next_id = 0
        self._dead: Optional[str] = None  # poison reason once connection dies
        self._closed = False              # close() is final even under retry
        self._dialing = False             # one reconnect dial at a time
        self._gen = 0                     # connection generation (reader tag)
        self._backoff = None              # reconnect delay stream (lazy)
        self._next_reconnect_t = 0.0
        #: successful re-dials (the parent's probe-driven recovery evidence)
        self.reconnects = 0
        self._install_locked(*self._dial())  # ctor is single-threaded

    # -- connection management ----------------------------------------------

    def _dial(self):
        """Dial + info handshake; returns ``(sock, reader, doc)``. Mutates
        NO proxy state, so the reconnect path can run it OUTSIDE the lock —
        a black-holed dial (connect_timeout_s) must never stall other lock
        users (close(), stop(), concurrent submits, the reader thread)."""
        host, port = self._addr
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout_s)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            reader = protocol.LineReader(sock)
            # the child tier's shape contract, fetched synchronously before
            # the reader thread takes over the receive side
            sock.sendall(protocol.encode_line({"id": 0, "op": "info"}))
            line = reader.next_line()
            if line is None:
                raise ConnectionError(f"tier at {host}:{port} closed "
                                      "during the info handshake")
            info = protocol.decode_line(line)
            if not info.get("ok"):
                raise ConnectionError(
                    f"tier info handshake failed: {info.get('message')}")
        except BaseException:
            # EVERY handshake failure (timeout, garbage, refusal) must
            # close the fd it dialed: a flapping child under reconnect
            # backoff would otherwise leak one socket per attempt
            sock.close()
            raise
        sock.settimeout(None)       # the reader blocks; handshake timed
        return sock, reader, info["result"]

    def _install_locked(self, sock, reader, doc) -> None:
        """Publish a dialed connection (caller holds ``_lock``, or is the
        single-threaded ctor) and spawn its generation-tagged reader."""
        self.row_dims = {op: int(d) for op, d in doc["row_dims"].items()}
        self.k = doc.get("k")
        # capability bits for a PARENT router's large-k classification:
        # the child tier's fleet-wide k bound rides through, and a child
        # that is ENTIRELY mesh-backed proxies as one sharded replica (a
        # mixed child serves both classes itself, so it reads as fast)
        self.k_max = doc.get("k_max")
        self.sharded = bool(doc.get("sharded_replicas")) and \
            doc.get("sharded_replicas") == doc.get("replicas")
        # model capability forwarding: a multi-tenant child tier declares
        # its zoo in the info doc — the proxy presents the WHOLE set to a
        # parent router (one RemoteEngine can serve several models), with
        # the child's default as its own default label
        child_models = doc.get("models") or {}
        self.models = frozenset(child_models) if child_models else None
        self.model = doc.get("default_model")
        # adaptive capability forwarding: which of the child's ops take
        # accuracy targets — a parent tier's info doc and router read the
        # same attribute the in-process engines expose
        self._ADAPTIVE_OPS = tuple(doc.get("adaptive_ops") or ())
        self.info = doc
        self._sock = sock
        self._reader = reader
        self._gen += 1
        self._reader_thread = threading.Thread(
            target=self._read_loop, args=(reader, self._gen),
            name=f"iwae-remote-{self._addr[0]}:{self._addr[1]}", daemon=True)
        self._reader_thread.start()

    def _reconnect_if_needed(self) -> None:
        """Healthy: no-op. Poisoned: re-dial under the RetryPolicy (one
        dial at a time, backoff-limited, dial itself OUTSIDE the lock) or
        raise the typed unavailable."""
        with self._lock:
            if self._dead is None:
                return
            if self._retry is None or self._closed:
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead})")
            now = time.monotonic()
            if now < self._next_reconnect_t:
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead}); next reconnect attempt in "
                    f"{self._next_reconnect_t - now:.2f}s")
            if self._dialing:
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead}); a reconnect dial is in progress")
            self._dialing = True
            old = self._sock
        # retire the dead socket first so its reader thread exits instead
        # of hanging on a half-open connection
        with contextlib.suppress(OSError):
            old.close()
        try:
            sock, reader, doc = self._dial()
        except (OSError, protocol.ProtocolError) as e:
            with self._lock:
                self._dialing = False
                if self._backoff is None:
                    self._backoff = self._retry.backoff(stream=self._gen)
                self._next_reconnect_t = \
                    time.monotonic() + self._backoff.next_delay()
            raise ReplicaUnavailable(
                f"remote tier reconnect failed: {e}") from None
        with self._lock:
            self._dialing = False
            if self._closed:
                # close() won the race against the dial: stay final
                sock.close()
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"(closed)")
            self._install_locked(sock, reader, doc)
            self._dead = None
            self._backoff = None
            self._next_reconnect_t = 0.0
            self.reconnects += 1

    # -- engine surface ------------------------------------------------------

    def submit(self, op: str, row, k: Optional[int] = None, *,
               seed: Optional[int] = None,
               model: Optional[str] = None,
               trace=None,
               target_se: Optional[float] = None,
               ess_floor: Optional[float] = None) -> Future:
        """One row to the child tier; returns the proxy Future.

        ``trace`` (a :class:`~...telemetry.tracing.TraceContext`) records
        this hop as a ``remote/hop`` span — open from send to response —
        and forwards the context on the wire, so the child tier's spans
        join the parent's tree.

        Validation (unknown op/model, wrong feature count, poisoned
        connection) raises synchronously, exactly like the in-process
        engine — the parent router's submit-failure path handles it. Under
        a ``RetryPolicy`` a poisoned proxy first attempts one (backoff-
        limited) reconnect, so the parent's warm probes drive recovery.
        ``model`` rides the wire's ``model`` field, so a parent fleet's
        model routing reaches the child tier's replicas unchanged.
        """
        if op not in self.row_dims:
            raise ValueError(
                f"unknown op {op!r}; this tier serves {sorted(self.row_dims)}")
        if model is not None:
            # the in-process engine's typed bad_request, via the ONE
            # shared validator: the child tier must hold these weights
            validate_model(model, self.models or ())
        row = row.tolist() if hasattr(row, "tolist") else list(row)
        if len(row) != self.row_dims[op]:
            raise ValueError(f"op {op!r} rows have {self.row_dims[op]} "
                             f"features, got {len(row)}")
        req: Dict[str, Any] = {"op": op, "x": row}
        if k is not None:
            req["k"] = int(k)
        if target_se is not None or ess_floor is not None:
            # adaptive targets ride the wire unchanged — the child tier's
            # own boundary validation answers malformed values with a
            # typed bad_request, which maps back to ValueError here (the
            # same shape the in-process engine raises synchronously)
            if op not in self._ADAPTIVE_OPS:
                raise ValueError(
                    f"target_se/ess_floor only apply to adaptive ops; "
                    f"this tier declares {sorted(self._ADAPTIVE_OPS)}, "
                    f"got op {op!r}")
            if target_se is not None:
                req["target_se"] = float(target_se)
            if ess_floor is not None:
                req["ess_floor"] = float(ess_floor)
        if model is not None:
            req["model"] = model
        if seed is not None:
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                # the leaf engines' int32 seed-tensor bound, enforced at
                # every boundary a seed can enter the fleet through
                raise ValueError(f"seed must be in [0, 2**31), got {seed}")
            req["seed"] = seed
        # poisoned: under a RetryPolicy, attempt ONE backoff-limited re-dial
        # (the parent's warm probe lands here); otherwise — or after
        # close() — the poison is final. The dial runs outside the lock.
        self._reconnect_if_needed()
        hop = None
        if trace is not None:
            from iwae_replication_project_tpu.telemetry.tracing import (
                start_span)
            hop = start_span("remote/hop", ctx=trace,
                             attrs={"host": self._addr[0],
                                    "port": self._addr[1], "op": op})
            req["trace"] = hop.ctx().wire()
        fut: Future = Future()
        with self._lock:
            if self._dead is not None:
                # died again between the reconnect check and the send
                if hop is not None:
                    hop.finish(error="unavailable")
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead})")
            self._next_id += 1
            req["id"] = self._next_id
            self._pending[self._next_id] = fut
            if hop is not None:
                self._spans[self._next_id] = hop
            try:
                # chaos hook: an injected OSError severs the proxy exactly
                # like a mid-send connection loss
                fault_point(SITE_REMOTE_SEND, addr=self._addr)
                self._sock.sendall(protocol.encode_line(req))  # iwaelint: disable=blocking-call-under-lock -- the proxy lock IS the frame serializer: id allocation, pending registration, and the send must be atomic per request or concurrent submits interleave frames; a dead child tier fails fast with OSError
            except OSError as e:
                del self._pending[self._next_id]
                self._spans.pop(self._next_id, None)
                self._dead = f"send failed: {e}"
                if hop is not None:
                    hop.finish(error="unavailable")
                raise ReplicaUnavailable(
                    f"remote tier send failed: {e}") from None
        return fut

    def start(self) -> None:
        """No-op: the child tier's engines are already running."""

    def stop(self, timeout_s: float = 60.0) -> None:
        """Drain the proxy: wait for every outstanding future (the child
        tier is still serving them), then close the connection. The child
        tier itself keeps running — its own lifecycle owner stops it."""
        with self._idle:
            self._idle.wait_for(lambda: not self._pending or self._dead,
                                timeout=timeout_s)
        self.close()

    def warmup(self, ops=(), ks=None) -> Dict[str, float]:
        """No-op: the child tier warmed its replicas before its ready line
        (serving/cli.py `_tier_mode`); there is nothing to compile here."""
        return {}

    def slo(self, timeout_s: float = 10.0) -> Dict[str, Any]:
        """The child tier's ``slo`` control document (``{"enabled": bool,
        "slo": <SLOMonitor.snapshot()>}``) — the scaling signal a fleet-of-
        fleets parent's autoscaler reads over the wire (telemetry/slo.py's
        ``peak_burns``/``window_requests`` reduce it identically to a local
        snapshot). Blocks up to ``timeout_s``; a dead/poisoned proxy raises
        :class:`ReplicaUnavailable`, exactly like :meth:`submit`."""
        self._reconnect_if_needed()
        fut: Future = Future()
        with self._lock:
            if self._dead is not None:
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead})")
            self._next_id += 1
            req = {"op": "slo", "id": self._next_id}
            self._pending[self._next_id] = fut
            try:
                self._sock.sendall(protocol.encode_line(req))  # iwaelint: disable=blocking-call-under-lock -- same frame-serializer rule as submit: id allocation, pending registration, and the send are atomic per request
            except OSError as e:
                del self._pending[self._next_id]
                self._dead = f"send failed: {e}"
                raise ReplicaUnavailable(
                    f"remote tier send failed: {e}") from None
        return fut.result(timeout=timeout_s)

    # -- receive side --------------------------------------------------------

    def _read_loop(self, reader: protocol.LineReader, gen: int) -> None:
        while True:
            try:
                line = reader.next_line()
            except (protocol.ProtocolError, OSError) as e:
                self._conn_lost(gen, f"receive failed: {e}")
                return
            if line is None:
                self._conn_lost(gen, "tier closed the connection")
                return
            try:
                resp = protocol.decode_line(line)
            except protocol.ProtocolError as e:
                self._conn_lost(gen, f"malformed response: {e}")
                return
            with self._lock:
                if gen != self._gen:
                    return      # superseded connection: not ours to serve
                fut = self._pending.pop(resp.get("id"), None)
                hop = self._spans.pop(resp.get("id"), None)
                self._idle.notify_all()
            if fut is None:
                continue        # duplicate/unknown id: first-wins upstream
            # complete OUTSIDE the lock: the parent router's callback may
            # re-enter submit()
            if resp.get("ok"):
                if hop is not None:
                    hop.finish()
                result = resp.get("result")
                # one submit = one row; unwrap the per-row result list
                _complete(fut, result=result[0]
                          if isinstance(result, list) and len(result) == 1
                          else result)
            else:
                if hop is not None:
                    hop.finish(error=resp.get("error", "internal"))
                exc_type = _CODE_EXC.get(resp.get("error", "internal"),
                                         RuntimeError)
                _complete(fut, exc=exc_type(resp.get("message", "")))

    def _conn_lost(self, gen: int, reason: str) -> None:
        """A reader thread's connection died: poison the proxy and fail
        everything outstanding — UNLESS the proxy already moved on to a
        newer connection (then the stale thread just exits)."""
        with self._lock:
            if gen != self._gen:
                return
            if self._dead is None:
                self._dead = reason
            orphans = list(self._pending.values())
            self._pending.clear()
            orphan_spans = list(self._spans.values())
            self._spans.clear()
            self._idle.notify_all()
        for hop in orphan_spans:
            hop.finish(error="unavailable")
        for fut in orphans:
            _complete(fut, exc=ReplicaUnavailable(
                f"remote tier connection lost: {reason}"))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._dead is None:
                self._dead = "closed"
            sock = self._sock
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            # best-effort shutdown: the socket may already be dead, and
            # close() below is the real teardown (waiver retired: the leak
            # pass proves close() acquisition-free)
            pass
        sock.close()

    def __enter__(self) -> "RemoteEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
