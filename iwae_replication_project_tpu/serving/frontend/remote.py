"""``RemoteEngine``: a serving tier as one replica of a bigger fleet.

The proxy presents the engine surface the replica router dispatches over —
``submit(op, row, k=, seed=)`` returning a Future, ``stop()``,
``row_dims``, ``k`` — backed by ONE JSON-lines TCP connection to a running
:class:`~.server.ServingTier`. A parent :class:`~.router.ReplicaRouter`
over N RemoteEngines therefore composes fleets out of *processes* (each
child tier owns its own device, CPU pin, and XLA runtime — the
``bench.py --serving`` ``replica_scaling`` sweep builds exactly this), and
recursively out of fleets: protocol.py's explicit ``seed`` field exists so
the parent's admission-order seeds ride through to the leaf engines and
results stay bitwise independent of which process served each request.

Failure semantics map back onto the engine exception taxonomy the router
already speaks:

* a typed ``overloaded`` response completes the future with
  :class:`~..batcher.EngineOverloaded` (the router tries peers without
  declaring the replica dead — an async shed means *full*, not *failed*);
* ``timeout`` becomes :class:`~..batcher.RequestTimeout` (per-request
  outcome, no reroute);
* a lost connection fails every outstanding future with
  :class:`~.router.ReplicaUnavailable` and poisons the proxy — subsequent
  submits raise synchronously, so the parent marks the replica unhealthy
  and its warm probes drive reconnection attempts.

Ops and payload dims are validated locally against the child tier's
``info`` document (fetched at connect time), so malformed requests raise
``ValueError`` synchronously like the in-process engine instead of
surfacing as a ``bad_request`` future failure that would smear the replica.

One lock guards the socket write side + the pending-future map; the reader
thread completes futures strictly outside it (completion callbacks — the
parent router's — re-enter :meth:`submit`).
"""

from __future__ import annotations

import socket
import threading
from concurrent.futures import Future
from typing import Any, Dict, Optional

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    RequestTimeout,
    complete_future as _complete,
)
from iwae_replication_project_tpu.serving.frontend import protocol
from iwae_replication_project_tpu.serving.frontend.router import (
    ReplicaUnavailable,
)

__all__ = ["RemoteEngine"]

#: typed response code -> the engine exception the router dispatches on
_CODE_EXC = {
    "overloaded": EngineOverloaded,
    "timeout": RequestTimeout,
    "unavailable": ReplicaUnavailable,
    "bad_request": ValueError,
}


class RemoteEngine:
    """The engine surface over one TCP connection to a serving tier."""

    def __init__(self, host: str, port: int, *,
                 connect_timeout_s: float = 30.0):
        self._addr = (host, port)
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = protocol.LineReader(self._sock)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        #: wire id -> Future for every in-flight request (guarded by _lock)
        self._pending: Dict[int, Future] = {}
        self._next_id = 0
        self._dead: Optional[str] = None    # poison reason once connection dies
        # the child tier's shape contract, fetched synchronously before the
        # reader thread takes over the receive side
        self._sock.sendall(protocol.encode_line({"id": 0, "op": "info"}))
        line = self._reader.next_line()
        if line is None:
            raise ConnectionError(f"tier at {host}:{port} closed during "
                                  "the info handshake")
        info = protocol.decode_line(line)
        if not info.get("ok"):
            raise ConnectionError(
                f"tier info handshake failed: {info.get('message')}")
        doc = info["result"]
        self.row_dims = {op: int(d) for op, d in doc["row_dims"].items()}
        self.k = doc.get("k")
        # capability bits for a PARENT router's large-k classification:
        # the child tier's fleet-wide k bound rides through, and a child
        # that is ENTIRELY mesh-backed proxies as one sharded replica (a
        # mixed child serves both classes itself, so it reads as fast)
        self.k_max = doc.get("k_max")
        self.sharded = bool(doc.get("sharded_replicas")) and \
            doc.get("sharded_replicas") == doc.get("replicas")
        self.info = doc
        self._sock.settimeout(None)     # the reader blocks; handshake timed
        self._reader_thread = threading.Thread(
            target=self._read_loop, name=f"iwae-remote-{host}:{port}",
            daemon=True)
        self._reader_thread.start()

    # -- engine surface ------------------------------------------------------

    def submit(self, op: str, row, k: Optional[int] = None, *,
               seed: Optional[int] = None) -> Future:
        """One row to the child tier; returns the proxy Future.

        Validation (unknown op, wrong feature count, poisoned connection)
        raises synchronously, exactly like the in-process engine — the
        parent router's submit-failure path handles it.
        """
        if op not in self.row_dims:
            raise ValueError(
                f"unknown op {op!r}; this tier serves {sorted(self.row_dims)}")
        row = row.tolist() if hasattr(row, "tolist") else list(row)
        if len(row) != self.row_dims[op]:
            raise ValueError(f"op {op!r} rows have {self.row_dims[op]} "
                             f"features, got {len(row)}")
        req: Dict[str, Any] = {"op": op, "x": row}
        if k is not None:
            req["k"] = int(k)
        if seed is not None:
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                # the leaf engines' int32 seed-tensor bound, enforced at
                # every boundary a seed can enter the fleet through
                raise ValueError(f"seed must be in [0, 2**31), got {seed}")
            req["seed"] = seed
        fut: Future = Future()
        with self._lock:
            if self._dead is not None:
                raise ReplicaUnavailable(
                    f"remote tier {self._addr[0]}:{self._addr[1]} is gone "
                    f"({self._dead})")
            self._next_id += 1
            req["id"] = self._next_id
            self._pending[self._next_id] = fut
            try:
                self._sock.sendall(protocol.encode_line(req))
            except OSError as e:
                del self._pending[self._next_id]
                self._dead = f"send failed: {e}"
                raise ReplicaUnavailable(
                    f"remote tier send failed: {e}") from None
        return fut

    def start(self) -> None:
        """No-op: the child tier's engines are already running."""

    def stop(self, timeout_s: float = 60.0) -> None:
        """Drain the proxy: wait for every outstanding future (the child
        tier is still serving them), then close the connection. The child
        tier itself keeps running — its own lifecycle owner stops it."""
        with self._idle:
            self._idle.wait_for(lambda: not self._pending or self._dead,
                                timeout=timeout_s)
        self.close()

    def warmup(self, ops=(), ks=None) -> Dict[str, float]:
        """No-op: the child tier warmed its replicas before its ready line
        (serving/cli.py `_tier_mode`); there is nothing to compile here."""
        return {}

    # -- receive side --------------------------------------------------------

    def _read_loop(self) -> None:
        while True:
            try:
                line = self._reader.next_line()
            except (protocol.ProtocolError, OSError) as e:
                self._fail_all(f"receive failed: {e}")
                return
            if line is None:
                self._fail_all("tier closed the connection")
                return
            try:
                resp = protocol.decode_line(line)
            except protocol.ProtocolError as e:
                self._fail_all(f"malformed response: {e}")
                return
            with self._lock:
                fut = self._pending.pop(resp.get("id"), None)
                self._idle.notify_all()
            if fut is None:
                continue        # duplicate/unknown id: first-wins upstream
            # complete OUTSIDE the lock: the parent router's callback may
            # re-enter submit()
            if resp.get("ok"):
                result = resp.get("result")
                # one submit = one row; unwrap the per-row result list
                _complete(fut, result=result[0]
                          if isinstance(result, list) and len(result) == 1
                          else result)
            else:
                exc_type = _CODE_EXC.get(resp.get("error", "internal"),
                                         RuntimeError)
                _complete(fut, exc=exc_type(resp.get("message", "")))

    def _fail_all(self, reason: str) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = reason
            orphans = list(self._pending.values())
            self._pending.clear()
            self._idle.notify_all()
        for fut in orphans:
            _complete(fut, exc=ReplicaUnavailable(
                f"remote tier connection lost: {reason}"))

    def close(self) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = "closed"
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "RemoteEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
