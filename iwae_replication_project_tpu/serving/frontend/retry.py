"""RetryPolicy: the client half of the failure model.

Every typed rejection in this tier ends with "retry with backoff" — this
module is where somebody finally does. One frozen policy object drives
both self-healing surfaces:

* :class:`~.client.TierClient` (``retry=`` ctor arg) — blocking requests
  retry typed-retryable errors and reconnect across dropped/garbled
  connections, with optional tail-latency hedging;
* :class:`~.remote.RemoteEngine` (``retry=`` ctor arg) — a poisoned proxy
  re-dials the child tier on the next submit (rate-limited by the same
  backoff), so a parent router's warm probes drive reconnection instead
  of writing the replica off forever.

Semantics:

* **backoff** is exponential with *decorrelated jitter*:
  ``sleep = min(cap, uniform(base, prev_sleep * 3))`` — retries from many
  clients de-synchronize instead of stampeding in lockstep. The jitter
  stream is ``random.Random`` seeded from ``(policy.seed, attempt
  context)``: a chaos run replays bitwise;
* **per-code retryability**: ``retry_codes`` names which typed protocol
  codes are worth retrying. Default: everything except ``bad_request`` —
  the request itself is wrong; and note retrying a served-but-lost
  request is SAFE here because serving results are a pure function of
  (weights, payload, seed, k), so a caller that pins its seed gets
  bitwise the same answer on any attempt;
* **retry_after_s**: ``overloaded`` / ``quota_exceeded`` responses carry a
  machine-readable wait hint (protocol.py); the policy sleeps
  ``max(backoff, hint)`` — an exact quota refill beats guessing. A
  ``quota_exceeded`` WITHOUT a hint is the cost-above-burst rejection
  that no wait can ever admit — the client raises it immediately (split
  the request) instead of burning its attempt budget;
* **deadline**: one overall budget per logical request across all
  attempts and hedges; when the next sleep would cross it, the last
  error surfaces;
* **hedging** (``hedge_after_s``): a blocking request unanswered after
  the hedge delay is re-sent on a SECOND connection with the same seed;
  first response wins and the loser's connection is closed (first-wins
  cancellation — the abandoned tier work completes harmlessly and its
  write is dropped). With an explicit seed the two are bitwise
  identical, so hedging is invisible except in latency.
"""

from __future__ import annotations

import dataclasses
import random
from typing import FrozenSet, Optional

__all__ = ["RetryPolicy", "Backoff", "DEFAULT_RETRY_CODES"]

#: codes worth retrying (see module docstring); ``bad_request`` never is
DEFAULT_RETRY_CODES: FrozenSet[str] = frozenset(
    {"overloaded", "quota_exceeded", "timeout", "unavailable", "internal"})


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Client retry/hedging knobs (frozen: share one across threads)."""

    max_attempts: int = 6
    base_delay_s: float = 0.02
    max_delay_s: float = 2.0
    #: overall wall budget per logical request (None = unbounded)
    deadline_s: Optional[float] = 30.0
    retry_codes: FrozenSet[str] = DEFAULT_RETRY_CODES
    #: also retry dropped/garbled connections (reconnecting first)
    retry_connection_errors: bool = True
    #: blocking-path tail-latency hedge delay (None = no hedging)
    hedge_after_s: Optional[float] = None
    #: seeds the jitter streams — chaos runs replay bitwise
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError(
                f"need 0 <= base_delay_s <= max_delay_s, got "
                f"{self.base_delay_s} / {self.max_delay_s}")
        unknown = set(self.retry_codes) - set(DEFAULT_RETRY_CODES) - \
            {"bad_request"}
        if unknown:
            raise ValueError(f"unknown retry code(s): {sorted(unknown)}")

    def retryable(self, code: str) -> bool:
        return code in self.retry_codes

    def backoff(self, stream: int = 0) -> "Backoff":
        """A fresh deterministic delay stream (one per logical request;
        `stream` decorrelates concurrent requests under one policy —
        integer mixing, not the deprecated tuple seeding)."""
        return Backoff(self, random.Random(self.seed * 1_000_003 + stream))


class Backoff:
    """Stateful decorrelated-jitter delay generator for ONE logical
    request: ``next_delay(hint)`` returns how long to sleep before the
    next attempt, honoring a server ``retry_after_s`` hint as a floor."""

    def __init__(self, policy: RetryPolicy, rng: random.Random):
        self._policy = policy
        self._rng = rng
        self._prev = policy.base_delay_s

    def next_delay(self, retry_after_s: Optional[float] = None) -> float:
        p = self._policy
        self._prev = min(p.max_delay_s,
                         self._rng.uniform(p.base_delay_s,
                                           max(p.base_delay_s,
                                               self._prev * 3.0)))
        return max(self._prev, retry_after_s or 0.0)
