"""Replica routing: least-inflight dispatch, (op, k) affinity, health.

The router owns the fleet-level request lifecycle between admission
(server.py — quotas and parsing live there; the router never sees a client
id) and the per-replica engines:

* **seed minting** — every admitted request gets a tier-level seed in
  arrival order (or keeps an explicitly supplied one). Serving results are
  a pure function of (weights, payload, seed, k) — serving/programs.py —
  so routing, re-routing, and replica choice are all bitwise invisible:
  the fleet returns exactly what one direct engine would (pinned by
  tests/test_frontend.py and ``bench.py --serving``'s ``replica_scaling``
  parity check);
* **selection policy** — least-inflight over healthy replicas, tie-broken
  by lowest replica index, with sticky (op, k)-group affinity: the replica
  that last served a group keeps it while its inflight stays within
  ``affinity_slack`` of the least-loaded candidate, so same-shape requests
  keep flowing to the replica whose AOT/jit caches (and, on hardware, its
  device-resident executables) are already warm for that bucket — load
  imbalance beyond the slack overrides affinity;
* **model-affinity classification** — a fleet may serve a whole model zoo
  (multi-tenant replicas expose ``engine.model`` / ``engine.models`` — the
  capability snapshot, same machinery as the large-k bits below). A
  request naming a model is eligible only for replicas that hold that
  model's weights; a model no replica declares is a synchronous ValueError
  (the typed ``bad_request`` upstream — it must never be served by the
  wrong weights). Model-less requests in a multi-model fleet resolve to
  the tier's ``default_model`` (the first replica's) at admission, so
  results stay a pure function of the request, never of routing;
  the affinity groups below are keyed (model, op, k), so each tenant's
  traffic keeps hitting the replica whose store entries are warm for it;
* **large-k classification** — a fleet may mix single-device replicas with
  mesh-backed :class:`~..sharded.ShardedScoreEngine` replicas
  (``engine.sharded``). A ``score`` request with k above
  ``large_k_threshold`` (default: the fast replicas' ``k_max``) is
  eligible only for sharded replicas; everything else keeps the fast
  single-device path (sharded replicas pick small traffic up only when
  the fleet has no fast replica at all). Replicas also only ever see ops
  they serve (``engine.row_dims``). An explicit request k outside
  ``[1, max over replicas' k_max]`` is a synchronous ValueError — the
  typed ``bad_request`` upstream — since no replica could legally take
  it; selection then never has to reason about impossible asks;
* **failure handling** — an engine that raises (at submit or via its
  future) marks its replica unhealthy and its outstanding work is
  re-dispatched to healthy peers *with the original seeds* (a reroute
  returns the identical result). A replica whose oldest in-flight request
  stalls past ``stall_deadline_s`` is drained the same way. Unhealthy
  replicas are re-admitted after a successful warm probe (a real request
  through the engine's warmed program that completes within
  ``probe_timeout_s``). Duplicate completions from abandoned dispatches
  are first-wins and error-ignored;
* **admission ceiling** — at most ``max_outstanding`` requests live in the
  tier at once; past it, :meth:`submit` raises :class:`TierOverloaded`
  (the typed ``overloaded`` response upstream). An individual replica's
  :class:`~..batcher.EngineOverloaded` shed makes the router try its
  peers first; only when EVERY healthy replica sheds does the caller see
  the overload;
* **graceful drain** — :meth:`drain` stops intake, flushes every replica
  via ``engine.stop()``, waits for the outstanding count to reach zero,
  and error-completes any leftover future with
  :class:`ReplicaUnavailable`: every accepted request gets a result or a
  typed error, never silence;
* **elastic fleet shape** — :meth:`add_replica` joins a new engine at a
  stable, monotonically-assigned index (indices are never reused, so
  affinity maps and per-replica gauges stay unambiguous across the
  fleet's whole history), and :meth:`remove_replica` retires one through
  the same drain contract as shutdown: the replica stops taking new work,
  its engine is flushed, and anything it still holds is rerouted to peers
  *with the original seeds* — an accepted request resolves identically
  whether the fleet grew, shrank, or held still, because seeds are minted
  in admission order before any replica is chosen. Every shape change
  recomputes the fleet capability snapshot (``k_max``, ``models``,
  large-k classification, ``default_model`` — which is sticky while its
  model is still served, so model-less traffic never silently switches
  weights mid-flight) and prunes affinity entries pointing at departed
  replicas. The fleet autoscaler (``serving/fleet``) drives both.

Observability: one :class:`~...telemetry.registry.MetricRegistry` per
router — ``router/inflight/r<i>`` and ``router/healthy/r<i>`` gauges per
replica plus routed/reroutes/sheds/replica_failures/affinity_hits/
stall_drains/probe_readmits counters — exported on the tier's Prometheus
``/metrics`` page next to each replica engine's own registry.

The router holds exactly ONE lock; engines and the metric registry have
their own and never call back into the router while holding them, and tier
futures are completed outside the lock — the lock graph stays acyclic by
construction (and analysis/rules/concurrency.py checks this package).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    RequestTimeout,
    complete_future,
)
from iwae_replication_project_tpu.serving.buckets import (
    target_class,
    validate_adaptive_target,
    validate_k,
    validate_model,
)
from iwae_replication_project_tpu.serving.faults import (
    SITE_ROUTER_DISPATCH,
    fault_point,
)
from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

__all__ = ["ReplicaRouter", "TierOverloaded", "ReplicaUnavailable"]

#: ops carrying an accuracy target instead of a fixed sample count — their
#: k is the CAP, their results end in a measured ``k_used`` column, and the
#: router dispatches them by least ESTIMATED work (the per-(op, target
#: class) EWMA of measured k_used) instead of least inflight
ADAPTIVE_OPS = ("score_adaptive",)

#: EWMA weight of the per-(op, target-class) measured-k_used estimator —
#: fast enough to track a workload shift within tens of requests, slow
#: enough that one freak row does not flip placement
WORK_EWMA_ALPHA = 0.3


class TierOverloaded(RuntimeError):
    """The tier-wide outstanding-request ceiling is hit; back off/retry."""


class ReplicaUnavailable(RuntimeError):
    """No healthy replica can take the request (fleet down or draining)."""


@dataclasses.dataclass
class _Tracked:
    """One admitted request's tier-level state (owned by the router)."""

    ticket: int
    op: str
    row: Any                      # validated payload row (np [d])
    k: Optional[int]
    seed: int
    future: Future
    #: resolved tenant (None only in an unlabeled single-model fleet)
    model: Optional[str] = None
    #: tracing context the attempt spans attach under (None = untraced)
    trace: Any = None
    #: the CURRENT dispatch attempt's open span — written by the flow that
    #: owns the request at that moment (dispatch path, or the owning
    #: engine-future callback; `owns` gates every touch), finished exactly
    #: once per attempt before the next attempt opens its own
    span: Any = None
    attempts: int = 0
    replica_index: int = -1
    t_dispatch: float = 0.0
    #: True while the dispatching thread is in the submit window — between
    #: placing the request on a replica and registering its done-callback,
    #: which runs OUTSIDE the router lock (engine.submit may block). A
    #: failure drain must not steal a request in this window: the
    #: dispatcher is still touching it, and stealing double-dispatches
    #: (two threads rerouting one request, both mutating its span and
    #: attempt count). Written only under the router lock.
    submitting: bool = False
    #: set (under the router lock) exactly once, when the tier future is
    #: completed — guards the outstanding-count decrement against the
    #: duplicate completions rerouting can produce
    finalized: bool = False
    #: adaptive accuracy target (``score_adaptive``; 0.0 = criterion
    #: disabled) — forwarded verbatim to the serving replica
    target_se: float = 0.0
    ess_floor: float = 0.0
    #: the coarse (decade-quantized) target class this request's measured
    #: k_used is attributed under; None = fixed-k traffic
    tclass: Optional[str] = None
    #: estimated samples this request will draw (adaptive: the target
    #: class's k_used EWMA, capped at k; fixed-k: the request k) — what the
    #: estimated-work selection sums per replica
    work: float = 1.0


class _Replica:
    """One engine plus its routing state. Deliberately lock-free: every
    mutable field is guarded by the owning router's single lock, so the
    fleet has one synchronization domain, not N+1."""

    __slots__ = ("index", "engine", "healthy", "draining", "outstanding",
                 "last_error", "sharded", "k_max", "ops", "model", "models",
                 "traces")

    def __init__(self, index: int, engine):
        self.index = index
        self.engine = engine
        self.healthy = True
        #: set by remove_replica: the replica finishes what it holds but
        #: takes no new work (excluded by _select, never warm-probed back)
        self.draining = False
        #: ticket -> _Tracked currently dispatched here (inflight = len)
        self.outstanding: Dict[int, _Tracked] = {}
        self.last_error: Optional[str] = None
        # capability snapshot (immutable per engine): the classification
        # bits _select filters on. Fakes without the attributes read as
        # fast/unbounded/serve-everything — the pre-large-k behavior.
        self.sharded = bool(getattr(engine, "sharded", False))
        self.k_max: Optional[int] = getattr(engine, "k_max", None)
        dims = getattr(engine, "row_dims", None)
        self.ops: Optional[frozenset] = \
            frozenset(dims) if dims is not None else None
        # model capability: the replica's default tenant plus the full set
        # it holds weights for (RemoteEngine proxies forward a child tier's
        # whole zoo). Neither attribute -> unlabeled (the single-model
        # legacy replica: serves model-less traffic only).
        self.model: Optional[str] = getattr(engine, "model", None)
        ms = getattr(engine, "models", None)
        self.models: Optional[frozenset] = \
            frozenset(ms) if ms else \
            (frozenset({self.model}) if self.model is not None else None)
        # tracing capability: whether the engine accepts submit(trace=) and
        # records pipeline-stage spans. Fakes without the bit never see the
        # kwarg — the router's attempt spans still cover the dispatch.
        self.traces = bool(getattr(engine, "traces", False))

    def serves(self, op: str) -> bool:
        return self.ops is None or op in self.ops

    def serves_model(self, model: Optional[str]) -> bool:
        """Whether this replica's weights may serve `model`: labeled
        replicas serve exactly their declared set; unlabeled replicas serve
        model-less traffic (the single-model legacy fleet)."""
        if model is None:
            return self.models is None
        return self.models is not None and model in self.models


class ReplicaRouter:
    """Least-inflight, affinity-aware dispatch over N engine replicas.

    ``engines`` share weights and config (the tier builds them that way);
    anything with the engine surface used here — ``submit(op, row, k=,
    seed=)`` returning a Future, ``stop()``, ``row_dims``, ``k`` — routes,
    so tests drive the full policy with fake engines and no device.
    """

    def __init__(self, engines: Sequence, *, max_outstanding: int = 4096,
                 affinity_slack: int = 2, stall_deadline_s: float = 30.0,
                 probe_timeout_s: float = 5.0,
                 probe_op: str = "score",
                 large_k_threshold: Optional[int] = None,
                 registry: Optional[MetricRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not engines:
            raise ValueError("a router needs at least one replica engine")
        if max_outstanding < 1:
            raise ValueError(
                f"max_outstanding must be >= 1, got {max_outstanding}")
        self.registry = registry if registry is not None else MetricRegistry()
        self.max_outstanding = int(max_outstanding)
        self.affinity_slack = int(affinity_slack)
        self.stall_deadline_s = float(stall_deadline_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.probe_op = probe_op
        self._clock = clock
        self._lock = threading.Lock()
        self._empty = threading.Condition(self._lock)
        # the replica list is COPY-ON-WRITE: every shape change (join,
        # removal) rebinds self._replicas to a fresh list under the lock,
        # so lock-free readers (serves_op, drain's flush walk) always
        # iterate one coherent snapshot. Replica indices are stable and
        # monotonic — never list positions — and _by_index is the only
        # index -> replica resolution (affinity maps survive removals).
        self._replicas: List[_Replica] = \
            [_Replica(i, e) for i, e in enumerate(engines)]
        self._by_index: Dict[int, _Replica] = \
            {r.index: r for r in self._replicas}
        self._next_index = len(self._replicas)
        #: the constructor's explicit large-k threshold, if any — honored
        #: verbatim across every fleet-shape recompute
        self._large_k_explicit = large_k_threshold
        self._affinity: Dict[Tuple, int] = {}
        #: (op, target-class) -> EWMA of measured per-row k_used — the
        #: estimated-work weight adaptive dispatch balances on (guarded by
        #: the router lock; fed by _on_engine_done from each adaptive
        #: result's k_used column)
        self._work_ewma: Dict[Tuple[str, Optional[str]], float] = {}
        #: where a model-less request lands in an all-labeled fleet: the
        #: FIRST replica's default model — resolved at admission so results
        #: are a pure function of the request, never of replica choice.
        #: STICKY across fleet-shape changes while its model is still
        #: served (see _recompute_locked).
        self.default_model: Optional[str] = None
        self._seed_counter = 0
        self._ticket_counter = 0
        self._outstanding_total = 0
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self._recompute_locked()
        self.registry.gauge("router/outstanding").set(0)
        for r in self._replicas:
            self._publish_replica(r)
        # pre-register the counter schema so /metrics carries every router
        # counter from the first scrape (same idiom as ServingMetrics)
        for name in ("routed", "completed", "errors", "reroutes", "sheds",
                     "quota_rejections", "replica_failures", "affinity_hits",
                     "stall_drains", "probe_readmits", "probes"):
            self.registry.counter(f"router/{name}")

    # -- metric plumbing ---------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        self.registry.counter(f"router/{name}").inc(n)

    def _publish_replica(self, r: _Replica) -> None:
        """Per-replica gauges (caller holds the lock or is __init__)."""
        self.registry.gauge(f"router/inflight/r{r.index}").set(
            len(r.outstanding))
        self.registry.gauge(f"router/healthy/r{r.index}").set(
            1 if r.healthy else 0)

    # -- fleet capability snapshot -------------------------------------------

    def _recompute_locked(self) -> None:
        """Recompute the fleet-wide capability snapshot from the CURRENT
        replica list (caller holds the lock, or is __init__). Runs on
        construction and on every fleet-shape change, so admission bounds
        (``k_max``), the model universe, and the large-k classification
        always describe the replicas that can actually serve — a stale
        snapshot would reject valid requests or admit impossible ones.

        * ``large_k_threshold`` — an explicit constructor threshold is
          honored verbatim; the derived default (the fast replicas'
          smallest ``k_max``) is re-derived, and it collapses to None when
          the fleet has no sharded replica left (nothing to classify onto —
          a threshold then would turn valid k into spurious unavailable
          errors) or when no fast replica exposes a bound (a 0 threshold
          would push everything onto the sharded class);
        * ``default_model`` is STICKY: while the current default's model is
          still served somewhere, it stays — re-deriving it from the (new)
          first replica would silently switch which weights serve
          model-less traffic mid-stream, breaking the results-are-a-pure-
          function-of-the-request contract. Only when the default's model
          leaves the fleet entirely is it re-resolved;
        * affinity entries pointing at departed replicas are pruned (the
          live ones keep their stable indices, so they stay valid).
        """
        reps = self._replicas
        self._has_fast = any(not r.sharded for r in reps)
        has_sharded = any(r.sharded for r in reps)
        if not has_sharded:
            self.large_k_threshold: Optional[int] = None
        elif self._large_k_explicit is not None:
            self.large_k_threshold = int(self._large_k_explicit)
        elif self._has_fast:
            fast_maxes = [r.k_max for r in reps
                          if not r.sharded and r.k_max is not None]
            self.large_k_threshold = min(fast_maxes) if fast_maxes else None
        else:
            self.large_k_threshold = None
        #: the tier-wide k admission bound (None = engines enforce theirs):
        #: max over replica k_max — a request k above it gets a synchronous
        #: ValueError (typed bad_request), never an internal error
        k_maxes = [r.k_max for r in reps if r.k_max is not None]
        self.k_max: Optional[int] = max(k_maxes) if k_maxes else None
        #: the union of declared model capabilities over the fleet (empty =
        #: unlabeled single-model fleet) — the typed-bad_request universe
        self.models: frozenset = frozenset().union(
            *(r.models for r in reps if r.models is not None)) \
            if any(r.models for r in reps) else frozenset()
        self._has_unlabeled = any(r.models is None for r in reps)
        if self.default_model is None or self.default_model not in self.models:
            self.default_model = next(
                (r.model for r in reps if r.model is not None), None)
        self._affinity = {g: i for g, i in self._affinity.items()
                          if i in self._by_index}
        self.registry.gauge("router/replicas").set(len(reps))

    # -- fleet shape: join + drain-based removal -----------------------------

    def add_replica(self, engine) -> int:
        """Join ``engine`` as a new replica; returns its stable index.

        The engine is expected to arrive warm (built over shared params
        with the persistent XLA/autotune caches active, so its first
        dispatches deserialize instead of compiling — the fleet
        autoscaler's scale-up contract); the router itself only snapshots
        its capabilities and folds them into the fleet-wide bounds.
        Existing traffic is untouched: seeds were minted at admission, so
        work the new replica picks up returns bitwise what any peer would
        have returned.
        """
        with self._lock:
            if self._closed:
                raise ReplicaUnavailable(
                    "serving tier is draining; no new replicas")
            index = self._next_index
            self._next_index += 1
            r = _Replica(index, engine)
            self._replicas = self._replicas + [r]     # copy-on-write
            self._by_index[index] = r
            self._publish_replica(r)
            self._recompute_locked()
        return index

    def remove_replica(self, index: int, timeout_s: float = 30.0):
        """Retire replica ``index`` through the drain contract; returns its
        engine (the caller owns disposal — the fleet autoscaler keeps it
        for teardown).

        The replica is first marked draining (it finishes what it holds
        but is never selected again), then its engine is flushed via
        ``engine.stop()`` — queued work dispatches and every in-flight
        future completes. After the outstanding set empties (or
        ``timeout_s`` passes — e.g. the replica died mid-removal), the
        replica leaves the fleet, capabilities recompute, and anything it
        still held is rerouted to peers *with the original seeds*: no
        accepted request is ever lost to a scale-down, and results are
        bitwise identical to a fleet that never shrank.
        """
        with self._lock:
            r = self._by_index.get(index)
            if r is None:
                raise ValueError(f"no replica with index {index}")
            if not any(x is not r and not x.draining
                       for x in self._replicas):
                raise ValueError("cannot remove the last replica")
            if r.draining:
                raise ValueError(f"replica r{index} is already draining")
            r.draining = True
        try:
            # outside the lock: engine.stop() flushes queues and joins
            # worker threads — foreign blocking work the router lock never
            # nests around
            r.engine.stop()
        except Exception as e:
            # the replica died mid-removal (the chaos schedule's favorite
            # moment): the standard failure path reroutes its in-flight
            # work with the original seeds; removal then proceeds
            self._replica_failed(r, e)
        deadline = self._clock() + timeout_s
        with self._empty:
            while r.outstanding:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    break
                self._empty.wait(timeout=min(remaining, 0.25))
        with self._lock:
            # steal only fully-dispatched leftovers — one still in its
            # submit window belongs to the dispatching thread, which
            # observes the unhealthy flag and reroutes it itself
            r.healthy = False
            leftovers = [t for t in r.outstanding.values()
                         if not t.submitting]
            for t in leftovers:
                del r.outstanding[t.ticket]
            self._replicas = [x for x in self._replicas if x is not r]
            self._by_index.pop(index, None)
            self._recompute_locked()
            self.registry.gauge(f"router/inflight/r{index}").set(0)
            self.registry.gauge(f"router/healthy/r{index}").set(0)
        for t in leftovers:
            self._count("reroutes")
            self._finish_span(t, ReplicaUnavailable(
                f"replica r{index} removed before the request completed"))
            self._redispatch(t, exclude={index})
        return r.engine

    def prime_affinity(self, model: Optional[str], op: str,
                       k: Optional[int], index: int) -> bool:
        """Placement hint from the fleet planner: point the ``(model, op,
        k)`` affinity group at replica ``index``, so the group's next
        request lands on the replica whose store entries the placement
        plan made resident there. A hint, not a constraint — load
        imbalance beyond ``affinity_slack`` still overrides, exactly like
        organically-earned affinity. Returns False (no-op) when the target
        is gone, draining, or unhealthy."""
        with self._lock:
            return self._prime_affinity_locked(model, op, k, index)

    def _prime_affinity_locked(self, model: Optional[str], op: str,
                               k: Optional[int], index: int) -> bool:
        r = self._by_index.get(index)
        if r is None or r.draining or not r.healthy:
            return False
        self._affinity[(model, op, k)] = index
        return True

    # -- introspection -----------------------------------------------------

    @property
    def engines(self) -> List:
        return [r.engine for r in self._replicas]

    @property
    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding_total

    def serves_op(self, op: str) -> bool:
        """Whether ANY replica serves `op` (capability sets are immutable
        per engine and the replica list is copy-on-write, so a lock-free
        read iterates one coherent snapshot — same basis as submit's
        check). The front end's SLO accounting uses this to keep garbage
        op names from minting burn-rate gauges."""
        return any(r.serves(op) for r in self._replicas)

    def profilers(self) -> tuple:
        """The live :class:`~...telemetry.profiling.DispatchProfiler` of
        every replica engine that carries one (in-process engines do by
        default; RemoteEngine proxies and fakes don't) — the ``/prof``
        HTTP view's backing set. Lock-free: the replica list is
        copy-on-write, same basis as :meth:`serves_op`."""
        return tuple(p for p in (getattr(r.engine, "profiler", None)
                                 for r in self._replicas) if p is not None)

    def replica_states(self) -> List[dict]:
        with self._lock:
            return [{"index": r.index, "healthy": r.healthy,
                     "draining": r.draining,
                     "inflight": len(r.outstanding),
                     "model": r.model,
                     "models": sorted(r.models) if r.models is not None
                     else None,
                     "last_error": r.last_error} for r in self._replicas]

    # -- request intake ----------------------------------------------------

    def submit(self, op: str, row, k: Optional[int] = None, *,
               seed: Optional[int] = None,
               model: Optional[str] = None,
               trace=None,
               target_se: Optional[float] = None,
               ess_floor: Optional[float] = None) -> Future:
        """Admit and dispatch one request row; returns the tier Future.

        ``trace`` is an optional
        :class:`~...telemetry.tracing.TraceContext`: every dispatch
        attempt then records an attempt-indexed child span
        (``router/attempt-<n>``, attrs: replica index) — a reroute after a
        replica failure shows up as attempt-1 (errored) + attempt-2, and
        the engine's pipeline-stage spans nest under the attempt that
        served the request.

        ``model`` names the tenant whose weights must serve the row; a
        model no replica declares is a synchronous ValueError (the typed
        ``bad_request`` upstream). ``None`` resolves to the fleet's
        ``default_model`` when every replica is model-labeled (a
        multi-model fleet must not let replica choice pick the weights).

        Raises synchronously for non-serving outcomes the caller must turn
        into typed responses: ValueError (bad payload/op/model, via the
        engine's own validation), :class:`TierOverloaded` (ceiling),
        :class:`EngineOverloaded` (every healthy replica shed), and
        :class:`ReplicaUnavailable` (no healthy replica / draining). Once
        a Future is returned, it ALWAYS completes — with a result, or with
        one of the typed errors above, or :class:`~..batcher.RequestTimeout`.
        """
        if not any(r.serves(op) for r in self._replicas):
            # typed bad_request, not 'unavailable': NO replica serves this
            # op even when fully healthy — the request is wrong, and a
            # retrying client must not burn its budget on it
            served = sorted(set().union(*(r.ops for r in self._replicas
                                          if r.ops is not None)))
            raise ValueError(f"unknown op {op!r}; this fleet serves "
                             f"{served}")
        model = self.resolve_model(model)
        tclass: Optional[str] = None
        if op in ADAPTIVE_OPS:
            # typed bad_request at the tier boundary, via the ONE shared
            # validator: the cap defaults to the fleet bound (resolved at
            # ADMISSION, so the request is fully specified before any
            # replica is chosen — results stay a pure function of it)
            if k is None:
                if self.k_max is None:
                    raise ValueError(
                        "score_adaptive needs an explicit k cap: no replica "
                        "in this fleet declares a k_max to default to")
                k = self.k_max
            target_se, ess_floor, k = validate_adaptive_target(
                target_se, ess_floor, k,
                self.k_max if self.k_max is not None else 2 ** 31 - 1)
            tclass = target_class(target_se, ess_floor)
        elif target_se is not None or ess_floor is not None:
            raise ValueError(
                f"target_se/ess_floor only apply to adaptive ops "
                f"({sorted(ADAPTIVE_OPS)}); {op!r} is fixed-k")
        elif k is not None:
            # typed bad_request at the tier boundary: an out-of-range k is
            # rejected HERE, before it can occupy the ceiling or reach a
            # replica as an internal error (the engines re-validate against
            # their own k_max; this is the fleet-wide bound)
            k = validate_k(k, self.k_max) if self.k_max is not None \
                else validate_k(k, 2 ** 31 - 1)
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise ReplicaUnavailable(
                    "serving tier is draining; no new requests")
            if self._outstanding_total >= self.max_outstanding:
                self._count("sheds")
                raise TierOverloaded(
                    f"tier ceiling hit ({self.max_outstanding} requests "
                    f"outstanding); shedding — retry with backoff")
            if seed is None:
                seed = self._seed_counter
                self._seed_counter = (self._seed_counter + 1) % (2 ** 31)
            self._ticket_counter += 1
            t = _Tracked(ticket=self._ticket_counter, op=op, row=row, k=k,
                         seed=int(seed), future=fut, model=model,
                         trace=trace,
                         target_se=target_se or 0.0,
                         ess_floor=ess_floor or 0.0, tclass=tclass,
                         work=self._estimated_work_locked(op, tclass, k))
            self._outstanding_total += 1
            self.registry.gauge("router/outstanding").set(
                self._outstanding_total)
        try:
            self._dispatch(t, exclude=set())
        except Exception as e:
            self._finalize(t, exc=e)
            raise
        self._count("routed")
        return fut

    def resolve_model(self, model: Optional[str]) -> Optional[str]:
        """The ONE model-resolution step: validate a named model against
        the fleet's declared capability set (unknown = ValueError, the
        typed ``bad_request`` — rejected before it can occupy the ceiling,
        because the one wrong outcome is the wrong weights answering), and
        pin a model-less request to ``default_model`` in an all-labeled
        fleet (so results never depend on which replica the balancer
        picked). The front end resolves BEFORE quota admission with this
        same call, so default-model traffic and explicitly-named traffic
        meter through one (client, model) lane."""
        if model is not None:
            return validate_model(model, self.models)
        if not self._has_unlabeled:
            return self.default_model
        return None

    # -- selection + dispatch ----------------------------------------------

    def _estimated_work_locked(self, op: str, tclass: Optional[str],
                               k: Optional[int]) -> float:
        """Estimated samples one request will draw (caller holds the lock).
        Fixed-k traffic costs exactly its k; adaptive traffic costs its
        (op, target-class) measured-k_used EWMA, capped at the request's
        own cap — before any measurement exists, the cap itself (the
        conservative prior: over-estimating new traffic spreads it, which
        is the safe failure mode)."""
        base = float(k) if k is not None else 1.0
        if tclass is None:
            return base
        est = self._work_ewma.get((op, tclass))
        return min(est, base) if est is not None else base

    def work_estimates(self) -> Dict[str, float]:
        """The live per-(op, target-class) measured-k_used EWMAs (stats /
        debugging surface; keys rendered ``op/class``)."""
        with self._lock:
            return {f"{op}/{tc}": est
                    for (op, tc), est in self._work_ewma.items()}

    def _note_k_used(self, t: _Tracked, result) -> None:
        """Fold an adaptive result's measured k_used column into its
        target class's work EWMA (the estimated-work dispatch weight)."""
        try:
            k_used = float(result[2])
        except Exception:
            return    # a fake replica returned a bare scalar: nothing to learn
        with self._lock:
            key = (t.op, t.tclass)
            prev = self._work_ewma.get(key)
            self._work_ewma[key] = k_used if prev is None else \
                prev + WORK_EWMA_ALPHA * (k_used - prev)

    def _wants_sharded(self, op: str, k: Optional[int]) -> bool:
        """Whether (op, k) belongs to the mesh-backed class: score above
        the threshold (k=None means the replica default — always small)."""
        return (op == "score" and self.large_k_threshold is not None
                and k is not None and k > self.large_k_threshold)

    def _eligible(self, r: _Replica, op: str, k: Optional[int],
                  model: Optional[str] = None) -> bool:
        """Capability filter under the classification policy: the replica
        must hold the request's model weights; large-k score needs a
        sharded replica; small traffic keeps the fast path (sharded
        replicas pick it up only in an all-sharded fleet); a replica never
        sees an op it does not serve or a k above its own bound."""
        if not r.serves(op) or not r.serves_model(model):
            return False
        if r.k_max is not None and k is not None and k > r.k_max:
            return False
        if op in ADAPTIVE_OPS:
            # the adaptive op only exists on replicas that register it (the
            # mesh-backed scorer; serves() above filtered) — the fast/
            # sharded k classification does not apply to a cap
            return True
        if self._wants_sharded(op, k):
            return r.sharded
        return not r.sharded or not self._has_fast

    def _select(self, group: Tuple,
                exclude: Set[int]) -> Optional[_Replica]:
        """Pick a replica (caller holds the lock): sticky group affinity
        while balanced, else least-LOAD with lowest-index tie-break — over
        the replicas eligible for this (model, op, k[, target-class])
        group. Load is the outstanding-request count for fixed-k traffic
        (the historical least-inflight policy, unchanged) and the summed
        estimated work — each outstanding request's ``work`` samples — for
        adaptive groups: ten easy rows (k_used ~ 50) must not count like
        ten k=5000 rows, or an easy-traffic replica would starve while its
        peer drowns."""
        model, op, k = group[:3]
        adaptive = len(group) > 3

        def load(r: _Replica) -> float:
            if adaptive:
                return sum(x.work for x in r.outstanding.values())
            return float(len(r.outstanding))

        cands = [r for r in self._replicas
                 if r.healthy and not r.draining and r.index not in exclude
                 and self._eligible(r, op, k, model)]
        if not cands:
            return None
        least = min(load(r) for r in cands)
        # the affinity slack is denominated in requests; for work-based
        # selection it scales by the group's per-request estimate so the
        # imbalance tolerance means "this many typical requests" either way
        slack = self.affinity_slack * (
            self._estimated_work_locked(op, group[3], k) if adaptive else 1.0)
        aff = self._affinity.get(group)
        if aff is not None:
            ar = self._by_index.get(aff)
            if ar is not None and ar.healthy and not ar.draining and \
                    aff not in exclude and \
                    self._eligible(ar, op, k, model) and \
                    load(ar) <= least + slack:
                self._count("affinity_hits")
                return ar
        chosen = min(cands, key=lambda r: (load(r), r.index))
        self._affinity[group] = chosen.index
        return chosen

    @staticmethod
    def _finish_span(t: _Tracked,
                     exc: Optional[BaseException] = None) -> None:
        """Close the request's CURRENT attempt span (no-op when untraced),
        stamping the typed error code when the attempt failed."""
        span, t.span = t.span, None
        if span is None:
            return
        if exc is None:
            span.finish()
        else:
            from iwae_replication_project_tpu.serving.frontend.protocol \
                import error_code_for
            span.finish(error=error_code_for(exc))

    def _dispatch(self, t: _Tracked, exclude: Set[int]) -> None:
        """Place `t` on a replica, walking past sheds and submit-time
        failures; raises the typed error when the fleet cannot take it."""
        from iwae_replication_project_tpu.telemetry.tracing import start_span
        any_shed = False
        # adaptive groups key affinity/selection by target class too: one
        # class's warm replica keeps its traffic, and the work-based load
        # comparison applies only within the adaptive family
        group = (t.model, t.op, t.k) if t.tclass is None \
            else (t.model, t.op, t.k, t.tclass)
        while True:
            with self._lock:
                r = self._select(group, exclude)
                if r is None:
                    break
                r.outstanding[t.ticket] = t
                t.replica_index = r.index
                t.attempts += 1
                t.t_dispatch = self._clock()
                t.submitting = True
                self._publish_replica(r)
            if t.trace is not None:
                # attempt-indexed child span: a rerouted request's tree
                # shows attempt-1 (errored) + attempt-2 side by side
                t.span = start_span(f"router/attempt-{t.attempts}",
                                    ctx=t.trace,
                                    attrs={"replica": r.index, "op": t.op})
            try:
                # chaos hook inside the try: an injected raise is attributed
                # to THIS replica (submit-time failure path), like a real one
                fault_point(SITE_ROUTER_DISPATCH, router=self,
                            replica=r.index, attempt=t.attempts)
                # outside the lock: engine.submit takes the engine's own
                # lock and may block briefly; the router lock never nests
                # around foreign blocking work. The model/trace ride along
                # only when resolved/supported — legacy fakes/engines keep
                # their signature.
                kw = {}
                if t.model is not None:
                    kw["model"] = t.model
                if t.span is not None and r.traces:
                    kw["trace"] = t.span.ctx()
                if t.tclass is not None:
                    # 0.0 means disabled at the wire/tracking layer; the
                    # engine's validator wants None for a disabled criterion
                    kw["target_se"] = t.target_se or None
                    kw["ess_floor"] = t.ess_floor or None
                ef = r.engine.submit(t.op, t.row, k=t.k, seed=t.seed, **kw)
            except EngineOverloaded as e:
                any_shed = True
                self._finish_span(t, e)
                self._unplace(t, r)
                exclude.add(r.index)
                continue
            except ValueError as e:
                self._finish_span(t, e)
                self._unplace(t, r)
                raise          # bad request: the engine's validation speaks
            except Exception as e:
                self._finish_span(t, e)
                self._unplace(t, r)
                self._replica_failed(r, e)
                exclude.add(r.index)
                continue
            ef.add_done_callback(
                lambda f, t=t, r=r: self._on_engine_done(t, r, f))
            with self._lock:
                t.submitting = False
                # the replica failed while we were in the submit window:
                # the drain skipped this request (we still owned it) — if
                # the engine future's callback hasn't claimed it either,
                # take the reroute ourselves, as a submit failure would
                abandoned = not r.healthy and \
                    r.outstanding.get(t.ticket) is t
                if abandoned:
                    del r.outstanding[t.ticket]
                    self._publish_replica(r)
            if not abandoned:
                return
            self._finish_span(t, RuntimeError(
                f"replica r{r.index} failed during submit"))
            exclude.add(r.index)
            continue
        if any_shed:
            self._count("sheds")
            raise EngineOverloaded(
                "every healthy replica shed the request (queues full); "
                "retry with backoff")
        raise ReplicaUnavailable("no healthy replica available")

    def _unplace(self, t: _Tracked, r: _Replica) -> None:
        with self._lock:
            r.outstanding.pop(t.ticket, None)
            t.submitting = False
            self._publish_replica(r)

    def _redispatch(self, t: _Tracked, exclude: Set[int],
                    shed_exc: Optional[BaseException] = None) -> None:
        """Callback-context dispatch: failures complete the future instead
        of raising (there is no caller to raise to). ``shed_exc`` marks a
        redispatch triggered by an async shed: if no peer can take the
        request, the shedding replica is FULL, not gone — the caller must
        see the original ``overloaded`` (back off and retry), never an
        ``unavailable`` that reads as fleet-down."""
        try:
            self._dispatch(t, exclude)
        except ReplicaUnavailable as e:
            self._finalize(t, exc=shed_exc if shed_exc is not None else e)
        except Exception as e:
            self._finalize(t, exc=e)

    # -- completion + failure paths ----------------------------------------

    # tolerant completion (the engine's contract): duplicate completions
    # from rerouted requests and caller-side cancellations must never kill
    # a completion callback
    _complete = staticmethod(complete_future)

    def _finalize(self, t: _Tracked, result=None, exc=None) -> None:
        """Complete the tier future (first completion wins) and retire the
        request from the outstanding count exactly once."""
        if exc is None:
            delivered = self._complete(t.future, result=result)
        else:
            delivered = self._complete(t.future, exc=exc)
        with self._lock:
            if t.finalized:
                return
            t.finalized = True
            self._outstanding_total -= 1
            self.registry.gauge("router/outstanding").set(
                self._outstanding_total)
            self._empty.notify_all()
        if delivered:
            self._count("completed" if exc is None else "errors")

    def _on_engine_done(self, t: _Tracked, r: _Replica, ef: Future) -> None:
        """Engine-future callback (runs on the replica's completion/dispatch
        thread). Success is delivered first-wins; an error from the replica
        currently owning the request marks it unhealthy, drains it, and
        reroutes; errors from abandoned (already-rerouted) dispatches are
        dropped — the live dispatch is authoritative."""
        with self._lock:
            owns = r.outstanding.get(t.ticket) is t
            if owns:
                del r.outstanding[t.ticket]
                self._publish_replica(r)
            finalized = t.finalized      # snapshot under the lock that
            # guards the flag; a completion landing after the snapshot is
            # deduplicated by _finalize itself
        exc = ef.exception()
        if exc is None:
            if owns:
                # the attempt that actually served the request closes its
                # span; an abandoned dispatch's late success must not touch
                # the live attempt's
                self._finish_span(t)
            result = ef.result()
            if t.tclass is not None:
                # measured k_used feeds the estimated-work weight this
                # class's NEXT requests dispatch under
                self._note_k_used(t, result)
            self._finalize(t, result=result)
            return
        if not owns or finalized:
            return
        self._finish_span(t, exc)
        if isinstance(exc, RequestTimeout):
            # the request's own deadline passed inside the replica: a typed
            # per-request outcome, not a replica failure — no reroute (its
            # deadline has already passed; a retry would serve it late)
            self._finalize(t, exc=exc)
            return
        if isinstance(exc, EngineOverloaded):
            # an async shed (remote replicas — frontend/remote.py — deliver
            # sheds through the future): the replica is FULL, not failed;
            # try its peers without marking it unhealthy
            if t.attempts <= len(self._replicas):
                self._count("reroutes")
                self._redispatch(t, exclude={r.index}, shed_exc=exc)
            else:
                self._finalize(t, exc=exc)
            return
        self._replica_failed(r, exc)
        if t.attempts <= len(self._replicas):
            self._count("reroutes")
            self._redispatch(t, exclude={r.index})
        else:
            self._finalize(t, exc=exc)

    def _replica_failed(self, r: _Replica, exc: BaseException) -> None:
        """Mark `r` unhealthy (once) and reroute everything it still holds."""
        with self._lock:
            was_healthy = r.healthy
            r.healthy = False
            r.last_error = f"{type(exc).__name__}: {exc}"
            # steal only fully-dispatched requests: one still in its submit
            # window belongs to the dispatching thread, which will observe
            # the unhealthy flag (or a submit failure / the errored engine
            # future) and reroute it itself — stealing it here would
            # double-dispatch it
            drained = [t for t in r.outstanding.values() if not t.submitting]
            for t in drained:
                del r.outstanding[t.ticket]
            self._publish_replica(r)
        if was_healthy:
            self._count("replica_failures")
        for other in drained:
            self._count("reroutes")
            # the failed replica's attempt dies with it — close its span
            # (errored) before the reroute opens the next attempt's
            self._finish_span(other, exc)
            self._redispatch(other, exclude={r.index})

    # -- health: stall detection + warm-probe re-admission ------------------

    def check_stalls(self, now: Optional[float] = None) -> int:
        """Drain any healthy replica whose OLDEST in-flight request has
        been outstanding longer than ``stall_deadline_s`` (a wedged engine
        backs up its window without ever raising). Returns the number of
        replicas drained. Called by the monitor thread; callable directly
        (tests drive it with a fake clock)."""
        now = self._clock() if now is None else now
        stalled: List[_Replica] = []
        with self._lock:
            for r in self._replicas:
                if r.healthy and r.outstanding:
                    oldest = min(t.t_dispatch
                                 for t in r.outstanding.values())
                    if now - oldest > self.stall_deadline_s:
                        stalled.append(r)
        for r in stalled:
            self._count("stall_drains")
            self._replica_failed(r, RequestTimeout(
                f"replica r{r.index} stalled: oldest in-flight request "
                f"exceeded {self.stall_deadline_s}s"))
        return len(stalled)

    def probe_unhealthy(self) -> int:
        """Warm-probe every unhealthy replica with one real request through
        its warmed program; a probe that completes in time re-admits the
        replica. Returns the number re-admitted."""
        with self._lock:
            # a draining replica is leaving the fleet: probing it back in
            # would hand it new work mid-removal
            down = [r for r in self._replicas
                    if not r.healthy and not r.draining]
            if not down:
                return 0
        readmitted = 0
        for r in down:
            self._count("probes")
            try:
                # probe each replica against ITS OWN contract (a mixed
                # fast/sharded fleet has different row dims, ops, and k
                # bounds per replica — a template probe would misfire)
                op = self.probe_op if r.serves(self.probe_op) \
                    else sorted(r.engine.row_dims)[0]
                probe_row = [0.0] * r.engine.row_dims[op]
                # a labeled replica is probed under its own model so the
                # probe exercises the same store entries live traffic hits
                kw = {"model": r.model} if r.model is not None else {}
                ef = r.engine.submit(op, probe_row,
                                     k=getattr(r.engine, "k", None), seed=0,
                                     **kw)
                ef.result(timeout=self.probe_timeout_s)
            except Exception:
                continue      # still down; next monitor tick retries
            with self._lock:
                r.healthy = True
                r.last_error = None
                self._publish_replica(r)
            self._count("probe_readmits")
            readmitted += 1
        return readmitted

    def start_monitor(self, interval_s: float = 0.25) -> None:
        """Background health loop: stall sweep + re-admission probes."""
        if self._monitor is not None:
            return
        self._monitor_stop.clear()

        def loop():
            while not self._monitor_stop.wait(interval_s):
                self.check_stalls()
                self.probe_unhealthy()

        self._monitor = threading.Thread(target=loop,
                                         name="iwae-tier-monitor",
                                         daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        if self._monitor is not None:
            self._monitor_stop.set()
            self._monitor.join()
            self._monitor = None

    # -- drain --------------------------------------------------------------

    def drain(self, timeout_s: float = 60.0) -> None:
        """Stop intake, flush every replica (``engine.stop()`` dispatches
        queued work and completes all in-flight futures), wait for the
        outstanding count to hit zero, and error-complete anything left
        (replicas that died mid-drain) with :class:`ReplicaUnavailable` —
        zero accepted requests are ever lost to a shutdown."""
        with self._lock:
            self._closed = True
        self.stop_monitor()
        for r in self._replicas:
            try:
                r.engine.stop()
            except Exception as e:
                self._replica_failed(r, e)
        deadline = self._clock() + timeout_s
        with self._empty:
            while self._outstanding_total > 0:
                remaining = deadline - self._clock()
                if remaining <= 0 or not self._empty.wait(
                        timeout=min(remaining, 0.25)):
                    if self._clock() >= deadline:
                        break
        leftovers: List[_Tracked] = []
        with self._lock:
            for r in self._replicas:
                leftovers.extend(r.outstanding.values())
                r.outstanding.clear()
                self._publish_replica(r)
        for t in leftovers:
            exc = ReplicaUnavailable(
                "tier drained before the request completed (replica lost "
                "mid-drain)")
            self._finish_span(t, exc)
            self._finalize(t, exc=exc)
