"""The TCP front end: admission control over the replica router.

``ServingTier`` binds a listening socket and speaks the JSON-lines
protocol (protocol.py): one daemon accept thread, one daemon thread per
connection, responses completed out-of-order via future callbacks under a
per-connection write lock. The request path per line is::

    parse -> control op? (info: answered here)
          -> global ceiling + per-client token bucket  (admission)
          -> one router.submit per payload row          (routing)
          -> response written when the last row lands   (completion)

Admission failures are typed *responses* (protocol.ERROR_CODES) — a
rejected request never drops the connection. Client identity stops at the
quota check: nothing client-derived flows into the router or the engines,
so per-client state can never reach an AOT program signature (the
multi-client zero-recompile test pins this).

Shutdown (:meth:`stop`) is a graceful drain: the listener closes first
(no new connections), the router drains every replica via
``engine.stop()``, each connection finishes writing its pending responses,
and only then do the sockets close — zero accepted requests go
unanswered. Requests arriving mid-drain get typed ``unavailable``
responses.

The tier is transport only: batching/padding/AOT policy live in the
replica engines, routing/health policy in router.py — this module never
imports jax and is fully exercised by tests over localhost sockets.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

from iwae_replication_project_tpu.serving.buckets import (
    validate_adaptive_target,
    validate_k,
    validate_precision,
)
from iwae_replication_project_tpu.serving.faults import (
    SITE_TIER_WRITE,
    fault_point,
)
from iwae_replication_project_tpu.serving.frontend import protocol
from iwae_replication_project_tpu.serving.frontend.quotas import (
    ClientQuotas,
    QuotaExceeded,
    QuotaPolicy,
)
from iwae_replication_project_tpu.serving.frontend.router import ReplicaRouter

__all__ = ["ServingTier"]


class _Pending:
    """One in-flight request's per-row completion state (guarded by the
    owning connection's lock)."""

    __slots__ = ("req_id", "results", "remaining", "error", "op", "model",
                 "t_start", "span")

    def __init__(self, req_id: Any, n_rows: int, op: Optional[str] = None,
                 model: Optional[str] = None, t_start: float = 0.0,
                 span=None):
        self.req_id = req_id
        self.results: List[Any] = [None] * n_rows
        self.remaining = n_rows
        self.error: Optional[BaseException] = None
        # observability state: the request's op/model/admission time (SLO
        # accounting) and its tier request span (trace tree root-or-child)
        self.op = op
        self.model = model
        self.t_start = t_start
        self.span = span


class _Connection:
    """One client connection: a blocking read loop plus callback-driven
    response writes. All mutable state (pending map, closed flag) and the
    socket write side live under ONE lock; the read loop never holds it."""

    def __init__(self, tier: "ServingTier", sock: socket.socket, peer):
        self._tier = tier
        self._sock = sock
        self._peer = peer
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._pending = 0
        self._dead = False

    # -- writes (any thread) ------------------------------------------------

    def _write(self, obj: Dict[str, Any]) -> None:
        data = protocol.encode_line(obj)
        with self._lock:
            if self._dead:
                return
            # chaos hook, under the write lock so injected garbage/drops
            # are frame-aligned (deterministic runs); actions here touch
            # the socket only and never raise — the sendall below then
            # fails exactly like a real peer reset
            fault_point(SITE_TIER_WRITE, sock=self._sock, conn=self)
            try:
                self._sock.sendall(data)  # iwaelint: disable=blocking-call-under-lock -- the per-connection write lock IS the frame serializer: concurrent responses interleaving on one socket would corrupt the line protocol; a dead peer fails fast with OSError rather than stalling
            except OSError:
                # the client vanished; the response was produced — nothing
                # to deliver it to. Reads will fail and retire the loop.
                # (no swallowed-exception waiver needed: the leak pass
                # proves _write acquisition-free, so the drop cannot leak)
                self._dead = True

    def _respond_error(self, req_id: Any, exc: BaseException) -> None:
        code = protocol.error_code_for(exc)
        # machine-readable back-off: the exception's own computed wait
        # (QuotaExceeded carries the exact refill time) beats the tier's
        # configured shed hint, which beats nothing
        hint = getattr(exc, "retry_after_s", None)
        if hint is None and code == "overloaded":
            hint = self._tier.shed_retry_after_s
        self._write(protocol.error_response(
            req_id, code, f"{type(exc).__name__}: {exc}",
            retry_after_s=hint))

    # -- request handling (read-loop thread + future callbacks) -------------

    def _row_done(self, pending: _Pending, i: int, fut) -> None:
        # the callback fires on an already-completed future, so exception()
        # and result() return immediately — but they are *blocking* calls
        # by contract, so both stay outside the connection lock
        exc = fut.exception()
        r = fut.result() if exc is None else None
        with self._lock:
            if exc is not None and pending.error is None:
                pending.error = exc
            elif exc is None:
                pending.results[i] = r.tolist() if hasattr(r, "tolist") else r
            pending.remaining -= 1
            finished = pending.remaining == 0
        if not finished:
            return
        code = None
        if pending.error is not None:
            code = protocol.error_code_for(pending.error)
            self._respond_error(pending.req_id, pending.error)
        else:
            self._write(protocol.ok_response(pending.req_id, pending.results))
        # observability AFTER the response write: the span's duration and
        # the SLO latency both cover the full request, response included
        self._tier.observe_request(pending.op, pending.model,
                                   pending.t_start, code, pending.span)
        with self._lock:
            self._pending -= 1
            self._idle.notify_all()

    def _handle(self, line: bytes) -> None:
        try:
            obj = protocol.decode_line(line)
        except protocol.ProtocolError as e:
            self._respond_error(None, e)
            return
        req_id = obj.get("id")
        op = obj.get("op")
        if op in protocol.CONTROL_OPS:
            doc = {"info": self._tier.info,
                   "stats": self._tier.stats,
                   "slo": self._tier.slo_doc}[op]() \
                if op != "traces" else self._tier.traces_doc(obj)
            self._write(protocol.ok_response(req_id, doc))
            return
        if op in protocol.JOB_OPS:
            # the bulk offline lane (jobs.py): job admission/status are
            # answered synchronously — the job's ROWS are pumped through
            # the router in the background, below interactive traffic.
            # Malformed job docs are typed bad_request responses and the
            # connection survives, like every other request.
            try:
                doc = self._tier.job_doc(op, obj)
            except Exception as e:
                self._respond_error(req_id, e)
                return
            self._write(protocol.ok_response(req_id, doc))
            return
        t_start = self._tier.clock()
        span = None
        model = None
        try:
            # trace context first: mint or accept (tier tracing on), but
            # VALIDATE unconditionally — a malformed/oversized trace field
            # is this request's typed bad_request whether or not the tier
            # records traces, and the connection survives it either way
            span = self._tier.open_request_span(obj.get("trace"), op,
                                                t_start)
            rows = _payload_rows(obj)
            client = obj.get("client")
            if client is not None and not isinstance(client, str):
                raise protocol.ProtocolError(
                    f"'client' must be a string, got "
                    f"{type(client).__name__}")
            model = obj.get("model")
            # the protocol surface of the typed unknown-model contract —
            # and the quota-lane resolution: a model-less request in an
            # all-labeled fleet resolves to the default model HERE, before
            # admission, so default and explicitly-named traffic meter
            # through the SAME (client, model) bucket
            try:
                model = self._tier.router.resolve_model(model)
            except ValueError as e:
                raise protocol.ProtocolError(str(e)) from None
            precision = obj.get("precision")
            if precision is not None:
                # the wire surface of the typed unknown-precision contract
                # (ISSUE 16): validate the vocabulary via the ONE shared
                # validator, then assert the fleet actually serves this
                # model at the asked-for numerics — a mismatch is this
                # request's typed bad_request, NEVER a silent serve at
                # whatever precision happens to be resident
                try:
                    validate_precision(precision)
                except ValueError as e:
                    raise protocol.ProtocolError(str(e)) from None
                held = self._tier.precisions_for(model)
                if precision not in held:
                    raise protocol.ProtocolError(
                        f"model {model!r} is not served at precision "
                        f"{precision!r} here; this fleet holds "
                        f"{sorted(held)}")
            k = obj.get("k")
            if k is not None:
                # the protocol surface of the typed out-of-range-k
                # contract: the ONE shared validator (buckets.validate_k,
                # type/positivity here; the fleet k_max range is the
                # router's synchronous ValueError — same typed code)
                try:
                    k = validate_k(k, 2 ** 31 - 1)
                except ValueError as e:
                    raise protocol.ProtocolError(str(e)) from None
            target_se = obj.get("target_se")
            ess_floor = obj.get("ess_floor")
            if target_se is not None or ess_floor is not None:
                # the wire surface of the adaptive-target contract: the ONE
                # shared validator (buckets.validate_adaptive_target —
                # type/positivity/reachability here; the fleet k_max cap
                # default is the router's synchronous ValueError, same
                # typed code). A malformed target is THIS request's
                # bad_request and the connection survives.
                try:
                    validate_adaptive_target(
                        target_se, ess_floor,
                        k if k is not None else 2 ** 31 - 1, 2 ** 31 - 1)
                except ValueError as e:
                    raise protocol.ProtocolError(str(e)) from None
            seed = obj.get("seed")
            if seed is not None:
                # the fleet-composition hook (protocol.py): one seed names
                # one row's RNG stream, so it only makes sense row-wise
                if not isinstance(seed, int) or isinstance(seed, bool):
                    raise protocol.ProtocolError(
                        f"'seed' must be an integer, got "
                        f"{type(seed).__name__}")
                if not 0 <= seed < 2 ** 31:
                    # seeds ride the engines' int32 seed tensor; an
                    # out-of-range value must die HERE as this client's
                    # bad_request, not inside a replica where it would
                    # error a whole coalesced batch and read as a
                    # replica failure
                    raise protocol.ProtocolError(
                        f"'seed' must be in [0, 2**31), got {seed}")
                if len(rows) != 1:
                    raise protocol.ProtocolError(
                        "'seed' applies to single-row payloads only")
            if span is not None:
                ann: Dict[str, Any] = {}
                if k is not None:
                    ann["k"] = k
                if target_se is not None:
                    ann["target_se"] = target_se
                if ess_floor is not None:
                    ann["ess_floor"] = ess_floor
                span.annotate(rows=len(rows), model=model, **ann)
            t_admit = self._tier.clock()
            self._tier.admit(client, len(rows), model=model)
            if span is not None:
                # quota admission as a timed child span (pre-timed emit:
                # zero tracing work inside the admission path itself)
                from iwae_replication_project_tpu.telemetry.tracing import (
                    emit_span)
                emit_span(span.ctx(), "tier/admit", t_admit,
                          self._tier.clock())
            pending = _Pending(req_id, len(rows), op=op, model=model,
                               t_start=t_start, span=span)
            with self._lock:
                self._pending += 1
            futures = []
            try:
                ctx = span.ctx() if span is not None else None
                kw: Dict[str, Any] = {}
                # forward adaptive targets only when the client set them —
                # the plain-op call shape is unchanged (fake routers in
                # tests keep their historical signatures)
                if target_se is not None:
                    kw["target_se"] = target_se
                if ess_floor is not None:
                    kw["ess_floor"] = ess_floor
                for row in rows:
                    futures.append(
                        self._tier.router.submit(op, row, k=k, seed=seed,
                                                 model=model, trace=ctx,
                                                 **kw))
            except Exception:
                # partial admission: rows already routed complete and are
                # discarded; the request as a unit gets the typed error —
                # and its full quota cost back (the client pays for served
                # requests, not for shed/rejected ones)
                self._tier.refund(client, len(rows), model=model)
                with self._lock:
                    self._pending -= 1
                    self._idle.notify_all()
                raise
            for i, f in enumerate(futures):
                f.add_done_callback(
                    lambda fut, i=i, p=pending: self._row_done(p, i, fut))
        except Exception as e:
            self._respond_error(req_id, e)
            self._tier.observe_request(op, model, t_start,
                                       protocol.error_code_for(e), span)

    def serve(self) -> None:
        """The read loop (own daemon thread): handle lines until EOF or a
        socket error, then wait for pending responses to flush and close."""
        reader = protocol.LineReader(self._sock)
        try:
            while True:
                try:
                    line = reader.next_line()
                except (protocol.ProtocolError, OSError):
                    break
                if line is None:
                    break
                if line.strip():
                    self._handle(line)
        finally:
            self.flush(timeout_s=60.0)
            self.close()
            self._tier._forget(self)

    def flush(self, timeout_s: float) -> bool:
        """Wait until every accepted request on this connection has been
        answered (the drain contract). Returns False on timeout."""
        with self._idle:
            return self._idle.wait_for(lambda: self._pending == 0,
                                       timeout=timeout_s)

    def close(self) -> None:
        with self._lock:
            self._dead = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            # best-effort shutdown of a possibly already-dead peer socket;
            # close() below is the real teardown (waiver retired: the leak
            # pass proves close() acquisition-free)
            pass
        self._sock.close()


def _engine_counters(engine) -> Dict[str, Any]:
    """One replica engine's counter snapshot for :meth:`ServingTier.stats`
    (fakes without a metrics registry report empty). Reads the counter
    block only — the full ``snapshot()`` would rebuild the process-wide
    store section once per replica just to discard it."""
    metrics = getattr(engine, "metrics", None)
    if metrics is None:
        return {}
    if hasattr(metrics, "counters"):
        return dict(metrics.counters())
    return dict(metrics.snapshot()["counters"])


def _payload_rows(obj: Dict[str, Any]) -> List[Any]:
    """The request's ``x`` as a list of rows (single-row payloads wrap)."""
    x = obj.get("x")
    if not isinstance(x, (list, tuple)) or len(x) == 0:
        raise protocol.ProtocolError(
            "'x' must be a non-empty row or list of rows")
    if isinstance(x[0], (list, tuple)):
        return list(x)
    return [x]


class ServingTier:
    """N engine replicas + router + quotas behind one TCP endpoint.

    ``engines`` are ready-made replicas over shared weights (the CLI and
    :func:`build_tier_engines` construct them; tests pass fakes). The tier
    owns their lifecycle from :meth:`start` to :meth:`stop`. ``port=0``
    binds an ephemeral port (read :attr:`port` after ``start``).
    """

    def __init__(self, engines: Sequence, *,
                 quota: Optional[QuotaPolicy] = None,
                 max_outstanding: int = 4096,
                 host: str = "127.0.0.1", port: int = 0,
                 affinity_slack: int = 2,
                 stall_deadline_s: float = 30.0,
                 probe_timeout_s: float = 5.0,
                 monitor_interval_s: float = 0.25,
                 large_k_threshold: Optional[int] = None,
                 shed_retry_after_s: float = 0.05,
                 bulk_headroom: Optional[int] = None,
                 registry=None, tracing: bool = True, recorder=None,
                 slo=None):
        self.router = ReplicaRouter(
            engines, max_outstanding=max_outstanding,
            affinity_slack=affinity_slack,
            stall_deadline_s=stall_deadline_s,
            probe_timeout_s=probe_timeout_s,
            large_k_threshold=large_k_threshold, registry=registry)
        self.registry = self.router.registry
        self.quotas = ClientQuotas(quota)
        self._quota = quota
        self.clock = time.monotonic
        # request tracing (telemetry/tracing.py): ``tracing=True`` (the
        # default) mints a trace per request — or joins one the client
        # supplied — and lands completed trees in ``recorder`` (the
        # process-default flight recorder unless injected). ``False``
        # disables minting/recording; the ``trace`` field is still
        # VALIDATED either way (protocol contract: malformed = typed
        # bad_request, connection survives).
        if tracing:
            from iwae_replication_project_tpu.telemetry.tracing import (
                get_recorder)
            self.recorder = recorder if recorder is not None \
                else get_recorder()
        else:
            self.recorder = None
        # SLO burn-rate accounting (telemetry/slo.py): None = a default
        # monitor on the router registry (its gauges share the fleet's
        # Prometheus page); pass an SLOMonitor to set objectives, or
        # ``False`` to disable
        if slo is None:
            from iwae_replication_project_tpu.telemetry.slo import SLOMonitor
            self.slo: Optional[object] = SLOMonitor(registry=self.registry)
        else:
            self.slo = slo if slo is not False else None
        #: the ``retry_after_s`` hint stamped on ``overloaded`` responses
        #: that carry no exact wait of their own (queue-shed recovery time
        #: is unknowable server-side; this is the tier's suggested pause)
        self.shed_retry_after_s = float(shed_retry_after_s)
        # the bulk offline lane (jobs.py): dataset-sized jobs pumped
        # through the router below interactive traffic — the pump fills
        # idle capacity only up to `bulk_headroom` outstanding requests
        # (default: a quarter of the admission ceiling), yielding the rest
        # to latency traffic
        from iwae_replication_project_tpu.serving.frontend.jobs import (
            BulkJobManager)
        self.jobs = BulkJobManager(
            self.router, admit=self.admit, refund=self.refund,
            headroom=(bulk_headroom if bulk_headroom is not None
                      else max(1, max_outstanding // 4)),
            registry=self.registry)
        self._host = host
        self._requested_port = port
        self._monitor_interval_s = monitor_interval_s
        self._lock = threading.Lock()
        self._conns: set = set()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    # -- admission ----------------------------------------------------------

    def admit(self, client: Optional[str], cost: int,
              model: Optional[str] = None) -> None:
        """Per-(client, model) token-bucket admission (the router applies
        the global ceiling itself at submit). Raises
        :class:`QuotaExceeded` — one tenant's model cannot starve another's
        budget under the same client id."""
        try:
            self.quotas.admit(client, cost, model=model)
        except QuotaExceeded:
            self.registry.counter("router/quota_rejections").inc()
            raise

    def refund(self, client: Optional[str], cost: int,
               model: Optional[str] = None) -> None:
        """Return an admitted request's tokens when routing rejected it
        (ceiling/shed/unavailable): the quota meters served work, so a
        request whose response is a typed routing error costs nothing."""
        self.quotas.refund(client, cost, model=model)

    # -- observability (tracing + SLO) --------------------------------------

    def open_request_span(self, trace_field, op, t_start: float):
        """The request's ``tier/request`` span: minted fresh, or joined to
        the wire ``trace`` context (fleet-of-fleets). Returns None when the
        tier does not trace — but the field is VALIDATED regardless, so a
        malformed trace is a typed ``bad_request`` on every tier."""
        from iwae_replication_project_tpu.telemetry import tracing

        trace_id = parent = None
        if trace_field is not None:
            try:
                trace_id, parent = tracing.parse_wire_trace(trace_field)
            except ValueError as e:
                raise protocol.ProtocolError(str(e)) from None
        if self.recorder is None:
            return None
        return tracing.start_span(
            "tier/request", recorder=self.recorder, trace_id=trace_id,
            parent_id=parent, t_start=t_start,
            attrs={"op": op if isinstance(op, str) else repr(op)})

    def observe_request(self, op, model, t_start: float,
                        error_code: Optional[str], span) -> None:
        """One finished (answered) request's observability fan-out: close
        its tier span and account it against the (model, op) SLO.
        ``bad_request`` traffic is traced but never SLO-observed — the
        request is the client's fault, and a garbage op name must not mint
        burn-rate gauges."""
        if span is not None:
            span.finish(error=error_code)
        if self.slo is None or error_code == "bad_request":
            return
        if not isinstance(op, str) or not self.router.serves_op(op):
            return
        self.slo.observe(op, self.clock() - t_start, model=model,
                         error_code=error_code)

    def precisions_for(self, model: Optional[str]) -> set:
        """The serving precision policies this fleet holds for `model`
        (every replica, for ``None`` — the unlabeled single-model fleet).
        An engine with no policy serves exact fp32, so it reads as
        ``"fp32"`` here: a client asserting ``precision: "fp32"`` against
        a legacy fleet is satisfied, not rejected."""
        out = set()
        for e in self.router.engines:
            if model is not None and getattr(e, "model", None) != model:
                continue
            out.add(getattr(e, "precision", None) or "fp32")
        return out

    def traces_doc(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        """The ``{"op": "traces"}`` control response: the flight recorder's
        retained traces (``limit``/``trace_id`` filters), as raw documents
        (default) or one Chrome trace-event object (``format: "chrome"``).
        A tier without tracing answers with empty state, not an error."""
        from iwae_replication_project_tpu.telemetry.tracing import (
            chrome_trace_events)

        limit = obj.get("limit")
        if not isinstance(limit, int) or isinstance(limit, bool):
            limit = None
        trace_id = obj.get("trace_id")
        if not isinstance(trace_id, str):
            trace_id = None
        if self.recorder is None:
            docs, stats = [], None
        else:
            docs = self.recorder.traces(limit=limit, trace_id=trace_id)
            stats = self.recorder.stats()
        if obj.get("format") == "chrome":
            return chrome_trace_events(docs)
        return {"stats": stats, "traces": docs}

    def job_doc(self, op: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Answer one bulk-lane wire op (protocol.JOB_OPS): job admission
        or status. Malformed docs raise ValueError — the connection's
        handler maps it to a typed ``bad_request`` response."""
        if op == "submit_job":
            return self.jobs.submit(obj)
        return self.jobs.status(obj)

    def slo_doc(self) -> Dict[str, Any]:
        """The ``{"op": "slo"}`` control response: the SLOMonitor's
        burn-rate + objective snapshot (telemetry/slo.py schema) — the
        scaling signal the fleet autoscaler (and a fleet-of-fleets parent,
        via :meth:`RemoteEngine.slo`) reads as JSON instead of scraping
        the Prometheus text page. A tier with SLO accounting disabled
        answers with empty state, not an error (same contract as
        :meth:`traces_doc`)."""
        if self.slo is None:
            return {"enabled": False, "slo": {}}
        return {"enabled": True, "slo": self.slo.snapshot()}

    # -- info ---------------------------------------------------------------

    def info(self) -> Dict[str, Any]:
        """The ``{"op": "info"}`` control response: what clients need to
        size payloads and pace themselves. Ops/dims are the UNION over the
        fleet (a mixed fast + sharded tier serves the union; the router
        keeps each request on replicas that serve its op)."""
        row_dims: Dict[str, int] = {}
        for e in self.router.engines:
            row_dims.update(getattr(e, "row_dims", {}))
        engines = self.router.engines
        sharded = [e for e in engines if getattr(e, "sharded", False)]
        # per-class templates: buckets/k describe the class that actually
        # serves the request (a mixed tier has two ladders; engines[0]
        # alone would misdescribe one class or the other)
        fast_t = next((e for e in engines
                       if not getattr(e, "sharded", False)),
                      engines[0])
        # per-model capability sub-docs (the multi-tenant contract clients
        # and RemoteEngine proxies read): which models this fleet holds,
        # each with its own ops/dims/k — empty for an unlabeled fleet
        models: Dict[str, Any] = {}
        for e in engines:
            m = getattr(e, "model", None)
            if m is None:
                continue
            doc = models.setdefault(
                m, {"ops": set(), "row_dims": {},
                    "k": getattr(e, "k", None),
                    "k_max": getattr(e, "k_max", None),
                    # the serving precision policy of this tenant's
                    # replicas (None-policy engines serve exact fp32)
                    "precision": getattr(e, "precision", None) or "fp32",
                    "replicas": 0})
            doc["ops"].update(getattr(e, "row_dims", {}))
            doc["row_dims"].update(getattr(e, "row_dims", {}))
            doc["replicas"] += 1
            if getattr(e, "k_max", None) is not None and \
                    doc["k_max"] is not None:
                doc["k_max"] = max(doc["k_max"], e.k_max)
        for doc in models.values():
            doc["ops"] = sorted(doc["ops"])
        return {
            "ops": sorted(row_dims),
            "row_dims": row_dims,
            # which ops take accuracy targets (target_se/ess_floor) — the
            # union over the fleet, like ops/row_dims
            "adaptive_ops": sorted({op for e in engines
                                    for op in getattr(e, "_ADAPTIVE_OPS",
                                                      ())}),
            "models": models,
            "default_model": self.router.default_model,
            "k": getattr(fast_t, "k", None),
            "k_max": self.router.k_max,
            "large_k_threshold": self.router.large_k_threshold,
            "sharded_replicas": len(sharded),
            "sharded": ({
                "buckets": list(getattr(getattr(sharded[0], "ladder",
                                                None), "buckets", ())),
                "k_chunk": sharded[0].menu.k_chunk,
                "k_max": sharded[0].k_max,
                "k": getattr(sharded[0], "k", None),
            } if sharded and hasattr(sharded[0], "menu") else None),
            "buckets": list(getattr(getattr(fast_t, "ladder", None),
                                    "buckets", ())),
            "replicas": len(engines),
            "max_outstanding": self.router.max_outstanding,
            "quota": ({"rate": self._quota.rate, "burst": self._quota.burst}
                      if self._quota is not None else None),
        }

    def stats(self) -> Dict[str, Any]:
        """The ``{"op": "stats"}`` control response: live router counters
        and gauges plus each replica engine's own counter snapshot — the
        over-the-wire view the bench's zero-recompile proof and the smoke's
        failure accounting read (same numbers the CLI prints at shutdown)."""
        snap = self.registry.snapshot()
        # the process executable store's counters ride the stats document
        # so the multi-model smoke/bench read hit/miss/eviction/readmit
        # accounting over the wire (import deferred to the call: the store
        # module is jax-free, but the tier's import surface stays minimal)
        from iwae_replication_project_tpu.utils.compile_cache import (
            store_stats)
        store = store_stats()
        return {
            "router": {name: v for name, v in snap["counters"].items()
                       if name.startswith("router/")},
            "gauges": {name: v for name, v in snap["gauges"].items()
                       if name.startswith("router/")},
            # the router's learned per-(op, target-class) k_used EWMAs —
            # what least-estimated-work dispatch weighs adaptive traffic by
            "work_estimates": self.router.work_estimates(),
            "jobs": self.jobs.jobs_doc(),
            "store": store,
            "replicas": self.router.replica_states(),
            "engines": [_engine_counters(e) for e in self.router.engines],
        }

    # -- lifecycle ----------------------------------------------------------

    def warmup(self, ops: Iterable[str] = ("score", "encode", "decode",
                                           "score_adaptive"),
               ks=None) -> Dict[str, float]:
        """Warm every replica's bucket ladder (AOT pre-compile); replicas
        share the process AOT registry in-process, so replica 2+ warmups
        are registry hits. Each replica warms only the ops it serves (a
        sharded replica's menu is score-only). Returns summed stats."""
        total: Dict[str, float] = {}
        for e in self.router.engines:
            mine = tuple(op for op in ops
                         if op in getattr(e, "row_dims", {}))
            if not mine:
                continue
            w = e.warmup(ops=mine, ks=ks)
            for key, v in w.items():
                total[key] = total.get(key, 0.0) + v
        return total

    def start(self) -> "ServingTier":
        """Start replicas, the health monitor, and the accept loop."""
        for e in self.router.engines:
            e.start()
        self.router.start_monitor(self._monitor_interval_s)
        self.jobs.start()
        if self._listener is None:
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind((self._host, self._requested_port))
            lst.listen(128)
            # a plain close() does not reliably wake a thread blocked in
            # accept() on Linux; a short accept timeout lets the loop poll
            # the stopping flag instead
            lst.settimeout(0.2)
            self._listener = lst
            self._stopping.clear()
            self._accept_thread = threading.Thread(
                target=self._accept_loop, args=(lst,),
                name="iwae-tier-accept", daemon=True)
            self._accept_thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        return self._listener.getsockname()[1] if self._listener else None

    def _accept_loop(self, listener: socket.socket) -> None:
        while not self._stopping.is_set():
            try:
                sock, peer = listener.accept()
            except socket.timeout:
                continue        # poll the stopping flag
            except OSError:
                return          # listener closed: shutdown
            sock.settimeout(None)   # connections block; accept timeout off
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Connection(self, sock, peer)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=conn.serve,
                             name=f"iwae-tier-conn-{peer[1]}",
                             daemon=True).start()

    def _forget(self, conn: _Connection) -> None:
        with self._lock:
            self._conns.discard(conn)

    def stop(self, timeout_s: float = 60.0) -> None:
        """Graceful drain: stop accepting, flush all replicas, answer
        everything, then close connections. Idempotent."""
        self._stopping.set()
        listener = self._listener
        self._listener = None
        if listener is not None:
            listener.close()
        if self._accept_thread is not None:
            self._accept_thread.join()
            self._accept_thread = None
        # the bulk pump stops BEFORE the drain: its already-submitted rows
        # complete below; unsubmitted rows stay unsubmitted — exactly the
        # interruption the job checkpoint/resume contract covers
        self.jobs.stop()
        # drain the fleet: every tier future completes (result or typed
        # error) before this returns
        self.router.drain(timeout_s=timeout_s)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.flush(timeout_s=timeout_s)
            c.close()
        with self._lock:
            self._conns.clear()
