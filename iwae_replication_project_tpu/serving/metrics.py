"""Serving observability: engine counters + per-bucket latency histograms,
backed by the telemetry registry (telemetry/registry.py).

This module used to carry its own log-spaced histogram/percentile code; that
implementation now lives ONCE in :class:`~..telemetry.registry.Histogram`
and :class:`ServingMetrics` is a thin schema adapter over a
:class:`~..telemetry.registry.MetricRegistry` — per-engine by default (two
engines must not share counters), injectable for tests or co-export. The
``iwae-serve`` CLI serves the same registry as a Prometheus text page
(``--metrics-port``; telemetry/exporters.py).

Export surfaces, unchanged schema:

* :meth:`ServingMetrics.snapshot` — the nested JSON document (CLI
  ``--stats``, bench artifacts);
* :meth:`ServingMetrics.flat` — flat ``str -> float`` rows for
  ``MetricsLogger`` (JSONL + TensorBoard stamping, same pipeline the
  experiment driver's per-stage rows ride).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from iwae_replication_project_tpu.telemetry.registry import (
    Histogram,
    MetricRegistry,
)

#: registry namespaces for the per-(op, bucket) histograms: total observed
#: latency plus its pipeline split — queue_wait (submit -> device enqueue:
#: coalescing policy + in-flight backpressure) and device_wait (enqueue ->
#: fetched: device compute + D2H). queue_wait + device_wait ~= latency.
_LAT = "latency/"
_QW = "queue_wait/"
_DW = "device_wait/"


class LatencyHistogram(Histogram):
    """Seconds-unit view of the shared log-spaced histogram: same bins
    (8/decade, 1 us .. 1000 s), summary keys carry the ``_s`` suffix the
    serving snapshot schema pins."""

    def __init__(self, lock=None):
        super().__init__(lock)

    def summary(self) -> Dict[str, Optional[float]]:
        s = super().summary()
        return {"count": s["count"], "mean_s": s["mean"],
                "total_s": s["total"], "p50_s": s["p50"],
                "p95_s": s["p95"], "p99_s": s["p99"]}


class ServingMetrics:
    """Thread-safe engine counters + per-(op, bucket) latency histograms.

    ``model`` labels a multi-tenant engine's histograms: latency keys become
    ``latency/<model>/<op>/b<bucket>`` (flat/Prometheus alike) so one
    exposition page over a zoo-serving tier separates tenants. ``None`` (the
    single-model default) keeps the historical unlabeled schema byte-for-
    byte. ``precision`` (ISSUE 16) adds the serving-precision dimension the
    same way: a non-None policy suffixes the tenant label
    (``<model>@<precision>``, matching the engine's executable-store label),
    stamps each kernel gate outcome with its precision, and adds a
    ``precision`` key to snapshots — while ``None`` keeps every schema, key,
    and byte identical to a pre-precision fleet (the fp32-only contract
    pinned by tests/test_telemetry.py). Snapshots additionally carry the
    process-wide executable-store section (``store``:
    hits/misses/evictions/demotions/readmits, resident-vs-budget bytes —
    utils/compile_cache.store_stats())."""

    COUNTERS = ("submitted", "completed", "timeouts", "shed", "errors",
                "dispatches", "real_rows", "padded_rows",
                "aot_hits", "aot_misses", "recompiles")

    #: the store keys exported flat (floats only; budget may be None and is
    #: flat-exported only when set)
    STORE_FLAT = ("hits", "misses", "evictions", "demotions", "readmits",
                  "resident_bytes", "entries")

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 model: Optional[str] = None,
                 precision: Optional[str] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self.model = model
        self.precision = precision
        # pre-register so snapshots carry every counter from the first call
        for name in self.COUNTERS:
            self.registry.counter(name)
        self._queue_depth = self.registry.gauge("queue_depth")
        self._inflight = self.registry.gauge("inflight")
        # per-(op, bucket, k) hot-loop selection stamps (engine._kernel_for
        # outcomes): the path code rides a registry gauge (Prometheus page),
        # the tile — a non-scalar — rides this dict for snapshot()/flat().
        # Written by the dispatcher thread, read by scrapes -> own lock.
        self._kernel_lock = threading.Lock()
        self._kernel: Dict[str, dict] = {}

    def count(self, name: str, n: float = 1) -> None:
        self.registry.counter(name).inc(n)

    def counters(self) -> Dict[str, float]:
        """Just the engine counter block of :meth:`snapshot` — what the
        tier's wire ``stats`` op reads per replica, without building the
        process-wide store section N times over."""
        snap = self.registry.snapshot()
        return {k: snap["counters"].get(k, 0) for k in self.COUNTERS}

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(int(depth))

    @property
    def queue_depth(self) -> int:
        return int(self._queue_depth.value)

    def set_inflight(self, n: int) -> None:
        """Batches currently between device enqueue and future completion
        (the pipeline's bounded window occupancy; 0 when idle or serial)."""
        self._inflight.set(int(n))

    @property
    def inflight(self) -> int:
        return int(self._inflight.value)

    def set_kernel(self, op: str, k: int, bucket: int, path_code: int,
                   path: str, tile: Optional[Tuple[int, int]]) -> None:
        """Stamp the hot-loop selection of one (op, k, bucket) dispatch
        config — recomputed per row config by the engine's gate (PR 6
        contract: never trace-order state). The code lands on a
        ``kernel/<op>/b<bucket>/k<k>`` gauge (scraped on the Prometheus
        page like any scalar); the tile joins it in snapshot()/flat()."""
        key = f"{op}/b{bucket}/k{k}"
        if self.precision:
            # the precision dimension of the kernel stamp (ISSUE 16):
            # fp32-only fleets (precision None) keep the historical key
            key = f"{key}/{self.precision}"
        self.registry.gauge(f"kernel/{key}").set(float(path_code))
        with self._kernel_lock:
            rec = {
                "path_code": int(path_code), "path": str(path),
                "tile": list(tile) if tile is not None else None,
            }
            if self.precision:
                rec["precision"] = str(self.precision)
            self._kernel[key] = rec

    def _label(self) -> Optional[str]:
        """The tenant label of this engine's histogram keys: the model
        name, ``@precision``-suffixed when a precision policy is set —
        the SAME composite the engine keys its executable-store entries
        under, so the latency split and the store residency split name
        tenants identically."""
        if self.precision:
            return f"{self.model or 'default'}@{self.precision}"
        return self.model

    def _hist_key(self, op: str, bucket: int) -> str:
        """The per-(op, bucket) histogram key, tenant-labeled when this
        engine serves a named model and/or a precision policy."""
        label = self._label()
        return f"{label}/{op}/b{bucket}" if label else f"{op}/b{bucket}"

    def record_latency(self, op: str, bucket: int, seconds: float,
                       trace_id: Optional[str] = None) -> None:
        """Total observed latency; ``trace_id`` (a traced request) lands as
        the latency bin's exemplar, so a quantile readout names a REAL
        trace retrievable from the flight recorder (snapshot()'s
        ``latency_exemplars`` section)."""
        self.registry.histogram(f"{_LAT}{self._hist_key(op, bucket)}",
                                factory=LatencyHistogram).record(
                                    seconds, exemplar=trace_id)

    def record_queue_wait(self, op: str, bucket: int, seconds: float) -> None:
        self.registry.histogram(f"{_QW}{self._hist_key(op, bucket)}",
                                factory=LatencyHistogram).record(seconds)

    def record_device_wait(self, op: str, bucket: int,
                           seconds: float) -> None:
        self.registry.histogram(f"{_DW}{self._hist_key(op, bucket)}",
                                factory=LatencyHistogram).record(seconds)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """The nested JSON document: counters, derived rates, per-bucket
        latency summaries — total (``latency``) plus the pipeline split
        (``queue_wait`` / ``device_wait``, recorded per request at
        completion). Padding waste = fraction of dispatched rows that
        were filler (the cost of the bucket ladder; high values mean the
        ladder is too coarse for the observed size mix)."""
        snap = self.registry.snapshot()
        c = {k: snap["counters"].get(k, 0) for k in self.COUNTERS}
        rows = c["real_rows"] + c["padded_rows"]

        def section(prefix):
            return {name[len(prefix):]: s
                    for name, s in snap["histograms"].items()
                    if name.startswith(prefix)}

        with self._kernel_lock:
            kernel = {key: dict(rec) for key, rec in self._kernel.items()}
        # latency-quantile exemplars: per (op, bucket), the trace id of a
        # request observed near the p50/p99 bins (None-free: keys appear
        # only once an exemplar exists — untraced engines see no change)
        exemplars: Dict[str, dict] = {}
        for name in snap["histograms"]:
            if not name.startswith(_LAT):
                continue
            h = self.registry.histogram(name, factory=LatencyHistogram)
            ex = {q: h.exemplar_near(qv)
                  for q, qv in (("p50", 0.50), ("p99", 0.99))}
            if any(v is not None for v in ex.values()):
                exemplars[name[len(_LAT):]] = {
                    q: (v["label"] if v is not None else None)
                    for q, v in ex.items()}
        # the process-wide executable-store section (capacity-bounded AOT
        # store, utils/compile_cache.py): one store serves every engine in
        # the process, so the numbers are global by design — stamped on
        # each snapshot so the wire `stats` op and the bench artifacts see
        # residency-vs-budget next to the per-engine counters
        from iwae_replication_project_tpu.utils.compile_cache import (
            store_stats)
        st = store_stats()
        store = {k: st[k] for k in ("hits", "misses", "evictions",
                                    "demotions", "readmits",
                                    "resident_bytes", "budget_bytes",
                                    "entries")}
        store["per_model"] = st["per_model"]
        doc = {
            "model": self.model,
            "store": store,
            "counters": c,
            "queue_depth": int(snap["gauges"].get("queue_depth", 0)),
            "inflight": int(snap["gauges"].get("inflight", 0)),
            # which hot-loop path the engine's score programs run
            # (ops/hot_loop.PATH_CODES; set by ServingEngine.warmup from
            # the lifted gate at the engine's own (config, k, bucket))
            "kernel_path": int(snap["gauges"].get("kernel_path", 0)),
            # per-(op, bucket, k) gate outcomes: the selected path (code +
            # name) and — when fused on the pallas path — the (tk, tb)
            # tile, stamped per dispatch config by the engine's gate
            "kernel": kernel,
            "padding_waste": (c["padded_rows"] / rows) if rows else 0.0,
            "latency": section(_LAT),
            "latency_exemplars": exemplars,
            "queue_wait": section(_QW),
            "device_wait": section(_DW),
        }
        if self.precision:
            # present ONLY under a precision policy: an fp32-only fleet's
            # snapshot stays byte-identical to pre-precision builds
            doc["precision"] = self.precision
        return doc

    def flat(self) -> Dict[str, float]:
        """Flat scalar dict for utils/logging.MetricsLogger (JSONL/TB): one
        key per counter plus
        ``{latency,queue_wait,device_wait}/<op>/b<bucket>/p{50,95,99}_s``."""
        snap = self.snapshot()
        out: Dict[str, float] = {k: float(v)
                                 for k, v in snap["counters"].items()}
        out["queue_depth"] = float(snap["queue_depth"])
        out["inflight"] = float(snap["inflight"])
        out["kernel_path"] = float(snap["kernel_path"])
        out["padding_waste"] = float(snap["padding_waste"])
        for key in self.STORE_FLAT:
            out[f"store/{key}"] = float(snap["store"][key])
        if snap["store"]["budget_bytes"] is not None:
            out["store/budget_bytes"] = float(snap["store"]["budget_bytes"])
        for key, rec in snap["kernel"].items():
            out[f"kernel/{key}/path_code"] = float(rec["path_code"])
        for kind in ("latency", "queue_wait", "device_wait"):
            for name, s in snap[kind].items():
                for q in ("p50_s", "p95_s", "p99_s", "mean_s"):
                    if s[q] is not None:
                        out[f"{kind}/{name}/{q}"] = float(s[q])
                out[f"{kind}/{name}/count"] = float(s["count"])
        return out
