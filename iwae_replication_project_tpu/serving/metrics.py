"""Serving observability: per-bucket latency histograms + engine counters.

Dependency-free streaming histograms (fixed log-spaced bins, O(1) per
record) rather than reservoirs: a serving engine must account *every*
request at heavy load, and p99 from log-spaced bins is within one bin width
(~33%) of truth at any traffic volume — the right trade for a gauge that
steers shedding policy.

Two export surfaces, both consistent with utils/logging.py:

* :meth:`ServingMetrics.snapshot` — the nested JSON document (CLI
  ``--stats``, bench artifacts);
* :meth:`ServingMetrics.flat` — flat ``str -> float`` rows for
  ``MetricsLogger`` (JSONL + TensorBoard stamping, same pipeline the
  experiment driver's per-stage rows ride).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

#: histogram bin geometry: 8 bins per decade from 1 us to 1000 s (+overflow)
_BINS_PER_DECADE = 8
_MIN_S = 1e-6
_DECADES = 9
_N_BINS = _BINS_PER_DECADE * _DECADES + 1


def _bin_index(seconds: float) -> int:
    if seconds <= _MIN_S:
        return 0
    i = int(math.log10(seconds / _MIN_S) * _BINS_PER_DECADE)
    return min(i, _N_BINS - 1)


def _bin_upper(i: int) -> float:
    return _MIN_S * 10.0 ** ((i + 1) / _BINS_PER_DECADE)


class LatencyHistogram:
    """Log-spaced latency histogram with percentile readout."""

    def __init__(self):
        self.counts: List[int] = [0] * _N_BINS
        self.n = 0
        self.total_s = 0.0

    def record(self, seconds: float) -> None:
        self.counts[_bin_index(seconds)] += 1
        self.n += 1
        self.total_s += seconds

    def percentile(self, q: float) -> Optional[float]:
        """Upper bound of the bin holding the q-quantile (q in [0, 1])."""
        if self.n == 0:
            return None
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return _bin_upper(i)
        return _bin_upper(_N_BINS - 1)

    def summary(self) -> Dict[str, Optional[float]]:
        mean = self.total_s / self.n if self.n else None
        return {"count": self.n, "mean_s": mean,
                "p50_s": self.percentile(0.50),
                "p95_s": self.percentile(0.95),
                "p99_s": self.percentile(0.99)}


class ServingMetrics:
    """Thread-safe engine counters + per-(op, bucket) latency histograms."""

    COUNTERS = ("submitted", "completed", "timeouts", "shed", "errors",
                "dispatches", "real_rows", "padded_rows",
                "aot_hits", "aot_misses", "recompiles")

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {k: 0 for k in self.COUNTERS}
        self._hist: Dict[Tuple[str, int], LatencyHistogram] = {}
        self.queue_depth = 0          # gauge, engine-maintained

    def count(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._c[name] += n

    def set_queue_depth(self, depth: int) -> None:
        self.queue_depth = int(depth)

    def record_latency(self, op: str, bucket: int, seconds: float) -> None:
        with self._lock:
            h = self._hist.get((op, bucket))
            if h is None:
                h = self._hist[(op, bucket)] = LatencyHistogram()
            h.record(seconds)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """The nested JSON document: counters, derived rates, per-bucket
        latency summaries. Padding waste = fraction of dispatched rows that
        were filler (the cost of the bucket ladder; high values mean the
        ladder is too coarse for the observed size mix)."""
        with self._lock:
            c = dict(self._c)
            hists = {f"{op}/b{bucket}": h.summary()
                     for (op, bucket), h in sorted(self._hist.items())}
        rows = c["real_rows"] + c["padded_rows"]
        return {
            "counters": c,
            "queue_depth": self.queue_depth,
            "padding_waste": (c["padded_rows"] / rows) if rows else 0.0,
            "latency": hists,
        }

    def flat(self) -> Dict[str, float]:
        """Flat scalar dict for utils/logging.MetricsLogger (JSONL/TB): one
        key per counter plus ``latency/<op>/b<bucket>/p{50,95,99}_s``."""
        snap = self.snapshot()
        out: Dict[str, float] = {k: float(v)
                                 for k, v in snap["counters"].items()}
        out["queue_depth"] = float(snap["queue_depth"])
        out["padding_waste"] = float(snap["padding_waste"])
        for name, s in snap["latency"].items():
            for q in ("p50_s", "p95_s", "p99_s", "mean_s"):
                if s[q] is not None:
                    out[f"latency/{name}/{q}"] = float(s[q])
            out[f"latency/{name}/count"] = float(s["count"])
        return out
