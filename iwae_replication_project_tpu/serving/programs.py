"""The serving kernels: batched-yet-row-independent jitted programs.

The training/eval stack keys its RNG per *batch* (one key fans into the
whole ``[k, B, d]`` sample tensor), so a row's values depend on which batch
it rides in — fatal for a micro-batching engine that pads ragged request
batches to shape buckets. These kernels instead ``vmap`` a per-ROW program
whose key is ``fold_in(base_key, request_seed)``: every row's result is a
pure function of (params, payload, seed, k), bitwise independent of batch
size and of the zero-filled padding rows around it. That invariance is what
lets the engine slice padded results with a straight face — it is pinned by
tests/test_serving.py::test_padded_bucket_parity.

All three ops share the signature
``(params, cfg, base_key, seeds[B], payload[B, d], ...)`` with ``cfg`` (and
``k`` where present) static, so the AOT registry (utils/compile_cache.py)
keys executables by (op, bucket shape, k, dtype) exactly as the bucket
ladder intends.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives.estimators import iwae_per_example


@partial(jax.jit, static_argnames=("cfg", "k"))
def score_rows(params, cfg: model.ModelConfig, base_key: jax.Array,
               seeds: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """Per-request k-sample IWAE log-likelihood estimate ``[B]``.

    ``log p̂(x_i) = logmeanexp_k(log w)`` — the serving primitive the IWAE
    bound makes natural (arXiv:1509.00519): tighter monotonically in k, and
    each request pays exactly its own k importance samples.
    """
    def row(seed, xr):
        lw = model.log_weights(params, cfg, jax.random.fold_in(base_key, seed),
                               xr[None], k)          # [k, 1]
        return iwae_per_example(lw)[0]
    return jax.vmap(row)(seeds, x)


@partial(jax.jit, static_argnames=("cfg", "k"))
def encode_rows(params, cfg: model.ModelConfig, base_key: jax.Array,
                seeds: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """Posterior representation per request: the k-sample mean of the deepest
    latent ``[B, n_latent_enc[-1]]`` (the usable embedding; k averages the
    sampling noise down without changing the dtype/shape contract)."""
    def row(seed, xr):
        h, _, _ = model.encode(params, cfg,
                               jax.random.fold_in(base_key, seed), xr[None], k)
        return jnp.mean(h[-1][:, 0, :], axis=0)
    return jax.vmap(row)(seeds, x)


@partial(jax.jit, static_argnames=("cfg",))
def decode_rows(params, cfg: model.ModelConfig, base_key: jax.Array,
                seeds: jax.Array, h_top: jax.Array) -> jax.Array:
    """Ancestral decode of deepest-latent rows -> pixel probabilities
    ``[B, x_dim]`` (the sample/reconstruction serving op)."""
    def row(seed, hr):
        probs = model.generate_x(params, cfg,
                                 jax.random.fold_in(base_key, seed),
                                 hr[None, None, :])  # [1, 1, x_dim]
        return probs[0, 0]
    return jax.vmap(row)(seeds, h_top)


@functools.lru_cache(maxsize=32)
def make_sharded_score_rows(cfg: model.ModelConfig, mesh, k_chunk: int = 250):
    """The mesh-sharded large-k ``score`` program:
    ``(params, base_key, seeds[B], x[B, d], k[int32 scalar]) -> [B]``.

    The paper's flagship evaluation (k=5000 NLL, arXiv:1509.00519) and the
    serving ``score`` op are the same computation at different k; this
    program serves both from one executable. Batch rows shard over ``dp``;
    the k sample axis streams over ``sp`` in fixed ``k_chunk`` blocks
    through parallel/eval.py's online-logsumexp carry, and the per-device
    carries merge with one ``pmax`` + one ``psum``
    (:func:`~...parallel.eval._merge_lse_over_sp`).

    Two properties carry the serving contract:

    * **per-request RNG** — block ``g`` of row ``i`` draws from
      ``fold_in(fold_in(base_key, seeds[i]), g)`` with ``g`` the *global*
      block index, so every row's sampled weights are bitwise independent
      of coalescing, padding, block scheduling, and mesh shape (the
      reduction is then bitwise-reproducible per (mesh, k_chunk) — the
      offline scorer :func:`~...parallel.eval.sharded_score_offline` calls
      this very program, making offline/online parity exact);
    * **dynamic k** — ``k`` is a traced scalar, not a static: the block
      loop is a dynamic ``fori_loop`` and the ragged tail is masked to
      ``-inf``, so one executable per batch bucket serves every
      ``k in [1, k_max]`` — a warmed engine takes a ragged (batch, k)
      stream with zero recompiles (tightness-vs-cost around k, Rainforth
      et al. arXiv:1802.04537, becomes a per-request knob).
    """
    from jax.sharding import PartitionSpec as P

    from iwae_replication_project_tpu.parallel.eval import (
        _local_row_streaming_log_px,
        _merge_lse_over_sp,
    )
    from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map

    n_sp = mesh.shape[AXES.sp]

    def local_fn(params, base_key, seeds_local, x_local, k_dyn):
        state = _local_row_streaming_log_px(params, cfg, base_key,
                                            seeds_local, x_local, k_dyn,
                                            k_chunk, n_sp)
        _, safe, s_g = _merge_lse_over_sp(state)
        return jnp.log(s_g) + safe - jnp.log(k_dyn.astype(jnp.float32))

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp), P(AXES.dp), P()),
        out_specs=P(AXES.dp),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def make_sharded_score_adaptive(cfg: model.ModelConfig, mesh,
                                k_chunk: int = 250):
    """The accuracy-targeted adaptive ``score_adaptive`` program:
    ``(params, base_key, seeds[B], x[B, d], k_cap[int32], target_se[f32],
    ess_floor[f32]) -> [B, 3]`` rows of ``(log p_hat, achieved_se, k_used)``.

    The adaptive sibling of :func:`make_sharded_score_rows`: same mesh
    split (rows over dp, sample blocks over sp), same per-(seed, global
    block) RNG stream, but the engine stops each row at the first
    stream-prefix whose delta-method SE (or ESS) meets the client's target
    — see :func:`~...parallel.eval._local_row_adaptive_log_px` for the
    two-phase stopping/recompute scheme and its bitwise
    early-stopped-prefix == fixed-k-prefix contract.

    All three targets ride as *dynamic* replicated scalars (<= 0 disables a
    criterion), so one executable per batch bucket serves every
    (k_cap, target_se, ess_floor) — a warmed engine takes a ragged
    (batch, target) stream with zero recompiles, exactly like the fixed
    dynamic-k program.
    """
    from jax.sharding import PartitionSpec as P

    from iwae_replication_project_tpu.parallel.eval import (
        _local_row_adaptive_log_px,
    )
    from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map

    n_sp = mesh.shape[AXES.sp]

    def local_fn(params, base_key, seeds_local, x_local, k_cap, target_se,
                 ess_floor):
        return _local_row_adaptive_log_px(params, cfg, base_key, seeds_local,
                                          x_local, k_cap, target_se,
                                          ess_floor, k_chunk, n_sp)

    return jax.jit(shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(), P(AXES.dp), P(AXES.dp), P(), P(), P()),
        out_specs=P(AXES.dp),
        check_vma=False,
    ))


#: op name -> (jitted program, takes static k?)
PROGRAMS = {
    "score": (score_rows, True),
    "encode": (encode_rows, True),
    "decode": (decode_rows, False),
}

#: op name -> kwargs whose leading axis carries zero-filled padding rows
#: beyond the real batch (the engine pads every dispatch to a bucket rung
#: and slices afterwards). This is the audit contract: the padding-taint
#: pass (analysis/audit) seeds row-taint on axis 0 of exactly these inputs
#: and statically proves no padded row can reach a reduction unmasked —
#: the jaxpr-level form of the row-independence invariant the padded-bucket
#: parity tests pin at runtime. A new serving op MUST declare its padded
#: inputs here or the auditor will not see its padding at all.
PADDED_ROW_KWARGS = {
    "score": ("seeds", "x"),
    "encode": ("seeds", "x"),
    "decode": ("seeds", "h_top"),
    # the same score program under the lifted gate's fused dispatch config
    # (ModelConfig.hot_loop_path pin — serving/engine._kernel_for): the
    # padded-row contract is identical, the audited dataflow routes the
    # per-row decoder block through the hot-loop dispatcher
    "score_fused": ("seeds", "x"),
    # the mesh-sharded large-k score program (make_sharded_score_rows):
    # same per-row payload contract, dispatched by ShardedScoreEngine
    "score_sharded": ("seeds", "x"),
    # the accuracy-targeted adaptive scorer (make_sharded_score_adaptive):
    # identical per-row payload contract — padded rows ride axis 0 of the
    # seed/payload inputs and must stay masked through both phases
    "score_adaptive": ("seeds", "x"),
}
