"""ShardedScoreEngine: paper-grade k behind the serving API.

The paper's flagship number is the k=5000 NLL; ``parallel/eval.py`` already
shards that computation over the ``(dp, sp)`` mesh, but the base
:class:`~.engine.ServingEngine` tops out at single-device static-k
programs. This engine is the missing join: the SAME request lifecycle
(micro-batcher -> bucket pad -> AOT dispatch -> in-flight window ->
completion slice), with the ``score`` op swapped for the mesh-sharded
dynamic-k program (serving/programs.make_sharded_score_rows):

* **batch rows shard over dp, k blocks stream over sp** — one dispatch per
  coalesced batch, however large k is; the cross-device merge is one
  ``pmax`` + one ``psum`` of the online-logsumexp carry;
* **k is a dynamic scalar**, so the AOT menu is 2-D in shape but 1-D in
  executables: one program per batch bucket serves every ``k`` in
  ``[1, k_max]`` (:class:`~.buckets.KChunkMenu`) — a warmed engine takes a
  ragged (batch, k) request stream with zero recompiles, which is what
  makes per-request k a traffic-scale knob rather than an offline job;
* **per-request RNG** — block ``g`` of a row draws from
  ``fold_in(fold_in(base_key, seed), g)``: results are bitwise independent
  of coalescing, padding, and block scheduling, and bitwise IDENTICAL to
  the offline scorer (``parallel/eval.sharded_score_offline`` calls the
  same jitted program);
* **device memory stays bounded by the existing pipeline**: each k=5000
  dispatch is ONE in-flight window slot whose working set is
  O(bucket x k_chunk), never O(bucket x k) — the window's
  ``max_inflight`` bound and the queue shed carry over unchanged.

Requests coalesce per (op, k) exactly as before, so mixed-k traffic forms
per-k batches that all hit the same executable. The replica router
(serving/frontend/router.py) classifies ``score`` requests above its k
threshold onto engines with ``sharded=True`` — this class — while small-k
traffic keeps the single-device fast path.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from iwae_replication_project_tpu.serving.buckets import (
    BucketLadder,
    KChunkMenu,
)
from iwae_replication_project_tpu.serving.engine import ServingEngine

__all__ = ["ShardedScoreEngine"]


def default_sharded_ladder(dp: int, max_batch: int) -> BucketLadder:
    """Power-of-two-style batch ladder where every rung is a dp multiple
    (shard_map needs equal per-device row shards): ``dp * (1, 2, 4, ...)``
    up to ``max_batch`` (floored to ``dp`` when smaller)."""
    rungs = []
    b = dp
    while b < max_batch:
        rungs.append(b)
        b *= 2
    rungs.append(max(max_batch - max_batch % dp, dp) if max_batch >= dp
                 else dp)
    return BucketLadder(tuple(sorted(set(rungs))))


class ShardedScoreEngine(ServingEngine):
    """Mesh-backed ``score``-only serving replica (see module docstring).

    ``mesh`` is a ``(dp, sp)`` :class:`jax.sharding.Mesh`
    (parallel/mesh.make_mesh; default: all local devices on ``sp`` — k is
    the axis that scales). ``k_chunk`` is the canonical sample-block size
    (it versions the RNG stream: results are a pure function of
    (weights, payload, seed, k, k_chunk)); ``k_max`` the typed admission
    bound. Batch ladder rungs must be dp multiples (default ladder
    complies). Everything else — coalescing, pipeline, timeouts, metrics —
    is the base engine.
    """

    def __init__(self, source=None, *, params=None, model_config=None,
                 mesh=None, k_chunk: int = 250, k_max: int = 5000,
                 k: Optional[int] = None, max_batch: int = 8,
                 ladder: Optional[BucketLadder] = None, **kw):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from iwae_replication_project_tpu.parallel.mesh import AXES, make_mesh
        from iwae_replication_project_tpu.serving.programs import (
            make_sharded_score_rows)

        from iwae_replication_project_tpu.serving.programs import (
            make_sharded_score_adaptive)

        if mesh is None:
            mesh = make_mesh(dp=1, sp=jax.device_count())
        dp = mesh.shape[AXES.dp]
        if ladder is None:
            ladder = default_sharded_ladder(dp, max_batch)
        bad = [b for b in ladder.buckets if b % dp != 0]
        if bad:
            raise ValueError(f"sharded batch buckets must be multiples of "
                             f"dp={dp}; got {bad}")
        # k_max deliberately NOT passed to super: the menu owns the bound
        # here, and an inherited default k (checkpoint / base 50) above it
        # CLAMPS instead of failing construction — only an explicit k must
        # fit the menu
        super().__init__(source, params=params, model_config=model_config,
                         k=k, max_batch=ladder.max_batch,
                         ladder=ladder, **kw)
        self.menu = KChunkMenu(batch=ladder, k_chunk=int(k_chunk),
                               k_max=int(k_max))
        if k is None:
            self.k = min(self.k, int(k_max))
        self.k = self.menu.validate_k(self.k)
        self.k_max = int(k_max)
        self.mesh = mesh
        self._dp = dp
        self.sharded = True
        # the large-k scoring service: fixed dynamic-k scoring plus its
        # accuracy-targeted adaptive sibling (same mesh split, same RNG
        # stream; k is the CAP there and the targets ride as dynamic
        # scalars — see serving/programs.make_sharded_score_adaptive)
        self._programs = {
            "score": (make_sharded_score_rows(self.cfg, mesh,
                                              self.menu.k_chunk), True),
            "score_adaptive": (make_sharded_score_adaptive(
                self.cfg, mesh, self.menu.k_chunk), True),
        }
        self.row_dims = {"score": self.cfg.x_dim,
                         "score_adaptive": self.cfg.x_dim}
        # re-commit weights + base key replicated over the mesh so every
        # dispatch's input shardings (hence its AOT signature) are stable
        self._params = jax.device_put(self._params,
                                      NamedSharding(mesh, P()))
        self._base_key = jax.device_put(self._base_key,
                                        NamedSharding(mesh, P()))
        self._row_spec = NamedSharding(mesh, P(AXES.dp))
        self._scalar_spec = NamedSharding(mesh, P())

    # the adaptive op's submits route through the shared target validator
    # (serving/buckets.validate_adaptive_target) and its k is the cap
    _ADAPTIVE_OPS = ("score_adaptive",)

    # -- dispatch plumbing (the hooks the base engine dispatches via) ------

    def _resolve_kernel(self, op: str, k: int, bucket: int) -> tuple:
        """The sharded scorer's kernel gate: the hot loop runs inside the
        per-row streaming body at ``k_chunk``-sample blocks over the
        bucket's dp-local rows, so THAT — (k_chunk, bucket/dp) — is the
        shape the probe must vouch for, independent of the request's
        dynamic k (one outcome per bucket keeps the zero-recompile
        contract: build keys never vary with k)."""
        from iwae_replication_project_tpu.models.iwae import _on_tpu
        from iwae_replication_project_tpu.ops.hot_loop import (
            serving_dispatch_config)

        if op not in self._GATED_OPS:
            return self.cfg, "reference", None
        rows = max(bucket // self._dp, 1)
        return serving_dispatch_config(self.cfg, self.menu.k_chunk, rows,
                                       on_tpu=_on_tpu(),
                                       force=self.kernel_path_force)

    def _program_for(self, op: str, k: int, bucket: int):
        """Per-bucket program: the sharded score program closes over its
        config, so a bucket whose gate resolves a fused path gets its own
        (lru-cached) jitted program; reference buckets share the pinned
        one built at construction."""
        from iwae_replication_project_tpu.serving.programs import (
            make_sharded_score_adaptive,
            make_sharded_score_rows,
        )

        cfg_d, _, _ = self._kernel_for(op, k, bucket)
        if cfg_d is self.cfg:
            return self._programs[op][0]
        if op == "score_adaptive":
            return make_sharded_score_adaptive(cfg_d, self.mesh,
                                               self.menu.k_chunk)
        return make_sharded_score_rows(cfg_d, self.mesh, self.menu.k_chunk)

    def _stamp_k(self, op: str, k: int):
        # one dynamic-k program per bucket serves every k: the kernel
        # stamp is per bucket, not per request k (a ragged k stream must
        # not mint a metrics gauge per distinct k)
        return "dyn"

    def _prof_flops(self, op: str, k: int, rows: int):
        """The profiling plane's MFU numerator under DYNAMIC k: the
        attribution key collapses every k into the bucket's one "dyn"
        class (one program, one executable — see :meth:`_stamp_k`), but
        the work is the request's actual k, so the FLOP count must use it
        — the measured-MFU gauge then stays honest across a ragged k
        stream instead of assuming the warmup k."""
        if op != "score":
            return None
        from iwae_replication_project_tpu.utils.flops import (
            serving_score_flops_per_row)
        return serving_score_flops_per_row(self.cfg, k) * rows

    def _trace_attrs(self, op: str, k: int, bucket: int, n: int) -> dict:
        # a traced large-k dispatch's span carries the streaming shape (the
        # dynamic request k, the chunk it streams in, the mesh split) so a
        # k=5000 p99 in the flight recorder attributes to blocks, not magic
        attrs = super()._trace_attrs(op, k, bucket, n)
        attrs.update({"sharded": True, "k_chunk": self.menu.k_chunk,
                      "dp": self._dp})
        return attrs

    def _dispatch_args(self, op: str, k: int, payload: np.ndarray,
                       seeds: np.ndarray,
                       targets: Optional[Tuple[float, float]] = None
                       ) -> Tuple[tuple, dict, dict]:
        """Positional args of one sharded dispatch: payload/seed rows shard
        over dp, k rides as a replicated dynamic scalar — NOT a static —
        so every k shares the bucket's one executable. The adaptive op
        appends its ``(target_se, ess_floor)`` pair the same way: dynamic
        replicated scalars, so one executable per bucket serves every
        (k_cap, target) with zero recompiles."""
        import jax

        payload_dev, seeds_dev = jax.device_put((payload, seeds),
                                                self._row_spec)
        k_arr = jax.device_put(np.int32(k), self._scalar_spec)
        if op in self._ADAPTIVE_OPS:
            tse, floor = targets if targets is not None else (0.0, 0.0)
            tse_arr = jax.device_put(np.float32(tse), self._scalar_spec)
            floor_arr = jax.device_put(np.float32(floor), self._scalar_spec)
            return ((self._params, self._base_key, seeds_dev, payload_dev,
                     k_arr, tse_arr, floor_arr), {}, {})
        return ((self._params, self._base_key, seeds_dev, payload_dev,
                 k_arr), {}, {})

    def _build_key(self, op: str, k: int, bucket: int) -> tuple:
        from iwae_replication_project_tpu.utils.compile_cache import (
            mesh_fingerprint)

        # k deliberately absent: the dynamic-k program's identity is
        # (config, chunk, mesh, bucket) — the zero-recompile contract. The
        # config is the GATE's dispatch config (carries the hot-loop pin),
        # whose resolution is bucket-only, never k (see _resolve_kernel).
        # The adaptive targets are dynamic scalars and equally absent: the
        # op-name prefix alone separates the two program families.
        prefix = "score_adaptive" if op in self._ADAPTIVE_OPS \
            else "score_sharded"
        return (prefix, self._kernel_for(op, k, bucket)[0],
                self.menu.k_chunk, mesh_fingerprint(self.mesh), bucket)

    def _aot_name(self, op: str) -> str:
        return "serve_score_adaptive" if op in self._ADAPTIVE_OPS \
            else "serve_score_sharded"

    def _prof_adaptive(self, inf, out):
        """Adaptive dispatches attribute the samples they actually drew:
        total k_used from the fetched result's third column, and FLOPs
        summed per row at each row's own k_used — never the cap (an
        easy-row-heavy batch must bill what it computed, or the profiler's
        MFU and the SLO burn rates could be gamed by cheap rows)."""
        if inf.op not in self._ADAPTIVE_OPS or out is None:
            return None
        from iwae_replication_project_tpu.utils.flops import (
            serving_score_flops_per_row)
        k_used = out[:len(inf.batch), 2]
        flops = float(sum(serving_score_flops_per_row(self.cfg, int(ku))
                          for ku in k_used))
        return flops, float(k_used.sum())

    def warmup(self, ops: Sequence[str] = ("score", "score_adaptive"),
               ks: Optional[Iterable[int]] = None) -> Dict[str, float]:
        """Pre-compile the batch ladder — one executable per rung covers
        the WHOLE k range (k is dynamic; the adaptive op's targets are
        dynamic too), so ``ks`` is only the probe value traced through
        (default: the engine's k)."""
        return super().warmup(ops=ops, ks=ks)
