"""Unified telemetry: metric registry, span tracing, on-device diagnostics.

Import surface (kept light — :mod:`.diagnostics` pulls the model stack and
is imported explicitly by the call sites that compute diagnostics):

* :mod:`.registry` — counters / gauges / log-spaced histograms behind one
  process-default :class:`MetricRegistry` (:func:`get_registry`);
* :mod:`.spans` — nested host-side :func:`span` timing that lands in the
  registry AND in ``jax.profiler`` traces under the same names;
* :mod:`.exporters` — Prometheus text page + the ``/metrics`` and
  ``/traces`` HTTP endpoints (JSONL/TensorBoard export rides
  :class:`~..utils.logging.MetricsLogger`);
* :mod:`.tracing` — per-request trace trees: explicit
  :class:`TraceContext` threading, a tail-sampled
  :class:`FlightRecorder` (:func:`get_recorder`), Chrome trace-event
  export;
* :mod:`.slo` — :class:`SLOMonitor`: per-(model, op) latency/availability
  objectives published as multi-window burn-rate gauges;
* :mod:`.profiling` — :class:`DispatchProfiler`: always-on per-dispatch
  device-time attribution, measured MFU/bandwidth gauges against the AOT
  registry's static roofline costs, and an EWMA drift detector emitting
  typed ``prof/drift`` findings (the ``iwae-prof`` regression gate is
  analysis/regress.py);
* :mod:`.parity` — :func:`statistical_parity`: the toleranced acceptance
  gate low-precision (bf16/int8) serving legs must pass against the fp32
  oracle (pure-numpy, offline — check stages / bench legs / tests);
* :mod:`.diagnostics` — :class:`DiagnosticsConfig`-gated ESS / log-weight
  variance / gradient-SNR / active-units reductions that run inside the
  jitted train/eval programs.
"""

from iwae_replication_project_tpu.telemetry.exporters import (
    prometheus_text,
    start_metrics_server,
)
from iwae_replication_project_tpu.telemetry.parity import (
    DEFAULT_TOLERANCES,
    ParityTolerances,
    statistical_parity,
)
from iwae_replication_project_tpu.telemetry.profiling import (
    DispatchProfiler,
    DriftFinding,
    ProfilingConfig,
    detect_chip_peaks,
)
from iwae_replication_project_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    get_registry,
)
from iwae_replication_project_tpu.telemetry.slo import (
    SLOMonitor,
    SLOObjective,
)
from iwae_replication_project_tpu.telemetry.spans import (
    current_span,
    span,
    spanned,
)
from iwae_replication_project_tpu.telemetry.tracing import (
    FlightRecorder,
    TraceContext,
    chrome_trace_events,
    get_recorder,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "get_registry",
    "current_span", "span", "spanned",
    "prometheus_text", "start_metrics_server",
    "FlightRecorder", "TraceContext", "chrome_trace_events", "get_recorder",
    "SLOMonitor", "SLOObjective",
    "DispatchProfiler", "DriftFinding", "ProfilingConfig",
    "detect_chip_peaks",
    "DEFAULT_TOLERANCES", "ParityTolerances", "statistical_parity",
]
