"""On-device estimator diagnostics: is the K-sample bound actually healthy?

Two papers define what to watch (PAPERS.md):

* Rainforth et al. ("Tighter Variational Bounds are Not Necessarily
  Better") — the quantity that decides whether a K-sample objective
  *trains* is the gradient **signal-to-noise ratio** SNR = |E[g]| / sigma[g],
  which for the IWAE encoder *decays* as K grows;
* "Reinterpreting Importance-Weighted Autoencoders" (arXiv:1704.02916) —
  IWAE is self-normalized importance sampling, whose health metric is the
  **effective sample size** of the K weights,
  ``ESS = (sum w)^2 / sum w^2``: ESS ~ K means the posterior is
  well-covered; ESS ~ 1 means one sample dominates and the bound is tight
  only on paper.

Everything here runs INSIDE the jitted train/eval programs — pure ``jnp``
reductions of tensors those programs already materialize (the ``[k, B]``
log-weights, the per-step grads), so enabling diagnostics adds reductions to
the device graph and **zero extra host syncs**: results ride the same
per-stage fetch the driver already performs. :class:`DiagnosticsConfig` is a
frozen (hashable -> jit-static) gate; with it absent/off every call site
compiles the byte-identical pre-diagnostics program.

Scalars emitted (the ``diag/`` namespace in metrics.jsonl / TensorBoard /
the registry):

=====================  ====================================================
``diag/ess``           mean over datapoints of ESS of the K weights
``diag/ess_frac``      same, normalized by the ACTUAL sample count (1.0 =
                       perfect coverage) — dynamic-k callers pass
                       ``n_samples``; the padded leading axis is never the
                       denominator
``diag/log_weight_var`` mean over datapoints of Var_k[log w]
``diag/kl_q_p``        MC estimate of E_q[log q(h|x) - log p(h)]
``diag/active_units``  latent units with Var_B[E_q[h|x]] > threshold
``diag/active_frac``   same, normalized by the total latent width
``diag/grad_snr``      mean over parameters of |E[g]| / sigma[g] over the
                       trailing ``snr_window`` optimizer steps (per the
                       objective's sample count K — Rainforth-style)
``diag/grad_snr_enc``  encoder-subtree mean (the one Rainforth predicts
``diag/grad_snr_dec``  decays with K); decoder+output-subtree mean
=====================  ====================================================
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from iwae_replication_project_tpu.models import iwae as model

_SNR_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class DiagnosticsConfig:
    """Hashable gate + knobs (frozen -> usable as a jit static / build key).

    ``enabled=False`` (or passing ``None`` where a config is accepted) must
    leave every hot path byte-identical to the pre-diagnostics program —
    bench.py ``--telemetry`` measures exactly that contract.
    """

    enabled: bool = True
    #: trailing optimizer steps in the gradient-SNR moment estimate (clamped
    #: to the steps one epoch dispatch actually runs)
    snr_window: int = 50
    #: posterior-mean variance threshold for the active-units count (the
    #: evaluation suite's 0.01 convention, Burda et al.)
    active_threshold: float = 0.01

    def __post_init__(self):
        # window 0 would make the SNR moments divide by zero -> silent NaN
        # rows (and an abort under the debug_nans sanitize profile)
        if self.snr_window < 1:
            raise ValueError(
                f"snr_window must be >= 1, got {self.snr_window}")


# ---------------------------------------------------------------------------
# weight-space diagnostics: pure reductions of the [k, B] log-weights
# ---------------------------------------------------------------------------

def ess(log_w: jnp.ndarray) -> jnp.ndarray:
    """Effective sample size of the K self-normalized weights, per datapoint.

    ``ESS = (sum_k w)^2 / sum_k w^2 = exp(2 lse(log w) - lse(2 log w))``,
    computed in log space so it is exact under the same max-stabilization
    the bound itself uses. Range ``[1, k]``: k for uniform weights, ->1 as
    one sample dominates.
    """
    lse1 = jax.nn.logsumexp(log_w, axis=0)
    lse2 = jax.nn.logsumexp(2.0 * log_w, axis=0)
    return jnp.exp(2.0 * lse1 - lse2)


def weight_diagnostics(log_w: jnp.ndarray,
                       n_samples=None) -> Dict[str, jnp.ndarray]:
    """Batch-mean ESS / ESS-fraction / log-weight variance of one pass.

    ``n_samples`` is the ACTUAL sample count when the leading axis is
    padded (dynamic-k callers — e.g. the adaptive scorer's masked sample
    blocks, where unused rows hold ``-inf``). The ``-inf`` rows already
    drop out of the log-space ESS reduction, but ``diag/ess_frac``'s
    denominator and the log-weight variance would otherwise silently use
    the PADDED ``shape[0]`` — under dynamic k that number is wrong, never
    just imprecise. With ``n_samples`` given (a traced scalar is fine — it
    never touches program shape), the fraction normalizes by the true
    count and the variance masks padding out of its moments; ``None``
    keeps the historical contract: the leading axis IS the sample count.
    ``n_samples`` may be per-row (``[B]``) or scalar; a zero count yields
    ``ess = ess_frac = 0`` — a 0/0 NaN would read as a health number.
    """
    if n_samples is None:
        k = log_w.shape[0]
        e = jnp.mean(ess(log_w))
        return {"diag/ess": e, "diag/ess_frac": e / k,
                "diag/log_weight_var": jnp.mean(jnp.var(log_w, axis=0))}
    n = jnp.asarray(n_samples, log_w.dtype)
    mask = jnp.arange(log_w.shape[0])[:, None] < n
    safe_n = jnp.maximum(n, 1.0)
    masked = jnp.where(mask, log_w, -jnp.inf)
    # inline the ESS identity instead of calling ess(): an all-masked
    # column has lse1 = lse2 = -inf, and the naive ``2*lse1 - lse2`` is a
    # NaN even though the answer (0 samples -> ESS 0) is well-defined —
    # substitute finite dummies and select, the OnlineLSE never-NaN idiom
    lse1 = jax.nn.logsumexp(masked, axis=0)
    lse2 = jax.nn.logsumexp(2.0 * masked, axis=0)
    empty = jnp.isneginf(lse1)
    per_row = jnp.where(
        empty, 0.0, jnp.exp(2.0 * jnp.where(empty, 0.0, lse1)
                            - jnp.where(empty, 0.0, lse2)))
    e = jnp.mean(per_row)
    lw = jnp.where(mask, log_w, 0.0)
    m = jnp.sum(lw, axis=0) / safe_n
    d = jnp.where(mask, log_w - m, 0.0)
    return {"diag/ess": e,
            "diag/ess_frac": jnp.mean(per_row / safe_n),
            "diag/log_weight_var": jnp.mean(jnp.sum(d * d, axis=0) / safe_n)}


# ---------------------------------------------------------------------------
# gradient SNR: trailing-window moment accumulation inside the epoch scan
# ---------------------------------------------------------------------------

def grad_accum_init(params) -> Tuple:
    """Zeroed ``(sum g, sum g^2)`` accumulator trees for the scan carry."""
    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return zeros, jax.tree.map(jnp.copy, zeros)


def grad_accum_update(acc: Tuple, grads, include: jnp.ndarray) -> Tuple:
    """Fold one step's grads in, weighted by `include` (0/1 window mask)."""
    s1, s2 = acc
    s1 = jax.tree.map(lambda a, g: a + include * g, s1, grads)
    s2 = jax.tree.map(lambda a, g: a + include * (g * g), s2, grads)
    return s1, s2


def _subtree_snr(sum_g, sum_sq, n: int) -> jnp.ndarray:
    """Mean over parameters of |mean| / std from the accumulated moments."""
    tot = jnp.zeros((), jnp.float32)
    count = 0
    for g, q in zip(jax.tree.leaves(sum_g), jax.tree.leaves(sum_sq)):
        m = g / n
        var = jnp.maximum(q / n - m * m, 0.0)
        tot = tot + jnp.sum(jnp.abs(m) / jnp.sqrt(var + _SNR_EPS))
        count += g.size
    return tot / count


def grad_snr_summary(sum_g, sum_sq, n: int) -> Dict[str, jnp.ndarray]:
    """Rainforth-style SNR scalars from windowed first/second grad moments.

    `sum_g`/`sum_sq` are params-shaped trees (``{"enc", "dec", "out"}``);
    the encoder subtree is reported separately because that is the gradient
    Rainforth et al. predict degrades as K grows, while the decoder's
    improves.
    """
    dec = ({"dec": sum_g["dec"], "out": sum_g["out"]},
           {"dec": sum_sq["dec"], "out": sum_sq["out"]})
    return {
        "diag/grad_snr": _subtree_snr(sum_g, sum_sq, n),
        "diag/grad_snr_enc": _subtree_snr(sum_g["enc"], sum_sq["enc"], n),
        "diag/grad_snr_dec": _subtree_snr(*dec, n),
    }


# ---------------------------------------------------------------------------
# the per-eval diagnostics program: one scan over the test batches
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("cfg", "k", "diag"))
def estimator_diagnostics(params, cfg: model.ModelConfig, key: jax.Array,
                          batches: jax.Array, k: int,
                          diag: DiagnosticsConfig) -> Dict[str, jax.Array]:
    """Weight-space + KL + active-units diagnostics over ``[n_batches, B, d]``
    test batches as ONE device program (the driver routes it through the AOT
    registry next to ``dataset_scalars``). Returns a dict of scalars.

    The active-units estimate here is the cheap in-graph version — variance
    across datapoints of the per-datapoint posterior mean (mean over the k
    samples the diagnostics pass already drew). The evaluation suite's
    dedicated estimator (evaluation/activity.py, fresh MC samples + PCA)
    remains the reference number; this one rides along at zero extra passes.
    """
    n_batches, batch = batches.shape[0], batches.shape[1]

    def body(carry, inp):
        acc, s1, s2 = carry
        i, xb = inp
        log_w, aux = model.log_weights_and_aux(
            params, cfg, jax.random.fold_in(key, i), xb, k)
        w = weight_diagnostics(log_w)
        kl = jnp.mean(aux["log_q"] - aux["log_prior"])
        acc = acc + jnp.stack([w["diag/ess"], w["diag/log_weight_var"], kl])
        means = [jnp.mean(h, axis=0) for h in aux["h"]]   # [B, d_l] per layer
        s1 = tuple(s + jnp.sum(m, axis=0) for s, m in zip(s1, means))
        s2 = tuple(s + jnp.sum(m * m, axis=0) for s, m in zip(s2, means))
        return (acc, s1, s2), None

    init = (jnp.zeros(3),
            tuple(jnp.zeros(d) for d in cfg.n_latent_enc),
            tuple(jnp.zeros(d) for d in cfg.n_latent_enc))
    (acc, s1, s2), _ = lax.scan(body, init,
                                (jnp.arange(n_batches), batches))
    acc = acc / n_batches
    n = n_batches * batch
    active = jnp.zeros((), jnp.float32)
    for s, q in zip(s1, s2):
        var = jnp.maximum(q / n - (s / n) ** 2, 0.0)
        active = active + jnp.sum(var > diag.active_threshold)
    total_units = sum(cfg.n_latent_enc)
    return {
        "diag/ess": acc[0],
        "diag/ess_frac": acc[0] / k,
        "diag/log_weight_var": acc[1],
        "diag/kl_q_p": acc[2],
        "diag/active_units": active,
        "diag/active_frac": active / total_units,
    }
