"""Registry exporters beyond the JSONL/TensorBoard pair.

The JSONL and TensorBoard surfaces already exist —
:class:`~..utils.logging.MetricsLogger` (and its ``log_registry``) stamps
``MetricRegistry.rows()`` through the same pipeline the experiment driver's
per-stage rows ride. This module adds the pull-based surface:

* :func:`prometheus_text` — the registry as a Prometheus text-format page
  (counters as ``*_total``, gauges, histograms as summaries with quantile
  labels), with ``# HELP`` lines per family and the histogram ``_sum``
  taken from the Histogram's exact tracked ``total``.  Same-name
  collisions across merged registries stay last-writer-wins (the
  documented merge order) but are COUNTED on the process registry's
  ``telemetry/export_collisions`` counter instead of passing silently;
* :func:`start_metrics_server` — a daemon-thread HTTP endpoint serving
  that page at ``/metrics`` — plus, when handed a flight recorder
  (telemetry/tracing.py), the retained request traces as Chrome
  trace-event JSON at ``/traces``; when handed dispatch profilers
  (telemetry/profiling.py), their snapshots at ``/prof``; and tier
  liveness at ``/healthz`` — all of which the ``iwae-serve`` CLI exposes
  via ``--metrics-port``.

Dependency-free (stdlib http.server); the server snapshots the registry per
request, so a long-lived scrape always sees current values.
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: summary-key -> prometheus quantile label (accepts unit-suffixed variants
#: like ``p50_s`` from the serving latency histograms)
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


#: name-prefix -> # HELP text (first match wins; anything unlisted gets a
#: generic line naming the original slash-path)
_HELP_PREFIXES = (
    ("latency/", "per-request serving latency in seconds, by "
                 "(model, op, bucket)"),
    ("queue_wait/", "submit-to-device-enqueue wait in seconds "
                    "(coalescing + in-flight backpressure)"),
    ("device_wait/", "device-enqueue-to-fetch wait in seconds "
                     "(compute + D2H)"),
    ("router/", "serving-tier replica router accounting"),
    ("slo/", "SLO burn-rate accounting: violation fraction over the "
             "trailing window divided by the error budget (1 - target)"),
    ("span/", "host-side span wall time in seconds (telemetry/spans.py)"),
    ("store/", "process executable-store accounting "
               "(utils/compile_cache.py)"),
    ("kernel/", "hot-loop path selected per (op, bucket, k) dispatch "
                "config (ops/hot_loop.PATH_CODES)"),
    ("autotune/", "tile/remat autotuner accounting (ops/autotune.py)"),
    ("telemetry/", "telemetry-pipeline self-accounting"),
    ("diag/", "on-device estimator diagnostics "
              "(telemetry/diagnostics.py)"),
    ("prof/", "continuous profiling plane: per-dispatch device time, "
              "measured MFU/bandwidth vs static roofline ceilings, and "
              "EWMA drift accounting (telemetry/profiling.py)"),
)


def _escape_help(text: str) -> str:
    """Escape a ``# HELP`` value per the exposition format: backslash and
    newline (a raw newline would terminate the comment mid-value and turn
    the remainder into a garbage sample line)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    """Escape a label VALUE per the exposition format: backslash,
    double-quote, newline — the three characters that would otherwise
    terminate or corrupt the quoted string."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _help_for(name: str, kind: str) -> str:
    for prefix, text in _HELP_PREFIXES:
        if name.startswith(prefix):
            return _escape_help(text)
    return _escape_help(f"iwae {kind} {name!r}")


def _sanitize(name: str) -> str:
    n = _NAME_RE.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(registries, namespace: str = "iwae") -> str:
    """Render one or more registries as a Prometheus exposition page.

    Later registries win on name collisions — pass the process-default
    registry first and subsystem registries after it.  Every collision is
    counted on the process registry's ``telemetry/export_collisions``
    counter (visible from the NEXT scrape), so a shadowed metric is a
    visible condition instead of a silently wrong dashboard.
    """
    if isinstance(registries, MetricRegistry):
        registries = (registries,)
    counters, gauges, hists = {}, {}, {}
    collisions = 0
    for reg in registries:
        snap = reg.snapshot()
        for src, dst in ((snap["counters"], counters),
                         (snap["gauges"], gauges),
                         (snap["histograms"], hists)):
            for k, v in src.items():
                if k in dst:
                    collisions += 1
                dst[k] = v
    if collisions:
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)
        get_registry().counter("telemetry/export_collisions").inc(collisions)

    lines = []
    for name, v in sorted(counters.items()):
        m = f"{namespace}_{_sanitize(name)}_total"
        lines += [f"# HELP {m} {_help_for(name, 'counter')}",
                  f"# TYPE {m} counter", f"{m} {_fmt(v)}"]
    for name, v in sorted(gauges.items()):
        m = f"{namespace}_{_sanitize(name)}"
        lines += [f"# HELP {m} {_help_for(name, 'gauge')}",
                  f"# TYPE {m} gauge", f"{m} {_fmt(v)}"]
    for name, s in sorted(hists.items()):
        m = f"{namespace}_{_sanitize(name)}"
        lines.append(f"# HELP {m} {_help_for(name, 'summary')}")
        lines.append(f"# TYPE {m} summary")
        for key, label in _QUANTILES:
            v = next((s[k] for k in (key, key + "_s") if s.get(k) is not None),
                     None)
            if v is not None:
                lines.append(
                    f'{m}{{quantile="{_escape_label(label)}"}} {_fmt(v)}')
        count = s.get("count") or 0
        lines.append(f"{m}_count {_fmt(count)}")
        # _sum from the histogram's exact tracked total; the mean * count
        # reconstruction (pre-satellite behavior) only as a fallback for
        # foreign summaries that never carried one
        total = next((s[k] for k in ("total", "total_s")
                      if s.get(k) is not None), None)
        if total is None:
            mean = next((s[k] for k in ("mean", "mean_s")
                         if s.get(k) is not None), None)
            total = mean * count if mean is not None else None
        if total is not None:
            lines.append(f"{m}_sum {_fmt(total)}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    registries: Sequence[MetricRegistry] = ()
    recorder = None     # optional FlightRecorder backing /traces
    profilers: Sequence = ()   # optional DispatchProfilers backing /prof
    health = None       # optional callable -> liveness dict backing /healthz

    def _send_json(self, doc, status: int = 200) -> None:
        import json

        body = json.dumps(doc).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?")[0]
        if path == "/traces":
            self._serve_traces()
            return
        if path == "/prof":
            self._serve_prof()
            return
        if path == "/healthz":
            self._serve_healthz()
            return
        if path not in ("/", "/metrics"):
            self.send_error(404)
            return
        body = prometheus_text(self.registries).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_traces(self):
        """Retained flight-recorder traces as Chrome trace-event JSON —
        save the response body and load it in chrome://tracing/Perfetto
        (the ``iwae-trace`` CLI does the same over the wire op)."""
        if self.recorder is None:
            self.send_error(404, "tracing is not enabled on this server")
            return
        from iwae_replication_project_tpu.telemetry.tracing import (
            chrome_trace_events)
        self._send_json(chrome_trace_events(self.recorder.traces()))

    def _serve_prof(self):
        """The profiling-plane snapshot(s) (telemetry/profiling.py): one
        document per attached profiler — per-key measured/EWMA state, the
        chip peaks in use, and the retained ``prof/drift`` findings."""
        if not self.profilers:
            self.send_error(404, "profiling is not enabled on this server")
            return
        self._send_json({"profilers": [p.snapshot() for p in self.profilers]})

    def _serve_healthz(self):
        """Tier liveness for the fleet controller and external probes:
        200 + the liveness document when healthy, 503 when the provider
        reports unhealthy OR raises (a dying tier must read as down, not
        as a scrape error)."""
        doc, ok = {"ok": True}, True
        if self.health is not None:
            try:
                doc = dict(self.health())
                ok = bool(doc.get("ok", True))
            except Exception as e:
                doc, ok = {"ok": False, "error": str(e)}, False
        self._send_json(doc, status=200 if ok else 503)

    def log_message(self, *args):  # scrapes must not spam the serving stdout
        pass


class _MetricsServer(ThreadingHTTPServer):
    def shutdown(self):
        """Stop serving AND close the listening socket — the stock
        ThreadingHTTPServer leaves the socket bound after shutdown(), which
        leaks the fd and EADDRINUSEs the next fixed-port start."""
        super().shutdown()
        self.server_close()


def start_metrics_server(registries, port: int,
                         host: str = "127.0.0.1",
                         recorder=None, profilers=None,
                         health=None) -> ThreadingHTTPServer:
    """Serve ``/metrics`` in a daemon thread; returns the live server
    (``.server_address[1]`` is the bound port — pass ``port=0`` for an
    ephemeral one; ``.shutdown()`` stops it and releases the port).
    ``recorder`` (a :class:`~.tracing.FlightRecorder`) additionally serves
    its retained traces as Chrome trace-event JSON at ``/traces``;
    ``profilers`` (an iterable of :class:`~.profiling.DispatchProfiler`)
    serves their merged snapshots at ``/prof``; ``health`` (a zero-arg
    callable returning a liveness dict with an ``ok`` key) backs
    ``/healthz`` — 200 when ok, 503 when not (or when the callable
    raises). ``/healthz`` always answers: with no callable it reports
    bare process liveness ``{"ok": true}``."""
    if isinstance(registries, MetricRegistry):
        registries = (registries,)

    class Handler(_MetricsHandler):
        pass

    Handler.registries = tuple(registries)
    Handler.recorder = recorder
    Handler.profilers = tuple(profilers) if profilers else ()
    # staticmethod: a bare function set as a class attribute would bind as
    # a method and receive the handler as a bogus first argument
    Handler.health = staticmethod(health) if health is not None else None
    srv = _MetricsServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, name="iwae-metrics-http",
                     daemon=True).start()
    return srv
