"""Registry exporters beyond the JSONL/TensorBoard pair.

The JSONL and TensorBoard surfaces already exist —
:class:`~..utils.logging.MetricsLogger` (and its ``log_registry``) stamps
``MetricRegistry.rows()`` through the same pipeline the experiment driver's
per-stage rows ride. This module adds the pull-based surface:

* :func:`prometheus_text` — the registry as a Prometheus text-format page
  (counters as ``*_total``, gauges, histograms as summaries with quantile
  labels);
* :func:`start_metrics_server` — a daemon-thread HTTP endpoint serving that
  page at ``/metrics``, which the ``iwae-serve`` CLI exposes via
  ``--metrics-port``.

Dependency-free (stdlib http.server); the server snapshots the registry per
request, so a long-lived scrape always sees current values.
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: summary-key -> prometheus quantile label (accepts unit-suffixed variants
#: like ``p50_s`` from the serving latency histograms)
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def _sanitize(name: str) -> str:
    n = _NAME_RE.sub("_", name)
    return n if not n[:1].isdigit() else "_" + n


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(registries, namespace: str = "iwae") -> str:
    """Render one or more registries as a Prometheus exposition page.

    Later registries win on (sanitized) name collisions — pass the
    process-default registry first and subsystem registries after it.
    """
    if isinstance(registries, MetricRegistry):
        registries = (registries,)
    counters, gauges, hists = {}, {}, {}
    for reg in registries:
        snap = reg.snapshot()
        counters.update(snap["counters"])
        gauges.update(snap["gauges"])
        hists.update(snap["histograms"])

    lines = []
    for name, v in sorted(counters.items()):
        m = f"{namespace}_{_sanitize(name)}_total"
        lines += [f"# TYPE {m} counter", f"{m} {_fmt(v)}"]
    for name, v in sorted(gauges.items()):
        m = f"{namespace}_{_sanitize(name)}"
        lines += [f"# TYPE {m} gauge", f"{m} {_fmt(v)}"]
    for name, s in sorted(hists.items()):
        m = f"{namespace}_{_sanitize(name)}"
        lines.append(f"# TYPE {m} summary")
        for key, label in _QUANTILES:
            v = next((s[k] for k in (key, key + "_s") if s.get(k) is not None),
                     None)
            if v is not None:
                lines.append(f'{m}{{quantile="{label}"}} {_fmt(v)}')
        count = s.get("count") or 0
        mean = next((s[k] for k in ("mean", "mean_s")
                     if s.get(k) is not None), None)
        lines.append(f"{m}_count {_fmt(count)}")
        if mean is not None:
            lines.append(f"{m}_sum {_fmt(mean * count)}")
    return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    registries: Sequence[MetricRegistry] = ()

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?")[0] not in ("/", "/metrics"):
            self.send_error(404)
            return
        body = prometheus_text(self.registries).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam the serving stdout
        pass


class _MetricsServer(ThreadingHTTPServer):
    def shutdown(self):
        """Stop serving AND close the listening socket — the stock
        ThreadingHTTPServer leaves the socket bound after shutdown(), which
        leaks the fd and EADDRINUSEs the next fixed-port start."""
        super().shutdown()
        self.server_close()


def start_metrics_server(registries, port: int,
                         host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Serve ``/metrics`` in a daemon thread; returns the live server
    (``.server_address[1]`` is the bound port — pass ``port=0`` for an
    ephemeral one; ``.shutdown()`` stops it and releases the port)."""
    if isinstance(registries, MetricRegistry):
        registries = (registries,)

    class Handler(_MetricsHandler):
        pass

    Handler.registries = tuple(registries)
    srv = _MetricsServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, name="iwae-metrics-http",
                     daemon=True).start()
    return srv
