"""Statistical parity acceptance for low-precision serving (ISSUE 16).

Bitwise parity is the wrong gate for bf16/int8 decoder paths: their whole
point is to NOT reproduce fp32 bit-for-bit. What must hold instead is that
the *estimator* a tenant receives is statistically indistinguishable for
serving purposes, and this module is the one definition of that contract —
shared by the ``precision_parity_smoke`` check stage, ``bench.py
--precision``, and the unit tests that pin the gate itself.

Given the ``[k, B]`` log-weight matrices of the fp32 oracle and of a
low-precision leg over the SAME rows / seeds / k, acceptance requires all
of:

* ``row_rel_max`` — max over rows of ``|Δ log p̂(x)|``, RELATIVE to the
  oracle's batch-NLL magnitude (rounding error through the decoder stack
  is proportional to the accumulated log-likelihood, so the same policy
  must pass at a 24-pixel smoke model and the 784-pixel paper model)
  within ``max_row_rel_delta``;
* ``batch_nll`` — ``|Δ mean(-log p̂)|`` in absolute nats (per-row errors
  average out, so the fleet-level quality number holds an absolute bound
  even at paper scale — and a systematic bias is exactly what must not
  hide behind a relative gate) within ``max_batch_nll_delta``;
* ``ess_frac`` — absolute drift of the normalized effective sample size
  (already in ``[0, 1]``) within ``max_ess_frac_drift``;
* ``log_weight_var`` — drift of ``mean Var_k[log w]`` relative to the
  oracle's value (the spread itself scales with the model) within
  ``max_log_weight_var_rel_drift``. Together with ``ess_frac`` this
  keeps a precision path from degrading weight coverage even where the
  mean survives (telemetry/diagnostics.py owns the health semantics).

Every check is two-sided (absolute values of deltas): a "better" NLL
from a quantized path is just as much a parity violation as a worse one —
it means the program is not computing the tenant's model.

This is an *offline* gate: pure numpy over log-weights the caller already
fetched (check stages, bench legs, tests). Nothing here runs inside the
dispatch hot path, and nothing here touches the device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np


@dataclasses.dataclass(frozen=True)
class ParityTolerances:
    """Acceptance bounds for one precision policy (frozen -> hashable,
    usable as a parameter of cached check programs).

    All bounds are two-sided; ``row`` and ``log_weight_var`` are relative
    to the oracle's own magnitude (see the module docstring), ``batch_nll``
    and ``ess_frac`` are absolute.
    """

    #: max over rows of |Δ log p̂(x)| / max(1, |oracle batch NLL|) — the
    #: per-request bound, scale-free
    max_row_rel_delta: float
    #: |Δ batch mean NLL| in nats — the fleet-quality bound
    max_batch_nll_delta: float
    #: |Δ mean ESS/k| — importance-weight coverage drift (range [0, 1])
    max_ess_frac_drift: float
    #: |Δ mean Var_k[log w]| / max(1, oracle value) — weight-spread drift
    max_log_weight_var_rel_drift: float

    def __post_init__(self):
        for f in dataclasses.fields(self):
            if getattr(self, f.name) <= 0:
                raise ValueError(f"{f.name} must be > 0 (a zero tolerance "
                                 f"is a request for bitwise parity — serve "
                                 f"fp32 instead)")


#: bf16 operands / fp32 accumulation: ~8 mantissa bits through the whole
#: pass — measured deltas sit ~10x inside these at both the 24-pixel
#: smoke shape and the 784-pixel paper shape, while a +1-nat bias or a
#: wrong-weights program lands far outside.
BF16_TOLERANCES = ParityTolerances(
    max_row_rel_delta=0.01,
    max_batch_nll_delta=0.1,
    max_ess_frac_drift=0.05,
    max_log_weight_var_rel_drift=0.1,
)

#: weight-only int8 (symmetric per-output-channel, fp32 accumulation):
#: quantization noise is bounded by the per-channel step but compounds
#: through the stack, so the gate is looser than bf16 — still orders of
#: magnitude tighter than any wrong-program failure mode.
INT8_TOLERANCES = ParityTolerances(
    max_row_rel_delta=0.02,
    max_batch_nll_delta=0.25,
    max_ess_frac_drift=0.1,
    max_log_weight_var_rel_drift=0.2,
)

#: policy name -> default gate; fp32 has no entry on purpose (its contract
#: is bitwise identity, checked directly by the callers)
DEFAULT_TOLERANCES = {"bf16": BF16_TOLERANCES, "int8": INT8_TOLERANCES}


def _row_log_phat(log_w: np.ndarray) -> np.ndarray:
    """Per-row IWAE estimate ``log p̂ = logsumexp_k(log w) - log k``,
    max-stabilized exactly like the bound itself."""
    m = np.max(log_w, axis=0)
    return m + np.log(np.mean(np.exp(log_w - m), axis=0))


def _weight_stats(log_w: np.ndarray) -> Dict[str, float]:
    """Host twin of diagnostics.weight_diagnostics + the NLL the serving
    row delivers."""
    k = log_w.shape[0]
    lse1 = np.max(log_w, axis=0) + np.log(
        np.sum(np.exp(log_w - np.max(log_w, axis=0)), axis=0))
    lse2 = np.max(2.0 * log_w, axis=0) + np.log(
        np.sum(np.exp(2.0 * log_w - np.max(2.0 * log_w, axis=0)), axis=0))
    ess = np.exp(2.0 * lse1 - lse2)
    return {
        "batch_nll": float(-np.mean(_row_log_phat(log_w))),
        "ess_frac": float(np.mean(ess) / k),
        "log_weight_var": float(np.mean(np.var(log_w, axis=0))),
    }


def statistical_parity(log_w_ref: np.ndarray, log_w_test: np.ndarray,
                       tol: ParityTolerances) -> Dict:
    """Gate one low-precision leg against the fp32 oracle.

    `log_w_ref` / `log_w_test` are ``[k, B]`` log-weight matrices over the
    same rows, seeds, and k (shape mismatch is a harness bug and raises).
    Returns a JSON-ready verdict::

        {"accepted": bool,
         "deltas":   {row_abs_max, row_rel_max, batch_nll, ess_frac,
                      log_weight_var, log_weight_var_rel},
         "ref":      {batch_nll, ess_frac, log_weight_var},
         "test":     {...},
         "failures": ["batch_nll 0.31 exceeds 0.25", ...]}

    Gated deltas are ``row_rel_max`` / ``batch_nll`` / ``ess_frac`` /
    ``log_weight_var_rel`` (the absolute ``row_abs_max`` and
    ``log_weight_var`` ride along for the artifact); ``failures`` is empty
    iff ``accepted``. A NaN anywhere in the test leg fails every gate it
    reaches (NaN comparisons are False, so ``accepted`` can never be True
    off a NaN delta — pinned by the unit tests).
    """
    if log_w_ref.shape != log_w_test.shape:
        raise ValueError(f"log-weight shapes differ: oracle "
                         f"{log_w_ref.shape} vs test {log_w_test.shape}; "
                         f"parity legs must share rows, seeds, and k")
    ref = _weight_stats(log_w_ref)
    test = _weight_stats(log_w_test)
    row_abs = float(np.max(np.abs(
        _row_log_phat(log_w_test) - _row_log_phat(log_w_ref))))
    var_abs = float(abs(test["log_weight_var"] - ref["log_weight_var"]))
    deltas = {
        "row_abs_max": row_abs,
        "row_rel_max": row_abs / max(1.0, abs(ref["batch_nll"])),
        "batch_nll": float(abs(test["batch_nll"] - ref["batch_nll"])),
        "ess_frac": float(abs(test["ess_frac"] - ref["ess_frac"])),
        "log_weight_var": var_abs,
        "log_weight_var_rel": var_abs / max(1.0, ref["log_weight_var"]),
    }
    bounds = {
        "row_rel_max": tol.max_row_rel_delta,
        "batch_nll": tol.max_batch_nll_delta,
        "ess_frac": tol.max_ess_frac_drift,
        "log_weight_var_rel": tol.max_log_weight_var_rel_drift,
    }
    failures = [f"{name} {deltas[name]:.6g} exceeds {bounds[name]:g}"
                for name in bounds
                if not deltas[name] <= bounds[name]]   # NaN-safe: not <=
    return {"accepted": not failures, "deltas": deltas,
            "ref": ref, "test": test, "failures": failures}
