"""Continuous profiling plane: per-dispatch device-time attribution.

``iwae-cost`` (PR 11) predicts roofline/MFU *statically* and ``bench.py``
measures it in one-shot offline runs; nothing in the serving path noticed
when a warm replica quietly degraded.  This module closes the loop: the
completion thread already owns the pipeline's ONE blocking device→host
fetch, so the measured device interval of every dispatched batch is
available for free — :class:`DispatchProfiler` stamps it per
``(model, program, bucket, k-class)`` into ``prof/*`` instruments and
derives **live measured MFU / bandwidth gauges** against the chip peak
tables (utils/flops.py) and each program's ``static_cost`` record from the
AOT executable store (utils/compile_cache.static_cost_records — the same
record the store bills its LRU budget with).

Three layers, all host-side metadata (profiling never touches seeds,
payloads, or program shapes — results are bitwise identical on/off, and
the off mode records nothing at all):

* **attribution** — ``prof/device_s/<key>`` histograms (one log-spaced
  histogram per attribution key) + ``prof/dispatches`` / ``prof/rows``
  counters: where device time actually goes, per program and shape, under
  live traffic — the per-request-variable-k future (adaptive-k, ROADMAP
  item 2) is un-debuggable without this split;
* **measured-vs-static gauges** — ``prof/mfu/<key>`` (measured matmul
  FLOP/s over the chip's bf16 peak), ``prof/hbm_frac/<key>`` (measured
  bytes/s over peak HBM bandwidth, numerator = the static record's
  perfect-fusion traffic lower bound), and ``prof/ceiling_ratio/<key>``
  (measured seconds over the static roofline floor — how far above "as
  fast as the hardware allows" this program actually runs);
* **drift detection** — a per-key EWMA baseline (mean + variance) of the
  device interval; once armed (``warmup_samples``), a sample departing its
  own baseline by ``z_threshold`` sigmas *upward* emits one typed
  ``prof/drift`` finding into a bounded ring (:meth:`findings`), counts
  ``prof/drift``, and publishes ``prof/z/<key>`` — the "replica quietly
  got slow" alarm the autoscaler's burn rates can't see at low traffic.

The serving engines attach a profiler per engine (serving/engine.py
``profiling=``; on by default — the per-dispatch cost is a handful of
dict/float ops on the completion thread, measured honestly in
``results/profiling_bench.json``).  The live snapshot is served at
``/prof`` by the metrics HTTP server (telemetry/exporters.py) and read by
the ``iwae-prof`` CLI; schema pinned in tests/test_telemetry.py.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from collections import deque
from typing import Dict, List, Optional

from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

__all__ = ["ProfilingConfig", "DispatchProfiler", "DriftFinding",
           "detect_chip_peaks"]


def detect_chip_peaks() -> Dict[str, Optional[float]]:
    """``{"peak_flops", "peak_hbm_bytes", "source"}`` for the local chip.

    TPU hosts resolve through the published per-generation tables
    (utils/flops.py); any other platform yields None peaks — the MFU /
    bandwidth gauges are then simply not published (never a fabricated
    denominator, the bench.py contract), while device-time attribution
    and drift detection run everywhere.  Fail-soft by design: this is
    called from engine construction, and a backend probe failure must
    degrade to "no peaks", not kill serving.
    """
    try:
        import jax

        from iwae_replication_project_tpu.utils.flops import (
            peak_flops_for_kind,
            peak_hbm_bytes_for_kind,
        )
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", dev.platform)
        if dev.platform != "tpu":
            return {"peak_flops": None, "peak_hbm_bytes": None,
                    "source": f"no peak table for platform "
                              f"{dev.platform!r} (kind {kind!r})"}
        flops, f_src = peak_flops_for_kind(kind)
        hbm, _ = peak_hbm_bytes_for_kind(kind)
        return {"peak_flops": flops, "peak_hbm_bytes": hbm, "source": f_src}
    except Exception as e:  # pragma: no cover - backend-dependent
        return {"peak_flops": None, "peak_hbm_bytes": None,
                "source": f"chip detection failed: {e}"}


@dataclasses.dataclass(frozen=True)
class ProfilingConfig:
    """Knobs of one engine's profiler (frozen: share across threads).

    ``ewma_alpha`` weights the exponential baseline (higher = faster
    adaptation, shorter memory); ``z_threshold`` is the drift alarm bound
    in baseline sigmas; ``warmup_samples`` arms the detector only after
    the baseline has seen that many intervals per key (a cold program's
    first dispatches are not drift); ``min_sigma_frac`` floors the
    baseline sigma at that fraction of the EWMA mean, so a near-constant
    baseline does not page on measurement jitter. ``peak_flops`` /
    ``peak_hbm_bytes`` override chip detection (the bench.py
    ``--peak-flops`` convention — how CPU CI smokes exercise the MFU
    gauges); None = detect."""

    enabled: bool = True
    ewma_alpha: float = 0.2
    z_threshold: float = 6.0
    warmup_samples: int = 8
    min_sigma_frac: float = 0.05
    max_findings: int = 256
    peak_flops: Optional[float] = None
    peak_hbm_bytes: Optional[float] = None

    def __post_init__(self):
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got "
                             f"{self.ewma_alpha}")
        if self.z_threshold <= 0:
            raise ValueError(f"z_threshold must be > 0, got "
                             f"{self.z_threshold}")
        if self.warmup_samples < 2:
            raise ValueError(f"warmup_samples must be >= 2, got "
                             f"{self.warmup_samples} — a baseline with "
                             f"fewer samples has no variance to test "
                             f"against")


@dataclasses.dataclass(frozen=True)
class DriftFinding:
    """One typed ``prof/drift`` finding: a warm program's device interval
    departed its own EWMA baseline by ``z`` sigmas (schema pinned in
    tests/test_telemetry.py; ``to_dict`` is the wire/CLI form)."""

    kind: str
    key: str
    program: str
    model: Optional[str]
    bucket: int
    k_class: str
    measured_s: float
    baseline_s: float
    sigma_s: float
    z: float
    ratio: float
    seq: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _KeyState:
    """Per-attribution-key EWMA baseline (owner's lock guards it)."""

    __slots__ = ("count", "ewma", "ewvar", "last_s", "last_mfu",
                 "last_hbm_frac", "last_ceiling_ratio", "last_z",
                 "ewma_k_used", "last_k_used")

    def __init__(self):
        self.count = 0
        self.ewma = 0.0
        self.ewvar = 0.0
        self.last_s = 0.0
        self.last_mfu: Optional[float] = None
        self.last_hbm_frac: Optional[float] = None
        self.last_ceiling_ratio: Optional[float] = None
        self.last_z: Optional[float] = None
        #: per-row sample-count attribution (adaptive-k dispatches only;
        #: None = this key never reported samples — fixed-k traffic)
        self.ewma_k_used: Optional[float] = None
        self.last_k_used: Optional[float] = None


class DispatchProfiler:
    """Per-dispatch device-time attributor + drift detector (module doc).

    ``registry`` is where the ``prof/*`` instruments land — the serving
    engine passes its own metrics registry so the profiling plane rides
    the same Prometheus page as the latency split.  ``label`` names the
    tenant (the engine's ``store_label`` composite, e.g.
    ``mnist@bf16``); None keeps unlabeled keys.  Thread-safe: ``observe``
    runs on the completion thread, snapshots/scrapes on any other; the
    profiler's lock is a leaf (registry publication happens OUTSIDE it,
    the SLOMonitor discipline — the lock graph stays a tree)."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 config: Optional[ProfilingConfig] = None,
                 label: Optional[str] = None,
                 peaks: Optional[Dict[str, Optional[float]]] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self.config = config if config is not None else ProfilingConfig()
        self.label = label
        if peaks is None:
            peaks = detect_chip_peaks()
        if self.config.peak_flops is not None:
            peaks = dict(peaks)
            peaks["peak_flops"] = float(self.config.peak_flops)
            peaks["source"] = "explicit ProfilingConfig.peak_flops override"
        if self.config.peak_hbm_bytes is not None:
            peaks = dict(peaks)
            peaks["peak_hbm_bytes"] = float(self.config.peak_hbm_bytes)
        self.peaks = peaks
        self._lock = threading.Lock()
        #: key -> _KeyState; guarded by _lock
        self._keys: Dict[str, _KeyState] = {}
        #: bounded typed prof/drift finding ring; guarded by _lock
        self._findings: deque = deque(maxlen=int(self.config.max_findings))
        self._seq = 0
        self._dropped_findings = 0

    def _key(self, program: str, bucket: int, k_class) -> str:
        base = f"{program}/b{bucket}/k{k_class}"
        return f"{self.label}/{base}" if self.label else base

    @staticmethod
    def static_floor_s(cost: Optional[dict],
                       peaks: Dict[str, Optional[float]]) -> Optional[float]:
        """The roofline floor: the static cost record's compute and
        traffic legs each at chip peak, whichever binds — the seconds the
        hardware *cannot* beat for this program.  None when the record or
        both peaks are missing (the ceiling-ratio gauge is then not
        published rather than divided by a guess)."""
        if not cost:
            return None
        floor = 0.0
        pf, pb = peaks.get("peak_flops"), peaks.get("peak_hbm_bytes")
        if pf and cost.get("flops"):
            floor = max(floor, float(cost["flops"]) / pf)
        if pb and cost.get("bytes_accessed_fused"):
            floor = max(floor, float(cost["bytes_accessed_fused"]) / pb)
        return floor or None

    def observe(self, *, program: str, bucket: int, k_class,
                rows: int, device_s: float,
                flops: Optional[float] = None,
                cost: Optional[dict] = None,
                samples: Optional[float] = None) -> Optional[DriftFinding]:
        """Account one completed dispatch; returns the drift finding when
        this sample tripped the detector (else None).

        ``device_s`` is the completion thread's measured enqueue→fetched
        interval for the whole batch; ``flops`` the analytic matmul-FLOP
        count of the batch (utils/flops.py — None skips the MFU gauge);
        ``cost`` the program's static cost record from the executable
        store (None skips bandwidth/ceiling gauges); ``samples`` the total
        importance samples the batch actually drew (adaptive-k dispatches
        — attribution at measured ``k_used``, so device-time burn can't be
        gamed by easy rows charged at the cap; None for fixed-k traffic).
        Non-positive intervals (a clock artifact) are clamped to zero,
        counted, and excluded from the baseline — the detector must never
        learn from (or alarm on) a negative duration."""
        cfg = self.config
        if device_s <= 0.0:
            self.registry.counter("prof/clamped_intervals").inc()
            return None
        key = self._key(program, bucket, k_class)
        mfu = hbm_frac = ceiling_ratio = None
        pf = self.peaks.get("peak_flops")
        pb = self.peaks.get("peak_hbm_bytes")
        if flops and pf:
            mfu = (flops / device_s) / pf
        if cost and pb and cost.get("bytes_accessed_fused"):
            hbm_frac = (float(cost["bytes_accessed_fused"]) / device_s) / pb
        floor = self.static_floor_s(cost, self.peaks)
        if floor:
            ceiling_ratio = device_s / floor

        finding = None
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                st = self._keys[key] = _KeyState()
            z = None
            if st.count >= cfg.warmup_samples:
                sigma = math.sqrt(max(st.ewvar, 0.0))
                sigma = max(sigma, cfg.min_sigma_frac * st.ewma)
                if sigma > 0.0:
                    z = (device_s - st.ewma) / sigma
                    if z > cfg.z_threshold:
                        self._seq += 1
                        if len(self._findings) == self._findings.maxlen:
                            self._dropped_findings += 1
                        finding = DriftFinding(
                            kind="prof/drift", key=key, program=program,
                            model=self.label, bucket=int(bucket),
                            k_class=str(k_class),
                            measured_s=float(device_s),
                            baseline_s=float(st.ewma),
                            sigma_s=float(sigma), z=float(z),
                            ratio=float(device_s / st.ewma)
                            if st.ewma > 0 else float("inf"),
                            seq=self._seq)
                        self._findings.append(finding)
            # baseline update AFTER the check (a drifted sample still
            # feeds the EWMA: a persistent slowdown converges to the new
            # normal instead of alarming forever)
            if st.count == 0:
                st.ewma = float(device_s)
            else:
                delta = device_s - st.ewma
                st.ewma += cfg.ewma_alpha * delta
                st.ewvar = ((1.0 - cfg.ewma_alpha)
                            * (st.ewvar + cfg.ewma_alpha * delta * delta))
            st.count += 1
            st.last_s = float(device_s)
            st.last_mfu = mfu
            st.last_hbm_frac = hbm_frac
            st.last_ceiling_ratio = ceiling_ratio
            st.last_z = z
            if samples is not None and rows > 0:
                k_used = float(samples) / float(rows)
                st.last_k_used = k_used
                st.ewma_k_used = k_used if st.ewma_k_used is None else \
                    st.ewma_k_used + cfg.ewma_alpha * (k_used
                                                       - st.ewma_k_used)

        # publish OUTSIDE the profiler lock (leaf-lock discipline: the
        # registry has its own lock and never calls back)
        reg = self.registry
        reg.histogram(f"prof/device_s/{key}").record(device_s)
        reg.counter("prof/dispatches").inc()
        reg.counter("prof/rows").inc(int(rows))
        if mfu is not None:
            reg.gauge(f"prof/mfu/{key}").set(mfu)
        if hbm_frac is not None:
            reg.gauge(f"prof/hbm_frac/{key}").set(hbm_frac)
        if ceiling_ratio is not None:
            reg.gauge(f"prof/ceiling_ratio/{key}").set(ceiling_ratio)
        if z is not None:
            reg.gauge(f"prof/z/{key}").set(z)
        if samples is not None:
            reg.counter("prof/samples").inc(int(samples))
            if rows > 0:
                reg.gauge(f"prof/k_used/{key}").set(float(samples)
                                                    / float(rows))
        if finding is not None:
            reg.counter("prof/drift").inc()
        return finding

    # -- read surfaces ------------------------------------------------------

    def findings(self, limit: Optional[int] = None) -> List[dict]:
        """The retained typed ``prof/drift`` findings, oldest first
        (``limit`` keeps the most recent N)."""
        with self._lock:
            docs = [f.to_dict() for f in self._findings]
        return docs[-limit:] if limit is not None else docs

    def snapshot(self) -> dict:
        """The profiling-plane document (``/prof``, ``iwae-prof``; schema
        pinned in tests/test_telemetry.py): per-key measured state +
        EWMA baselines, the chip peaks in use, and the finding ring."""
        with self._lock:
            keys = {}
            for key, st in self._keys.items():
                doc = {
                    "count": st.count,
                    "ewma_s": st.ewma,
                    "sigma_s": math.sqrt(max(st.ewvar, 0.0)),
                    "last_s": st.last_s,
                    "last_mfu": st.last_mfu,
                    "last_hbm_frac": st.last_hbm_frac,
                    "last_ceiling_ratio": st.last_ceiling_ratio,
                    "last_z": st.last_z,
                }
                # k_used attribution only exists for keys that reported
                # sample counts (adaptive-k traffic) — fixed-k keys keep
                # the original schema (pinned in tests/test_telemetry.py)
                if st.ewma_k_used is not None:
                    doc["ewma_k_used"] = st.ewma_k_used
                    doc["last_k_used"] = st.last_k_used
                keys[key] = doc
            findings = [f.to_dict() for f in self._findings]
            dropped = self._dropped_findings
        return {
            "label": self.label,
            "peaks": dict(self.peaks),
            "config": {
                "ewma_alpha": self.config.ewma_alpha,
                "z_threshold": self.config.z_threshold,
                "warmup_samples": self.config.warmup_samples,
            },
            "keys": keys,
            "findings": findings,
            "dropped_findings": dropped,
        }
