"""The metric registry: ONE implementation of counters/gauges/histograms.

Before this module the framework carried three disjoint metric stores —
``serving/metrics.py`` (private log-spaced histograms + a counter dict),
``utils/logging.py`` (JSONL/TensorBoard rows with no aggregation), and
``utils/profiling.py`` (StepTimer percentiles from a sorted list). The
registry is the single spine they all report through: named instruments,
created on first touch, thread-safe, exportable as a nested snapshot, a flat
scalar row (for :class:`~..utils.logging.MetricsLogger`), or a
Prometheus-style text page (:mod:`.exporters`).

Design points:

* **log-spaced histograms**, not reservoirs: O(1) per record, every event
  accounted at any volume, and a quantile readout within one bin width
  (~33% at 8 bins/decade) of truth — the serving engine's shedding policy
  and the span tracer both want a cheap always-on gauge, not a sample;
* **get-or-create by name**: call sites never hold instrument handles across
  module boundaries, so exporters see every metric without wiring;
* a **process-default registry** (:func:`get_registry`) for cross-cutting
  instruments (spans, driver diagnostics); subsystems that need isolation
  (one :class:`~..serving.metrics.ServingMetrics` per engine, tests) build
  their own instance — same types, same exporters.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Union

#: default histogram geometry: 8 bins per decade from 1e-6 to 1e3 (+overflow)
#: — for seconds this spans 1 us .. 1000 s, the whole latency range the
#: framework observes, at ~33% quantile resolution
BINS_PER_DECADE = 8
HIST_LO = 1e-6
HIST_DECADES = 9


class Counter:
    """Monotonic counter (int-preserving until a float is added)."""

    __slots__ = ("_lock", "_v")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._v: Union[int, float] = 0

    def inc(self, n: Union[int, float] = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> Union[int, float]:
        # scrape-side reads hold the same lock the writers hold — the
        # discipline analysis/rules/concurrency.py enforces on this module
        # (a bare read is benign for a float in CPython, but the mixed
        # regime is exactly what the checker exists to keep out)
        with self._lock:
            return self._v


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("_lock", "_v")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._v: float = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        with self._lock:  # lock-consistent read; see Counter.value
            return self._v


class Histogram:
    """Log-spaced-bin histogram with percentile readout.

    `lo` is the lower edge of the first bin; values at or below it land in
    bin 0, values past the top decade in the overflow bin. ``percentile``
    returns the *upper bound* of the bin holding the q-quantile — an upper
    estimate within one bin width of truth.
    """

    __slots__ = ("_lock", "lo", "bins_per_decade", "counts", "n", "total",
                 "vmax", "exemplars")

    def __init__(self, lock: Optional[threading.Lock] = None,
                 lo: float = HIST_LO, bins_per_decade: int = BINS_PER_DECADE,
                 decades: int = HIST_DECADES):
        self._lock = lock or threading.Lock()
        self.lo = lo
        self.bins_per_decade = bins_per_decade
        self.counts: List[int] = [0] * (bins_per_decade * decades + 1)
        self.n = 0
        self.total = 0.0
        self.vmax = 0.0  # exact observed max: clamps the percentile upper
        #                  bounds (a quantile can never exceed the max, and
        #                  the overflow bin's nominal bound is meaningless)
        #: per-bin last (value, label) exemplar — e.g. the trace id of a
        #: request observed in that latency bin, so a quantile readout can
        #: name a REAL trace in the flight recorder. Lazily allocated: a
        #: histogram never fed exemplars pays nothing.
        self.exemplars: Optional[Dict[int, tuple]] = None

    def _bin_index(self, v: float) -> int:
        if v <= self.lo:
            return 0
        i = int(math.log10(v / self.lo) * self.bins_per_decade)
        return min(i, len(self.counts) - 1)

    def _bin_upper(self, i: int) -> float:
        return self.lo * 10.0 ** ((i + 1) / self.bins_per_decade)

    def record(self, v: float, exemplar=None) -> None:
        with self._lock:
            i = self._bin_index(v)
            self.counts[i] += 1
            self.n += 1
            self.total += v
            if v > self.vmax:
                self.vmax = v
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (v, exemplar)

    def percentile(self, q: float) -> Optional[float]:
        """Upper bound of the bin holding the q-quantile (q in [0, 1]),
        clamped by the exact observed max."""
        with self._lock:
            return self._percentile(q)

    def exemplar_near(self, q: float) -> Optional[Dict[str, object]]:
        """``{"value", "label"}`` of the exemplar nearest the q-quantile's
        bin (ties resolve downward), or None when no exemplars were ever
        recorded — the quantile -> real-trace link the flight recorder
        resolves."""
        with self._lock:
            if not self.exemplars or self.n == 0:
                return None
            target = q * self.n
            seen = 0
            qi = len(self.counts) - 1
            for i, c in enumerate(self.counts):
                seen += c
                if seen >= target:
                    qi = i
                    break
            best = min(self.exemplars, key=lambda b: (abs(b - qi), b))
            v, label = self.exemplars[best]
            return {"value": v, "label": label}

    def _percentile(self, q: float) -> Optional[float]:
        # caller holds self._lock: counts/n/vmax are read as one consistent
        # state (the writers mutate them together under the same lock)
        if self.n == 0:
            return None
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return min(self._bin_upper(i), self.vmax)
        return self.vmax

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:  # one consistent view across count/mean/percentiles
            mean = self.total / self.n if self.n else None
            return {"count": self.n, "mean": mean,
                    # the exact running sum: exporters emit it verbatim as
                    # the Prometheus `_sum` instead of reconstructing
                    # mean * count (which re-rounds what we already track)
                    "total": self.total if self.n else None,
                    "p50": self._percentile(0.50),
                    "p95": self._percentile(0.95),
                    "p99": self._percentile(0.99),
                    "max": self.vmax if self.n else None}


class MetricRegistry:
    """Named instruments, created on first touch; one lock per registry.

    Names are slash-separated paths (``"latency/score/b4"``,
    ``"span/train/stage"``); exporters keep them verbatim (JSONL/TB) or
    sanitize them (Prometheus).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def _get_or_create(self, store: dict, name: str, make):
        inst = store.get(name)
        if inst is None:
            with self._lock:
                inst = store.get(name)
                if inst is None:
                    for other in (self._counters, self._gauges,
                                  self._histograms):
                        if other is not store and name in other:
                            raise ValueError(
                                f"metric {name!r} already registered as a "
                                f"different instrument type")
                    inst = store[name] = make()
        return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(self._counters, name,
                                   lambda: Counter(self._lock))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(self._gauges, name,
                                   lambda: Gauge(self._lock))

    def histogram(self, name: str, factory=None) -> Histogram:
        """`factory` customizes the histogram class/geometry on FIRST touch
        (later calls return the existing instrument unchanged)."""
        make = (lambda: factory(self._lock)) if factory is not None \
            else (lambda: Histogram(self._lock))
        return self._get_or_create(self._histograms, name, make)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """Nested document: counter/gauge values + histogram summaries."""
        with self._lock:
            counters = {k: c._v for k, c in self._counters.items()}
            gauges = {k: g._v for k, g in self._gauges.items()}
            hists = list(self._histograms.items())
        return {"counters": counters, "gauges": gauges,
                "histograms": {k: h.summary() for k, h in sorted(hists)}}

    def rows(self, prefix: str = "") -> Dict[str, float]:
        """Flat ``name -> float`` rows for MetricsLogger (JSONL + TB):
        counters/gauges verbatim, histograms as ``<name>/<stat>``."""
        snap = self.snapshot()
        out: Dict[str, float] = {}
        for k, v in snap["counters"].items():
            out[prefix + k] = float(v)
        for k, v in snap["gauges"].items():
            out[prefix + k] = float(v)
        for name, s in snap["histograms"].items():
            for stat, v in s.items():
                if v is not None:
                    out[f"{prefix}{name}/{stat}"] = float(v)
        return out


#: the process-default registry: spans, driver diagnostics, and anything
#: cross-cutting report here; subsystem-scoped registries are built per owner
_DEFAULT = MetricRegistry()


def get_registry() -> MetricRegistry:
    return _DEFAULT
