"""Service-level objectives: per-(model, op) burn-rate gauges.

The elastic-fleet control loop (ROADMAP item 2) needs one signal above raw
latency histograms: *how fast is each (model, op) burning its error
budget?*  This module declares latency/availability objectives and
publishes multi-window burn rates as ordinary registry gauges, so they
ride the same Prometheus page as everything else — the admission signal an
autoscaler consumes.

Definitions (the standard SRE accounting):

* a request is a **latency violation** when it errored or took longer than
  the objective's ``latency_s`` (an errored request is not a fast good
  response);
* a request is an **availability violation** when its typed error code is
  server-attributable (``DEFAULT_ERROR_CODES``: internal / timeout /
  unavailable / overloaded).  ``bad_request`` and ``quota_exceeded`` are
  the client's doing and never burn the server's budget (the front end
  does not even observe ``bad_request`` traffic — a garbage op name must
  not mint gauges);
* **burn rate** over a window = observed violation fraction ÷ allowed
  violation fraction (``1 - target``).  1.0 means the budget burns exactly
  as fast as it refills; a fast-window burn ≫ 1 with the slow window
  confirming is the page/scale-up signal.

Windows are bucketed rings (``buckets`` slots per window, advanced by the
injectable clock), so ``observe`` is O(1) amortized and the gauges read
the trailing window, not process-lifetime averages.

Published instruments, per key (``<model>/<op>``, or ``<op>`` for the
unlabeled single-model tier):

* gauges ``slo/<key>/latency_burn_<win>`` and
  ``slo/<key>/availability_burn_<win>`` for every window (default ``5m``
  and ``1h``);
* counters ``slo/<key>/requests``, ``slo/<key>/latency_violations``,
  ``slo/<key>/errors``; plus the unkeyed ``slo/clock_regressions``
  (injected-clock steps backwards are clamped to the high-water mark and
  counted — windows never rewind and burns never go negative).

Schema pinned in tests/test_telemetry.py.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from iwae_replication_project_tpu.telemetry.registry import MetricRegistry

__all__ = ["SLOObjective", "SLOMonitor", "DEFAULT_ERROR_CODES",
           "DEFAULT_WINDOWS", "peak_burns", "window_requests"]

#: typed protocol codes that count against the availability objective —
#: the server-attributable half of protocol.ERROR_CODES
DEFAULT_ERROR_CODES = frozenset(
    {"internal", "timeout", "unavailable", "overloaded"})

#: (window seconds, gauge label): the classic fast/slow multi-window pair
DEFAULT_WINDOWS: Tuple[Tuple[float, str], ...] = ((300.0, "5m"),
                                                  (3600.0, "1h"))


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One (model, op)'s objectives (frozen: share across threads).

    ``latency_s`` is the per-request threshold, ``latency_target`` the
    fraction of requests that must beat it, ``availability_target`` the
    fraction that must not error."""

    latency_s: float = 0.5
    latency_target: float = 0.99
    availability_target: float = 0.999

    def __post_init__(self):
        if self.latency_s <= 0:
            raise ValueError(f"latency_s must be > 0, got {self.latency_s}")
        for name in ("latency_target", "availability_target"):
            v = getattr(self, name)
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1), got {v} — a "
                                 f"target of 1.0 makes every burn rate "
                                 f"infinite")


class _Ring:
    """One bucketed sliding window of (total, latency-bad, error-bad)
    counts.  No lock of its own: the owning monitor's lock guards it."""

    __slots__ = ("width_s", "total", "bad_lat", "bad_err", "epoch")

    def __init__(self, window_s: float, buckets: int):
        self.width_s = window_s / buckets
        self.total = [0] * buckets
        self.bad_lat = [0] * buckets
        self.bad_err = [0] * buckets
        self.epoch: Optional[int] = None   # absolute index of current slot

    def _advance(self, now: float) -> None:
        e = int(now / self.width_s)
        n = len(self.total)
        if self.epoch is None:
            self.epoch = e
            return
        # step clamped to [0, n]: a backwards clock (already clamped by the
        # monitor, but this ring must be safe standalone) must not clear
        # slots or move the epoch back — time only advances here
        step = min(max(e - self.epoch, 0), n)
        for j in range(1, step + 1):
            i = (self.epoch + j) % n
            self.total[i] = self.bad_lat[i] = self.bad_err[i] = 0
        if e > self.epoch:
            self.epoch = e

    def observe(self, now: float, lat_bad: bool, err_bad: bool) -> None:
        self._advance(now)
        i = self.epoch % len(self.total)
        self.total[i] += 1
        self.bad_lat[i] += lat_bad
        self.bad_err[i] += err_bad

    def fractions(self, now: float) -> Tuple[float, float, int]:
        self._advance(now)
        t = sum(self.total)
        if not t:
            return 0.0, 0.0, 0
        return sum(self.bad_lat) / t, sum(self.bad_err) / t, t


class SLOMonitor:
    """Observe request outcomes; publish burn-rate gauges per (model, op).

    ``objectives`` maps ``(model, op)`` (model ``None`` = the unlabeled
    lane) to :class:`SLOObjective`; anything unlisted uses ``default``.
    ``registry`` is where the gauges/counters land — the serving tier
    passes its router registry so the burn rates share the fleet's
    Prometheus page.  The clock is injectable (tests drive window
    rotation with a fake clock, like quotas and the batcher)."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 default: SLOObjective = SLOObjective(),
                 objectives: Optional[Dict[Tuple[Optional[str], str],
                                           SLOObjective]] = None,
                 windows: Sequence[Tuple[float, str]] = DEFAULT_WINDOWS,
                 buckets_per_window: int = 30,
                 error_codes: frozenset = DEFAULT_ERROR_CODES,
                 clock: Callable[[], float] = time.monotonic):
        if not windows:
            raise ValueError("at least one burn-rate window is required")
        self.registry = registry if registry is not None else MetricRegistry()
        self.default = default
        self.objectives = dict(objectives) if objectives else {}
        self.windows = tuple((float(w), str(label)) for w, label in windows)
        self.error_codes = frozenset(error_codes)
        self._buckets = int(buckets_per_window)
        self._clock = clock
        self._lock = threading.Lock()
        #: key -> [one _Ring per window]; guarded by _lock
        self._rings: Dict[str, list] = {}
        #: high-water clock mark, guarded by _lock — see _now_clamped
        self._last_now: Optional[float] = None

    def _now_clamped(self) -> Tuple[float, bool]:
        """Read the clock, clamped to its own high-water mark.

        An injectable clock is not guaranteed monotonic (a wall-clock
        passed by mistake, NTP step, or a test fixture rewinding): feeding
        a backwards ``now`` into the rings would either resurrect stale
        slots or mint negative burn windows.  Policy per the observability
        plan: CLAMP to the last seen time and COUNT the regression —
        never crash, never go back.  Caller must hold ``_lock``."""
        now = self._clock()
        regressed = self._last_now is not None and now < self._last_now
        if regressed:
            now = self._last_now
        else:
            self._last_now = now
        return now, regressed

    @staticmethod
    def key_for(model: Optional[str], op: str) -> str:
        """The gauge-name key (mirrors ServingMetrics' histogram keys)."""
        return f"{model}/{op}" if model else op

    def objective_for(self, model: Optional[str], op: str) -> SLOObjective:
        obj = self.objectives.get((model, op))
        if obj is None and model is not None:
            obj = self.objectives.get((None, op))   # op-wide fallback
        return obj if obj is not None else self.default

    def observe(self, op: str, latency_s: float, *,
                model: Optional[str] = None,
                error_code: Optional[str] = None) -> None:
        """Account one finished request and republish its key's gauges."""
        obj = self.objective_for(model, op)
        err_bad = error_code is not None and error_code in self.error_codes
        lat_bad = err_bad or latency_s > obj.latency_s
        key = self.key_for(model, op)
        with self._lock:
            now, regressed = self._now_clamped()
            rings = self._rings.get(key)
            if rings is None:
                rings = self._rings[key] = [
                    _Ring(w, self._buckets) for w, _ in self.windows]
            fracs = []
            for ring in rings:
                ring.observe(now, lat_bad, err_bad)
                fracs.append(ring.fractions(now))
        # publish OUTSIDE the monitor lock: the registry has its own lock
        # and the lock graph stays a tree by construction
        if regressed:
            self.registry.counter("slo/clock_regressions").inc()
        for (_, label), (lat_frac, err_frac, _n) in zip(self.windows, fracs):
            self.registry.gauge(f"slo/{key}/latency_burn_{label}").set(
                lat_frac / (1.0 - obj.latency_target))
            self.registry.gauge(f"slo/{key}/availability_burn_{label}").set(
                err_frac / (1.0 - obj.availability_target))
        self.registry.counter(f"slo/{key}/requests").inc()
        if lat_bad:
            self.registry.counter(f"slo/{key}/latency_violations").inc()
        if err_bad:
            self.registry.counter(f"slo/{key}/errors").inc()

    def snapshot(self) -> Dict[str, dict]:
        """Current burn rates per key (the wire/bench-facing document;
        schema pinned in tests/test_telemetry.py)."""
        with self._lock:
            now, _ = self._now_clamped()
            keys = {key: [r.fractions(now) for r in rings]
                    for key, rings in self._rings.items()}
        out: Dict[str, dict] = {}
        for key, fracs in keys.items():
            model, _, op = key.rpartition("/")
            obj = self.objective_for(model or None, op or key)
            wins = {}
            for (_, label), (lat_frac, err_frac, n) in zip(self.windows,
                                                           fracs):
                wins[label] = {
                    "requests": n,
                    "latency_burn": lat_frac / (1.0 - obj.latency_target),
                    "availability_burn":
                        err_frac / (1.0 - obj.availability_target),
                }
            out[key] = {
                "objective": dataclasses.asdict(obj),
                "windows": wins,
            }
        return out


# -- snapshot reductions (the autoscaler's scalar signals) -------------------
#
# Pure functions over the snapshot() document — NOT monitor methods — so the
# fleet controller applies the identical reduction to a local monitor's
# snapshot and to one shipped over the wire by the `slo` control op (a
# fleet-of-fleets parent scales children it only sees as JSON).

def peak_burns(snapshot: Dict[str, dict]) -> Dict[str, float]:
    """Worst burn rate per window label across every (model, op) key and
    both objectives (latency and availability).

    One scalar per window is what the scaling decision consumes: the fleet
    must grow when ANY class burns its budget — averaging across keys would
    let a small hot tenant drown under a large cold one. Empty snapshot
    (no traffic yet) reads as 0.0 burns for no windows; callers treat a
    missing label as burn 0."""
    out: Dict[str, float] = {}
    for doc in snapshot.values():
        for label, win in doc.get("windows", {}).items():
            burn = max(float(win.get("latency_burn", 0.0)),
                       float(win.get("availability_burn", 0.0)))
            out[label] = max(out.get(label, 0.0), burn)
    return out


def window_requests(snapshot: Dict[str, dict]) -> Dict[str, int]:
    """Total requests per window label summed across keys — the idleness
    half of the scaling signal (a fleet with zero trailing-window traffic
    and no burn is a scale-down candidate)."""
    out: Dict[str, int] = {}
    for doc in snapshot.values():
        for label, win in doc.get("windows", {}).items():
            out[label] = out.get(label, 0) + int(win.get("requests", 0))
    return out
