"""Hierarchical span tracing: one name, three sinks.

``with span("train/stage"):`` nests (a thread-local stack joins names into a
path like ``train/stage/aot/epoch``), and on exit the wall time lands in

* the metric registry — histogram ``span/<path>`` (p50/p95/p99 + count),
  exported with everything else (JSONL rows, TensorBoard, Prometheus);
* ``jax.profiler.TraceAnnotation(<path>)`` — the SAME names appear on the
  host timeline of an XLA profiler trace (TensorBoard profile tab), so a
  registry percentile can be cross-checked against the trace event it
  summarizes.

Spans are host-side: around dispatches, stages, request handling. They do
not (cannot) reach inside a jitted program — device-side attribution comes
from the stable program names the framework already bakes into its XLA
modules (``epoch_IWAE_k50`` etc., training/epoch.py).

jax is imported lazily so importing this module (e.g. from
utils/compile_cache.py, which entry points import before configuring jax's
platform) does not initialize jax backends.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

from iwae_replication_project_tpu.telemetry.registry import (
    MetricRegistry,
    get_registry,
)

_tls = threading.local()
_trace_annotation_cls = None  # resolved lazily; False = unavailable


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Optional[str]:
    """The innermost active span's full path on this thread, or None."""
    st = _stack()
    return st[-1] if st else None


def _annotation(path: str):
    global _trace_annotation_cls
    if _trace_annotation_cls is None:
        try:
            import jax
            _trace_annotation_cls = jax.profiler.TraceAnnotation
        except Exception:  # jax absent/too old: spans still time + register
            _trace_annotation_cls = False
    if _trace_annotation_cls is False:
        return contextlib.nullcontext()
    return _trace_annotation_cls(path)


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricRegistry] = None) -> Iterator[str]:
    """Time a host-side section under `name`, nested inside any active span.

    Yields the full path. Exceptions propagate; the span still records (a
    failing dispatch's latency is exactly the one worth seeing).
    """
    reg = registry if registry is not None else get_registry()
    st = _stack()
    path = f"{st[-1]}/{name}" if st else name
    st.append(path)
    t0 = time.perf_counter()
    try:
        with _annotation(path):
            yield path
    finally:
        st.pop()
        reg.histogram(f"span/{path}").record(time.perf_counter() - t0)


def spanned(fn, name: str, registry: Optional[MetricRegistry] = None):
    """Wrap a callable so every invocation runs under ``span(name)``.

    AOT-compatible: the wrapper re-exposes the wrappee's ``.lower`` (what
    :func:`~..utils.compile_cache.aot_call` uses to build executables), so a
    span-wrapped jitted function still routes through the warm-path registry.
    """
    def call(*args, **kwargs):
        with span(name, registry=registry):
            return fn(*args, **kwargs)

    call.__name__ = getattr(fn, "__name__", name)
    call.__qualname__ = getattr(fn, "__qualname__", name)
    if hasattr(fn, "lower"):
        call.lower = fn.lower
    call.__wrapped__ = fn
    return call
