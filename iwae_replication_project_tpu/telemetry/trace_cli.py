"""``iwae-trace``: dump a serving tier's flight recorder.

A pure socket client (no jax, no device — like ``iwae-serve --client``):
connects to a running tier, issues the ``traces`` control op
(serving/frontend/protocol.py), and writes the result as Chrome
trace-event JSON (load the file in ``chrome://tracing`` or Perfetto) or
as the raw trace documents.

Examples::

    iwae-trace 127.0.0.1:7777 --out traces.json     # chrome format
    iwae-trace 127.0.0.1:7777 --raw --limit 8       # raw docs, stdout
    iwae-trace 127.0.0.1:7777 --stats               # recorder accounting
    iwae-trace 127.0.0.1:7777 --trace-id ab12...    # one trace by id

The same data is served over HTTP at ``/traces`` when the tier runs with
``--metrics-port`` — this CLI exists for tiers without the metrics server
and for piping into files/jq.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="iwae-trace",
        description="dump a serving tier's tail-sampled request traces "
                    "as Chrome trace-event JSON")
    ap.add_argument("target", metavar="HOST:PORT",
                    help="a running iwae-serve tier's TCP endpoint")
    ap.add_argument("--out", type=str, default=None,
                    help="write here instead of stdout")
    ap.add_argument("--raw", action="store_true",
                    help="raw flight-recorder trace documents (+ stats) "
                         "instead of Chrome trace-event JSON")
    ap.add_argument("--stats", action="store_true",
                    help="recorder accounting only (kept/dropped/ring "
                         "occupancy), no trace bodies")
    ap.add_argument("--limit", type=int, default=None,
                    help="most recent N traces only")
    ap.add_argument("--trace-id", dest="trace_id", type=str, default=None,
                    help="one trace by id (e.g. from a latency exemplar)")
    ap.add_argument("--json", action="store_true",
                    help="wrap the output in the shared observability-CLI "
                         "envelope (tool/schema/mode/ok/findings/data — "
                         "same convention as iwae-prof --json)")
    return ap


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    from iwae_replication_project_tpu.serving.frontend.client import (
        TierClient, TierError)

    host, _, port = args.target.rpartition(":")
    try:
        cli = TierClient(host or "127.0.0.1", int(port))
    except (OSError, ValueError) as e:
        print(f"iwae-trace: cannot reach tier at {args.target!r}: {e}",
              file=sys.stderr)
        return 2
    try:
        if args.stats:
            doc = cli.traces(limit=0)["stats"]
        else:
            doc = cli.traces(limit=args.limit, trace_id=args.trace_id,
                             fmt=None if args.raw else "chrome")
    except TierError as e:
        print(f"iwae-trace: tier rejected the traces op: {e}",
              file=sys.stderr)
        return 2
    finally:
        cli.close()
    n = len(doc.get("traceEvents", doc.get("traces", []))) \
        if isinstance(doc, dict) else 0
    if args.json:
        # the one --json convention every observability CLI shares; the
        # envelope maker lives with iwae-prof (analysis/regress.py) and
        # the schema is pinned in tests/test_telemetry.py
        from iwae_replication_project_tpu.analysis.regress import (
            make_envelope)
        mode = ("stats" if args.stats
                else "raw" if args.raw else "chrome")
        doc = make_envelope("iwae-trace", mode, ok=True, data=doc)
    text = json.dumps(doc, indent=2)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
        print(f"iwae-trace: wrote {args.out} ({n} "
              f"{'events' if not args.raw else 'traces'})")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
