"""End-to-end request tracing: trace context, spans, tail-sampled recorder.

The serving fleet's latency story used to stop at process-local histograms:
a p99 spike on the Prometheus page could not be traced to WHICH hop, queue,
or dispatch ate the time.  This module is the missing spine — one trace per
request, threaded from the front end through quota admission, router
dispatch/reroute attempts, RemoteEngine hops, and the engine pipeline
stages (queue → coalesce/pad → AOT dispatch → device → fetch), assembled
into a tree and retained by a bounded flight recorder.

Design points:

* **explicit context, not thread-locals** — a serving request hops threads
  (connection reader → dispatcher → completion → router callback), so the
  context object (:class:`TraceContext`: trace id + parent span id +
  recorder) rides the request itself.  :mod:`.spans` (histogram spans)
  stays the cheap always-on aggregate; this module is the per-request
  tree;
* **record everything, retain a sample** — spans are recorded for every
  traced request; *retention* is tail-sampled at trace completion: every
  trace containing an error span is kept, the slowest tail (top
  ``slow_fraction`` against a rolling window of recent durations) is kept,
  and 1-in-``sample_every`` of the rest is kept — so the recorder's ring
  holds exactly the traces worth looking at;
* **lock-cheap** — one lock per recorder; a span record is an append plus
  two integer updates.  The ring (``deque(maxlen=...)``) and the
  in-progress bound keep memory flat no matter the traffic;
* **completion = all spans closed** — a trace finalizes when its open-span
  count returns to zero, so reroutes, hedges, and cross-hop work (the
  slow loser of a hedge race) land in the SAME tree instead of being
  dropped as "late".  Traces abandoned by a crashed participant expire
  after ``open_ttl_s`` (counted, never leaked);
* **tracing never touches results** — trace ids, span ids and timestamps
  live entirely beside the (weights, payload, seed, k) request function:
  serving results are bitwise identical with tracing on or off
  (``scripts/trace_smoke.py`` + ``bench.py --tracing`` pin this).

Wire format (serving/frontend/protocol.py): the request's ``trace`` field
is ``"<trace-id>"`` or ``"<trace-id>/<parent-span-id>"`` — each part 1-64
chars of ``[A-Za-z0-9_.:-]``.  The front end mints a trace when the field
is absent and *accepts* one when present (fleet-of-fleets: a parent tier's
RemoteEngine hop span becomes the child tier's parent).  Anything else is
a typed ``bad_request``; the connection survives.

Export: :func:`chrome_trace_events` renders retained traces as Chrome
trace-event JSON (``chrome://tracing`` / Perfetto loadable) — served by
the wire ``traces`` control op, the metrics server's ``/traces`` endpoint,
and the ``iwae-trace`` CLI (telemetry/trace_cli.py).
"""

from __future__ import annotations

import itertools
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FlightRecorder", "Span", "SpanRecord", "TraceContext",
    "chrome_trace_events", "emit_span", "get_recorder", "mint_trace_id",
    "parse_wire_trace", "start_span", "TRACE_WIRE_MAX_CHARS",
]

#: one wire ``trace`` part: 1-64 chars, URL/log-safe, no ``/`` (separator)
_PART_RE = re.compile(r"[A-Za-z0-9_.:\-]{1,64}\Z")
#: the full wire field bound (two parts + separator) — anything longer is
#: a typed ``bad_request`` at the protocol surface, never server bloat
TRACE_WIRE_MAX_CHARS = 129

#: process-unique id material: a random process tag + a monotonic counter
#: (``itertools.count.__next__`` is atomic in CPython) — ids are opaque
#: labels and deliberately NOT drawn from any RNG the models use, so
#: tracing can never perturb a sampled weight
_PROC_TAG = os.urandom(4).hex()
_IDS = itertools.count(1)


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (collision-safe across processes)."""
    return os.urandom(8).hex()


def _mint_span_id() -> str:
    return f"{_PROC_TAG}-{next(_IDS):x}"


def parse_wire_trace(value: Any) -> Tuple[str, Optional[str]]:
    """Validate one wire ``trace`` field -> ``(trace_id, parent_span_id)``.

    Raises ValueError (the typed ``bad_request`` upstream) for non-strings,
    oversized fields, extra parts, or parts outside the charset — a
    malformed trace must never take the connection down or grow server
    state."""
    if not isinstance(value, str):
        raise ValueError(
            f"'trace' must be a string, got {type(value).__name__}")
    if len(value) > TRACE_WIRE_MAX_CHARS:
        raise ValueError(
            f"'trace' exceeds {TRACE_WIRE_MAX_CHARS} chars ({len(value)})")
    parts = value.split("/")
    if len(parts) > 2:
        raise ValueError("'trace' is '<trace-id>' or "
                         "'<trace-id>/<parent-span-id>' (one '/' at most)")
    for p in parts:
        if not _PART_RE.fullmatch(p):
            raise ValueError(
                "'trace' parts must be 1-64 chars of [A-Za-z0-9_.:-]")
    return parts[0], (parts[1] if len(parts) == 2 else None)


class SpanRecord:
    """One finished span (immutable once recorded)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "t_end", "attrs", "error")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, t_start: float, t_end: float,
                 attrs: Optional[dict], error: Optional[str]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t_start = t_start
        self.t_end = t_end
        self.attrs = attrs
        self.error = error

    def doc(self) -> Dict[str, Any]:
        """The span's JSON document (the flight-recorder schema tests pin)."""
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t_start_s": self.t_start,
            "duration_s": max(0.0, self.t_end - self.t_start),
            "attrs": dict(self.attrs) if self.attrs else {},
            "error": self.error,
        }


class TraceContext:
    """Where a child span attaches: (recorder, trace id, parent span id)."""

    __slots__ = ("recorder", "trace_id", "span_id")

    def __init__(self, recorder: "FlightRecorder", trace_id: str,
                 span_id: str):
        self.recorder = recorder
        self.trace_id = trace_id
        self.span_id = span_id

    def wire(self) -> str:
        """The context as the protocol ``trace`` field (the hop format)."""
        return f"{self.trace_id}/{self.span_id}"


class Span:
    """A live span: created by :func:`start_span`, closed by :meth:`finish`.

    Owned by the flow that created it — fields are written by one logical
    owner at a time (the request's current hop), never concurrently; the
    recorder's lock serializes the actual recording."""

    __slots__ = ("_recorder", "trace_id", "span_id", "parent_id", "name",
                 "t_start", "attrs", "_done")

    def __init__(self, recorder: "FlightRecorder", trace_id: str,
                 parent_id: Optional[str], name: str, t_start: float,
                 attrs: Optional[dict]):
        self._recorder = recorder
        self.trace_id = trace_id
        self.span_id = _mint_span_id()
        self.parent_id = parent_id
        self.name = name
        self.t_start = t_start
        self.attrs = dict(attrs) if attrs else None
        self._done = False
        recorder._begin(trace_id)

    def ctx(self) -> TraceContext:
        """The context children (local or over-the-wire) attach under."""
        return TraceContext(self._recorder, self.trace_id, self.span_id)

    def annotate(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    def child(self, name: str, attrs: Optional[dict] = None) -> "Span":
        return start_span(name, ctx=self.ctx(), attrs=attrs)

    def finish(self, error: Optional[str] = None,
               t_end: Optional[float] = None) -> None:
        """Record the span (idempotent: reroute/hedge races may try twice;
        the first close wins). `error` is the typed code (or any short
        label) that marks the whole trace error-retained."""
        if self._done:
            return
        self._done = True
        t_end = time.monotonic() if t_end is None else t_end
        self._recorder._record(SpanRecord(
            self.trace_id, self.span_id, self.parent_id, self.name,
            self.t_start, t_end, self.attrs, error), opened=True)


def start_span(name: str, *, ctx: Optional[TraceContext] = None,
               recorder: Optional["FlightRecorder"] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               attrs: Optional[dict] = None,
               t_start: Optional[float] = None) -> Span:
    """Open a span: under `ctx` (child), or rooting/joining a trace.

    With ``ctx``, the span is a child in that context's trace.  Without it,
    ``trace_id``/``parent_id`` join an existing trace (the wire-accept
    path) or — both absent — mint a fresh trace (the front end's root)."""
    if ctx is not None:
        rec, tid, pid = ctx.recorder, ctx.trace_id, ctx.span_id
    else:
        rec = recorder if recorder is not None else get_recorder()
        tid = trace_id if trace_id is not None else mint_trace_id()
        pid = parent_id
    return Span(rec, tid, pid, name,
                time.monotonic() if t_start is None else t_start, attrs)


def emit_span(ctx: TraceContext, name: str, t_start: float, t_end: float,
              attrs: Optional[dict] = None,
              error: Optional[str] = None) -> None:
    """Record one already-timed span under `ctx` (the engine pipeline's
    stage spans: timestamps were stamped on the hot path, the record is
    assembled at completion — zero tracing work between them)."""
    ctx.recorder._record(SpanRecord(
        ctx.trace_id, _mint_span_id(), ctx.span_id, name, t_start, t_end,
        attrs, error), opened=False)


class _OpenTrace:
    """In-progress trace state (guarded by the owning recorder's lock)."""

    __slots__ = ("records", "open_spans", "t_created")

    def __init__(self, t_created: float):
        self.records: List[SpanRecord] = []
        self.open_spans = 0
        self.t_created = t_created


class FlightRecorder:
    """Bounded, tail-sampling store of completed request traces.

    ``capacity`` bounds the retained ring; ``sample_every`` keeps
    1-in-N healthy/fast traces (1 = keep everything — what smokes use);
    ``slow_fraction`` keeps the slowest tail against a rolling window of
    recent trace durations (armed once ``slow_min_history`` durations have
    been seen — before that, only errors and the 1-in-N sample retain);
    ``max_open``/``open_ttl_s`` bound in-progress state against abandoned
    traces.  One instance per process by default (:func:`get_recorder`);
    tests and benches build isolated ones.
    """

    #: rolling-duration window backing the slow-tail threshold
    _DUR_WINDOW = 256

    def __init__(self, capacity: int = 256, sample_every: int = 16,
                 slow_fraction: float = 0.05, slow_min_history: int = 32,
                 max_open: int = 4096, open_ttl_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        self.capacity = int(capacity)
        self.sample_every = int(sample_every)
        self.slow_fraction = float(slow_fraction)
        self.slow_min_history = int(slow_min_history)
        self.max_open = int(max_open)
        self.open_ttl_s = float(open_ttl_s)
        self._clock = clock
        # RLock: the finalize/expire helpers re-take it so EVERY write to
        # the shared state is visibly under the lock (the concurrency
        # checker's discipline; same idiom as utils/compile_cache.py)
        self._lock = threading.RLock()
        self._open: Dict[str, _OpenTrace] = {}
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._durations: "deque[float]" = deque(maxlen=self._DUR_WINDOW)
        self._finalized = 0
        self._counters = {
            "traces_started": 0, "finalized": 0, "kept_error": 0,
            "kept_slow": 0, "kept_sampled": 0, "dropped": 0,
            "late_spans": 0, "open_overflow": 0, "abandoned": 0,
        }

    # -- span intake (called by Span/emit_span) -----------------------------

    def _begin(self, trace_id: str) -> None:
        """Register an opening span (creates the trace on first touch)."""
        with self._lock:
            t = self._open.get(trace_id)
            if t is None:
                if len(self._open) >= self.max_open:
                    self._expire_open(self._clock())
                if len(self._open) >= self.max_open:
                    # still full: refuse the new trace; its spans will be
                    # counted late and dropped — bounded memory beats
                    # completeness for a recorder
                    self._counters["open_overflow"] += 1
                    return
                t = self._open[trace_id] = _OpenTrace(self._clock())
                self._counters["traces_started"] += 1
            t.open_spans += 1

    def _record(self, rec: SpanRecord, opened: bool) -> None:
        finalize = None
        with self._lock:
            t = self._open.get(rec.trace_id)
            if t is None:
                self._counters["late_spans"] += 1
                return
            t.records.append(rec)
            if opened:
                t.open_spans -= 1
            if t.open_spans <= 0:
                finalize = self._open.pop(rec.trace_id)
        if finalize is not None:
            self._finalize_trace(rec.trace_id, finalize)

    # -- completion + tail sampling -----------------------------------------

    def _finalize_trace(self, trace_id: str, t: _OpenTrace) -> None:
        """Tail-sample one completed trace into the ring. `t` has already
        been popped from the open set, so this re-entrant lock section is
        the only writer that will ever see it."""
        records = t.records
        t0 = min(r.t_start for r in records)
        t1 = max(r.t_end for r in records)
        duration = max(0.0, t1 - t0)
        error = any(r.error is not None for r in records)
        with self._lock:
            # slow threshold BEFORE this duration joins the window (a burst
            # of identical requests must not all read as "slow vs itself")
            slow = False
            if len(self._durations) >= self.slow_min_history:
                ds = sorted(self._durations)
                idx = min(len(ds) - 1,
                          int(len(ds) * (1.0 - self.slow_fraction)))
                # STRICTLY above the threshold: a uniform workload (every
                # duration equal) has no tail and must not read as all-slow
                slow = duration > ds[idx]
            self._durations.append(duration)
            n = self._finalized
            self._finalized += 1
            self._counters["finalized"] += 1
            if error:
                kept = "error"
                self._counters["kept_error"] += 1
            elif slow:
                kept = "slow"
                self._counters["kept_slow"] += 1
            elif n % self.sample_every == 0:
                kept = "sampled"
                self._counters["kept_sampled"] += 1
            else:
                self._counters["dropped"] += 1
                return
            ids = {r.span_id for r in records}
            roots = [r for r in records
                     if r.parent_id is None or r.parent_id not in ids]
            records.sort(key=lambda r: r.t_start)
            self._ring.append({
                "trace_id": trace_id,
                "root": roots[0].name if roots else records[0].name,
                "duration_s": duration,
                "error": error,
                "kept": kept,
                "spans": [r.doc() for r in records],
            })

    def _expire_open(self, now: float) -> None:
        """Drop in-progress traces older than the TTL (abandoned by a
        crashed participant); called with the RLock already held."""
        with self._lock:
            stale = [tid for tid, t in self._open.items()
                     if now - t.t_created > self.open_ttl_s]
            for tid in stale:
                del self._open[tid]
                self._counters["abandoned"] += 1

    # -- export -------------------------------------------------------------

    def traces(self, limit: Optional[int] = None,
               trace_id: Optional[str] = None) -> List[dict]:
        """Retained trace documents, oldest first (``limit`` keeps the most
        recent N; ``trace_id`` filters — the histogram-exemplar lookup)."""
        with self._lock:
            docs = list(self._ring)
        if trace_id is not None:
            docs = [d for d in docs if d["trace_id"] == trace_id]
        if limit is not None and limit >= 0:
            # limit=0 means NO bodies (the --stats query), not docs[-0:]
            # (which would slice the whole ring)
            docs = docs[-int(limit):] if limit else []
        return docs

    def stats(self) -> Dict[str, Any]:
        """Recorder accounting (schema pinned in tests/test_telemetry.py)."""
        with self._lock:
            out = dict(self._counters)
            out["retained"] = len(self._ring)
            out["open"] = len(self._open)
        out["capacity"] = self.capacity
        out["sample_every"] = self.sample_every
        out["slow_fraction"] = self.slow_fraction
        return out

    def clear(self) -> None:
        """Drop retained and in-progress traces (tests/benches between
        phases); counters keep counting."""
        with self._lock:
            self._ring.clear()
            self._open.clear()


def chrome_trace_events(trace_docs: List[dict]) -> Dict[str, Any]:
    """Retained trace documents as a Chrome trace-event JSON object.

    Each trace renders as one synthetic thread (``tid``) so its spans nest
    visually by time; span/parent/trace ids and attrs ride ``args``.
    Loadable in ``chrome://tracing`` and Perfetto.
    """
    events: List[dict] = []
    pid = os.getpid()
    for i, doc in enumerate(trace_docs):
        tid = i + 1
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": f"trace {doc['trace_id']} "
                             f"({doc['kept']}, {doc['root']})"},
        })
        for s in doc["spans"]:
            args = dict(s["attrs"])
            args.update({"trace_id": doc["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"]})
            if s["error"] is not None:
                args["error"] = s["error"]
            events.append({
                "ph": "X", "cat": "iwae", "name": s["name"],
                "pid": pid, "tid": tid,
                "ts": round(s["t_start_s"] * 1e6, 3),
                "dur": round(s["duration_s"] * 1e6, 3),
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


#: the process-default flight recorder: the serving tier, RemoteEngine hops
#: and the in-process client all record here unless handed an instance —
#: one recorder = one assembled tree when client and fleet share a process
_DEFAULT = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _DEFAULT
