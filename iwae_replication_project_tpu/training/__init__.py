from iwae_replication_project_tpu.training.train_step import (
    TrainState,
    create_train_state,
    make_train_step,
    make_adam,
)
from iwae_replication_project_tpu.training.schedule import (
    burda_stage_lr,
    burda_stages,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_adam",
    "burda_stage_lr",
    "burda_stages",
]
