"""Whole-epoch training as ONE compiled XLA call.

At reference scale (2-layer MLPs, batch 100) a single train step is ~300 us of
TPU work — per-step Python dispatch dominates wall-clock. The TPU-native
answer: keep the training set resident in HBM, and run shuffle + (optional)
stochastic binarization + every optimizer step of an epoch inside one
`lax.scan`. The host issues one dispatch per epoch instead of N_batches.

This also moves the data pipeline's randomness on-device: the permutation and
the Bernoulli re-binarization draw from the same threaded PRNG key as the
model noise, so an epoch is a pure function `(state, x_train, epoch_idx) ->
(state, losses)` — reproducible, checkpointable, and shardable.

With a :class:`~..telemetry.diagnostics.DiagnosticsConfig` the scan
additionally accumulates the first/second gradient moments of the trailing
``snr_window`` steps and returns Rainforth-style gradient-SNR scalars next
to the losses — still one dispatch, zero extra host syncs (the driver
fetches them with its per-stage fetch). Off (the default), the compiled
program is byte-identical to the pre-diagnostics one.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec, objective_value_and_grad
from iwae_replication_project_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    grad_accum_init,
    grad_accum_update,
    grad_snr_summary,
)
from iwae_replication_project_tpu.telemetry.spans import spanned
from iwae_replication_project_tpu.training.train_step import TrainState, make_adam


def make_epoch_fn(spec: ObjectiveSpec, cfg: model.ModelConfig, n_train: int,
                  batch_size: int, stochastic_binarization: bool = False,
                  optimizer: optax.GradientTransformation | None = None,
                  shuffle: bool = True, donate: bool = True,
                  epochs_per_call: int = 1,
                  diagnostics: Optional[DiagnosticsConfig] = None
                  ) -> Callable[[TrainState, jax.Array], Tuple[TrainState, jax.Array]]:
    """Build ``epoch(state, x_train) -> (state, per-batch losses)``, jitted.

    `x_train` is the full ``[n_train, x_dim]`` set (placed on device once by
    the caller); drop-remainder batching like the host pipeline.

    With ``epochs_per_call > 1`` the returned function runs that many
    consecutive epochs inside one dispatch (an outer `lax.scan`; losses from
    all epochs concatenated). Each dispatch through a remote-device transport
    costs ~10-15 ms, so at small-dataset scale (e.g. digits: ~5 ms of device
    work per pass) per-pass dispatch dominates the stage loop — the
    experiment driver batches the long late stages with this knob.

    With `diagnostics` enabled the second return value becomes
    ``(losses, {"diag/grad_snr*": scalars})`` — SNR moments accumulated over
    the trailing ``min(snr_window, n_batches)`` steps of each epoch (the
    last epoch's, under ``epochs_per_call > 1``).
    """
    opt = optimizer if optimizer is not None else make_adam()
    n_batches = n_train // batch_size
    if n_batches == 0:
        raise ValueError(f"batch_size={batch_size} exceeds n_train={n_train}")
    if epochs_per_call < 1:
        raise ValueError(f"epochs_per_call={epochs_per_call} must be >= 1")
    diag_on = diagnostics is not None and diagnostics.enabled
    window = min(diagnostics.snr_window, n_batches) if diag_on else 0

    def epoch(state: TrainState, x_train: jax.Array):
        # four independent streams: the carried key is never itself consumed
        # by fold_in/permutation draws, preserving JAX's key-independence
        # guarantee across epochs
        key_next, k_batch, k_perm, k_bin = jax.random.split(state.key, 4)
        if shuffle:
            perm = jax.random.permutation(k_perm, n_train)
        else:
            perm = jnp.arange(n_train)
        idx = perm[: n_batches * batch_size].reshape(n_batches, batch_size)

        def step(st, batch_idx, i):
            batch = x_train[batch_idx]
            if stochastic_binarization:
                batch = jax.random.bernoulli(
                    jax.random.fold_in(k_bin, i), batch).astype(jnp.float32)
            bkey = jax.random.fold_in(k_batch, i)
            bound, grads = objective_value_and_grad(spec, st.params, cfg, bkey, batch)
            neg = jax.tree.map(jnp.negative, grads)
            updates, opt_state = opt.update(neg, st.opt_state, st.params)
            params = optax.apply_updates(st.params, updates)
            return (TrainState(params, opt_state, st.key, st.step + 1),
                    -bound, grads)

        if not diag_on:
            def body(st, xs):
                st, loss, _ = step(st, *xs)
                return st, loss

            state, losses = lax.scan(body, state, (idx, jnp.arange(n_batches)))
            return state._replace(key=key_next), losses

        def body(carry, xs):
            st, acc = carry
            st, loss, grads = step(st, *xs)
            include = (xs[1] >= n_batches - window).astype(jnp.float32)
            return (st, grad_accum_update(acc, grads, include)), loss

        (state, (s1, s2)), losses = lax.scan(
            body, (state, grad_accum_init(state.params)),
            (idx, jnp.arange(n_batches)))
        return (state._replace(key=key_next),
                (losses, grad_snr_summary(s1, s2, window)))

    # stable, descriptive program names: they become the XLA module names, so
    # persistent-compilation-cache entries (`jit_epoch_IWAE_k50-<hash>`) and
    # profiler traces are attributable to the objective that compiled them
    if epochs_per_call == 1:
        epoch.__name__ = epoch.__qualname__ = f"epoch_{spec.name}_k{spec.k}"
        return spanned(jax.jit(epoch, donate_argnums=(0,) if donate else ()),
                       "train/epoch")

    def multi(state: TrainState, x_train: jax.Array):
        state, out = lax.scan(lambda st, _: epoch(st, x_train), state,
                              None, length=epochs_per_call)
        if not diag_on:
            return state, out.reshape(-1)
        losses, diag = out
        # SNR moments from the LAST epoch of the block: the freshest window
        return state, (losses.reshape(-1),
                       jax.tree.map(lambda a: a[-1], diag))

    multi.__name__ = multi.__qualname__ = \
        f"epoch_block{epochs_per_call}_{spec.name}_k{spec.k}"
    return spanned(jax.jit(multi, donate_argnums=(0,) if donate else ()),
                   "train/epoch_block")
