"""The Burda 8-stage training schedule (PDF §3.4 p.8; experiment_example.py:75-77).

Stage i (1-based) runs ``3^(i-1)`` passes over the data at learning rate
``1e-4 * round(10^(1 - (i-1)/7), 1)`` — 1e-3 decaying to 1e-4, 3280 passes total.
"""

from __future__ import annotations

from typing import List, Tuple


def burda_stage_lr(stage: int) -> float:
    """Learning rate for 1-based `stage` (experiment_example.py:76)."""
    return 1e-4 * round(10.0 ** (1.0 - (stage - 1) / 7.0), 1)


def burda_stage_passes(stage: int, passes_scale: float = 1.0) -> int:
    """``max(1, round(3^(stage-1) * passes_scale))`` — the scale shrinks or
    stretches the schedule proportionally while keeping its geometric
    structure (small datasets overfit the 3280-pass MNIST schedule; see
    utils/config.py `passes_scale`)."""
    return max(1, int(round(3 ** (stage - 1) * passes_scale)))


def burda_stages(n_stages: int = 8, passes_scale: float = 1.0
                 ) -> List[Tuple[int, float, int]]:
    """``[(stage, lr, n_passes), ...]`` — sums to 3280 passes at n_stages=8,
    passes_scale=1 (657 at the digits protocol's 0.2)."""
    return [(i, burda_stage_lr(i), burda_stage_passes(i, passes_scale))
            for i in range(1, n_stages + 1)]
