"""The jitted training step and its state.

Replaces the reference's eager Keras ``train_step`` (flexible_IWAE.py:221-247)
with a pure ``(state, batch) -> (state, metrics)`` function compiled once by XLA.
Objective dispatch happens at *trace* time (the objective is a static spec), so
there is no branching inside the compiled graph. Adam uses the reference's
nonstandard ``eps=1e-4`` (experiment_example.py:39, matching Burda).

The learning rate is an injected hyperparameter, so the 8-stage schedule can
retune it *without* resetting Adam moments — the same behavior as the reference
mutating ``optimizer.learning_rate`` across stages (experiment_example.py:76).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec, objective_value_and_grad


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    key: jax.Array
    step: jax.Array  # per-batch counter (the reference's misnamed `epoch`, flexible_IWAE.py:245)


def make_adam(lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
              eps: float = 1e-4) -> optax.GradientTransformation:
    return optax.inject_hyperparams(optax.adam)(learning_rate=lr, b1=b1, b2=b2, eps=eps)


def create_train_state(key: jax.Array, cfg: model.ModelConfig,
                       output_bias=None, lr: float = 1e-3,
                       optimizer: optax.GradientTransformation | None = None) -> TrainState:
    k_init, k_train = jax.random.split(key)
    params = model.init_params(k_init, cfg, output_bias=output_bias)
    opt = optimizer if optimizer is not None else make_adam(lr)
    return TrainState(params=params, opt_state=opt.init(params), key=k_train,
                      step=jnp.zeros((), jnp.int32))


def set_learning_rate(state: TrainState, lr: float) -> TrainState:
    """Stage-boundary LR update, preserving Adam moments.

    Rebuilds the hyperparams mapping instead of assigning into it — the old
    TrainState may still be referenced (rollback, pending checkpoint) and must
    keep its LR.
    """
    opt_state = state.opt_state
    new_hp = dict(opt_state.hyperparams)
    new_hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
    return state._replace(opt_state=opt_state._replace(hyperparams=new_hp))


def make_train_step_fn(spec: ObjectiveSpec, cfg: model.ModelConfig,
                       optimizer: optax.GradientTransformation | None = None
                       ) -> Callable[[TrainState, jax.Array], Tuple[TrainState, Dict[str, jax.Array]]]:
    """The raw (un-jitted) step — jit it yourself, or via make_train_step /
    parallel.auto.make_pjit_train_step."""
    opt = optimizer if optimizer is not None else make_adam()

    def step(state: TrainState, batch: jax.Array):
        key, subkey = jax.random.split(state.key)
        bound, grads = objective_value_and_grad(spec, state.params, cfg, subkey, batch)
        neg_grads = jax.tree.map(jnp.negative, grads)  # maximize the bound
        updates, opt_state = opt.update(neg_grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss": -bound, spec.name: -bound}
        return TrainState(params, opt_state, key, state.step + 1), metrics

    return step


def make_train_step(spec: ObjectiveSpec, cfg: model.ModelConfig,
                    optimizer: optax.GradientTransformation | None = None,
                    donate: bool = True):
    """Build the jitted single-device step; see parallel.dp for the sharded one.

    With ``cfg.fused_likelihood`` the step's log-weight pass runs through the
    blocked hot-loop dispatcher (ops/hot_loop.py) — kernel selection happens
    once at trace time and lands on the telemetry ``kernel_path`` gauge, so a
    driver can stamp which path its compiled step uses. The step is wrapped
    in a ``train/step`` span (the whole-epoch scan path has its own
    ``train/epoch`` span in training/epoch.py).
    """
    from iwae_replication_project_tpu.telemetry.spans import spanned
    step = make_train_step_fn(spec, cfg, optimizer)
    return spanned(jax.jit(step, donate_argnums=(0,) if donate else ()),
                   "train/step")
