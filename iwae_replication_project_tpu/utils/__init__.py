from iwae_replication_project_tpu.utils.config import ExperimentConfig
from iwae_replication_project_tpu.utils.logging import MetricsLogger
from iwae_replication_project_tpu.utils.checkpoint import (
    save_checkpoint,
    restore_latest,
    latest_step,
)

__all__ = [
    "ExperimentConfig",
    "MetricsLogger",
    "save_checkpoint",
    "restore_latest",
    "latest_step",
]
