"""Checkpoint / resume via Orbax (SURVEY.md §5: the reference only has
per-stage ``save_weights`` "just in case it stops" with no restore logic,
experiment_example.py:95; here a checkpoint is the full resumable state).

A checkpoint = model params + optimizer state + RNG key + step counter +
stage index (+ the experiment config JSON), written atomically by Orbax with
retention of the newest `keep` steps.

**Integrity**: every save also writes a manifest
(``<directory>/manifests/<step>.json``: per-file size + SHA-256 over the
step's tree — outside the step directory, so Orbax's own layout stays
untouched). :func:`restore_latest` verifies the newest step against its
manifest BEFORE handing it to Orbax and, on a mismatch (the classic
truncated-by-preemption write), falls back to the newest intact retained
step with a loud warning instead of crashing the run — Orbax keeps
``keep=3`` steps precisely so there is somewhere to fall back to. A step
with no manifest (pre-integrity checkpoints) is accepted as before; if
Orbax then fails to read it, the fallback walk continues. Training replay
is deterministic (the whole-epoch scan carries the RNG key), so resuming
from an older intact step reproduces bitwise the run the newest step would
have — it just redoes a few passes (pinned by the chaos smoke).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from iwae_replication_project_tpu.training.train_step import TrainState
from iwae_replication_project_tpu.utils.faults import (
    SITE_CKPT_SAVE,
    fault_point,
)


class CheckpointConfigMismatch(ValueError):
    """The stored config belongs to a DIFFERENT experiment — a run-dir
    collision, never an integrity problem: no fallback, refuse loudly."""


def _config_identity(config_json: str) -> Optional[dict]:
    """The science-field subset of a stored config JSON (output dirs and
    execution knobs may legitimately differ between save and resume).

    Parses the raw JSON dict rather than constructing an ExperimentConfig so
    checkpoints written by older/newer config schemas still compare on the
    fields they share. Returns None (treated as no-information, not mismatch)
    for unparseable payloads."""
    import dataclasses

    from iwae_replication_project_tpu.utils.config import (
        SCIENCE_FIELDS,
        ExperimentConfig,
    )
    try:
        d = json.loads(config_json)
    except json.JSONDecodeError:
        return None
    if not isinstance(d, dict):
        return None
    defaults = dataclasses.asdict(ExperimentConfig())
    return {k: (list(v) if isinstance(v, (tuple, list)) else v)
            for k in SCIENCE_FIELDS
            for v in [d.get(k, defaults.get(k))]}


def _manager(directory: str, keep: int = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
    )


# ---------------------------------------------------------------------------
# integrity manifests
# ---------------------------------------------------------------------------

def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), "manifests",
                        f"{int(step)}.json")


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), str(int(step)))


def _file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_step_files(step_dir: str) -> List[str]:
    out = []
    for dirpath, _, filenames in os.walk(step_dir):
        for fname in filenames:
            out.append(os.path.relpath(os.path.join(dirpath, fname),
                                       step_dir))
    return sorted(out)


def write_manifest(directory: str, step: int) -> str:
    """Record (size, sha256) of every file under the step's tree. Written
    atomically (tmp + rename) OUTSIDE the step directory so Orbax's layout
    and retention are untouched; returns the manifest path."""
    step_dir = _step_dir(directory, step)
    files = {rel: {"bytes": os.path.getsize(os.path.join(step_dir, rel)),
                   "sha256": _file_digest(os.path.join(step_dir, rel))}
             for rel in _walk_step_files(step_dir)}
    path = _manifest_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"step": int(step), "files": files}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def prune_manifests(directory: str, live_steps: List[int]) -> None:
    """Drop manifests for steps Orbax's retention already deleted."""
    mdir = os.path.join(os.path.abspath(directory), "manifests")
    if not os.path.isdir(mdir):
        return
    live = {f"{int(s)}.json" for s in live_steps}
    for fname in os.listdir(mdir):
        if fname.endswith(".json") and fname not in live:
            os.remove(os.path.join(mdir, fname))


def verify_checkpoint(directory: str, step: int,
                      subtree: Optional[str] = None) -> Optional[str]:
    """Check the step's files against its manifest. Returns None when
    intact — or when no manifest exists (pre-integrity checkpoints carry
    no information; the restore path treats them as before) — else a
    human-readable description of the FIRST mismatch (missing/truncated/
    corrupted file, or a whole missing step directory). ``subtree``
    restricts verification to files under that item (e.g. ``"meta"`` for
    consumers that only read the config JSON — hashing a multi-GB state
    tree to read a 1 KB meta blob would be pure startup latency)."""
    mpath = _manifest_path(directory, step)
    if not os.path.isfile(mpath):
        return None
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        return f"unreadable manifest {mpath}: {e}"
    step_dir = _step_dir(directory, step)
    if not os.path.isdir(step_dir):
        return f"step directory missing: {step_dir}"
    for rel, want in sorted(manifest.get("files", {}).items()):
        if subtree is not None and \
                not rel.startswith(subtree.rstrip("/") + "/"):
            continue
        path = os.path.join(step_dir, rel)
        if not os.path.isfile(path):
            return f"missing file: {rel}"
        size = os.path.getsize(path)
        if size != want["bytes"]:
            return (f"size mismatch on {rel}: {size} bytes on disk vs "
                    f"{want['bytes']} in the manifest (truncated write?)")
        if _file_digest(path) != want["sha256"]:
            return f"checksum mismatch on {rel} (corrupted contents)"
    return None


def checkpoint_steps(directory: str) -> List[int]:
    """Retained step numbers, newest first (empty when no checkpoints)."""
    if not os.path.isdir(directory):
        return []
    mgr = _manager(directory)
    steps = sorted((int(s) for s in mgr.all_steps()), reverse=True)
    mgr.close()
    return steps


def truncate_newest_checkpoint(directory: str) -> Optional[str]:
    """Chaos helper: truncate the newest step's largest file to half its
    size — the canonical preemption-mid-write corruption. Returns the
    mutilated path (None when there is nothing to corrupt). The next
    :func:`restore_latest` must detect it and fall back."""
    steps = checkpoint_steps(directory)
    if not steps:
        return None
    step_dir = _step_dir(directory, steps[0])
    files = [(os.path.getsize(os.path.join(step_dir, rel)), rel)
             for rel in _walk_step_files(step_dir)]
    if not files:
        return None
    size, rel = max(files)
    path = os.path.join(step_dir, rel)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))
    return path


def _integrity_warn(directory: str, step: int, problem: str) -> None:
    if jax.process_index() != 0:
        return
    msg = (f"WARNING: checkpoint step {step} under {directory!r} failed "
           f"integrity verification ({problem}); falling back to the "
           f"newest intact retained checkpoint — the deterministic pass "
           f"replay reproduces the lost work bitwise")
    print(msg)
    print(msg, file=sys.stderr)


# ---------------------------------------------------------------------------
# save / restore
# ---------------------------------------------------------------------------

def save_checkpoint(directory: str, step: int, state: TrainState, stage: int,
                    config_json: str = "", keep: int = 3,
                    passes_done: Optional[int] = None) -> None:
    """`passes_done` = passes completed *within* `stage` at save time; None
    (and every pre-r5 checkpoint, whose meta lacks the field) means the stage
    finished — resume continues at the next stage."""
    mgr = _manager(directory, keep)
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "key": state.key,
        "step": state.step,
    }
    meta = {"config": config_json, "stage": stage}
    if passes_done is not None:
        meta["passes_done"] = int(passes_done)
    mgr.save(step, args=ocp.args.Composite(
        state=ocp.args.StandardSave(payload),
        meta=ocp.args.JsonSave(meta),
    ))
    mgr.wait_until_finished()
    if jax.process_index() == 0:
        # integrity manifest AFTER the save is durable (wait above), one
        # writer per multihost job; Orbax already pruned old steps, so the
        # manifest set mirrors retention exactly
        write_manifest(directory, step)
        prune_manifests(directory, [int(s) for s in mgr.all_steps()])
    mgr.close()
    # chaos hook: fires with the save fully durable — actions here model
    # corruption that lands AFTER a successful write (the truncated-newest
    # case restore_latest's fallback exists for)
    fault_point(SITE_CKPT_SAVE, directory=directory, step=int(step))


def stored_config_json(directory: str) -> Optional[str]:
    """The experiment-config JSON the newest *intact* checkpoint was written
    under (None when no checkpoint, or none stored). Lets consumers that only
    have a run directory — e.g. the serving engine's ``ServingEngine(ckpt_
    dir)`` path — rebuild the architecture template before restoring weights.
    Walks the same integrity fallback as :func:`restore_latest`, verifying
    only the ``meta`` item it actually reads (the config is identical
    across a run's retained steps — the identity guard enforces that — so
    hashing the full state tree here would double every consumer's cold
    start for no information)."""
    for step in checkpoint_steps(directory):
        problem = verify_checkpoint(directory, step, subtree="meta")
        if problem is not None:
            _integrity_warn(directory, step, problem)
            continue
        mgr = _manager(directory)
        try:
            meta = mgr.restore(step, args=ocp.args.Composite(
                meta=ocp.args.JsonRestore()))["meta"]
        except Exception as e:
            mgr.close()
            # no manifest vouched for this step (verify passed vacuously):
            # treat an unreadable pre-integrity step like a corrupt one
            _integrity_warn(directory, step, f"unreadable meta: {e}")
            continue
        mgr.close()
        return meta.get("config") or None
    return None


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_latest(directory: str, template: TrainState, *,
                   expect_config_json: Optional[str] = None
                   ) -> Optional[Tuple[int, TrainState, int, Optional[int]]]:
    """Restore ``(step, state, stage, passes_done)`` from the newest intact
    checkpoint, or None. ``passes_done`` is the number of passes completed
    within ``stage`` when the checkpoint was written — None when the stage
    had finished (also for pre-r5 checkpoints, which only saved at stage
    boundaries).

    `template` supplies the pytree structure/dtypes (an identically-constructed
    fresh TrainState). When `expect_config_json` is given, the stored config is
    compared against it and a mismatch raises instead of silently resuming a
    *different* experiment's weights (run-dir collision protection).

    Integrity: each candidate step is verified against its manifest first;
    a mismatch (or an unreadable manifest-less step) warns loudly and falls
    back to the next-newest retained step. A config mismatch
    (:class:`CheckpointConfigMismatch`) always raises — an intact checkpoint
    of the WRONG experiment is not something to fall back past.
    """
    for step in checkpoint_steps(directory):
        problem = verify_checkpoint(directory, step)
        if problem is not None:
            _integrity_warn(directory, step, problem)
            continue
        vouched = os.path.isfile(_manifest_path(directory, step))
        try:
            return _restore_step(directory, step, template,
                                 expect_config_json)
        except CheckpointConfigMismatch:
            raise
        except Exception as e:
            if vouched:
                # the manifest says the files are exactly as written, yet
                # Orbax cannot read them: that is a code/schema bug, not
                # corruption — surface it instead of quietly regressing
                # to older weights
                raise
            _integrity_warn(directory, step,
                            f"unreadable pre-integrity checkpoint: {e}")
    return None


def _restore_step(directory: str, step: int, template: TrainState,
                  expect_config_json: Optional[str]
                  ) -> Tuple[int, TrainState, int, Optional[int]]:
    mgr = _manager(directory)
    # meta first: the config-mismatch guard must fire BEFORE the state restore,
    # where a different architecture would die inside Orbax with a cryptic
    # pytree/shape error instead of the intended message
    meta = mgr.restore(step, args=ocp.args.Composite(
        meta=ocp.args.JsonRestore()))["meta"]
    stage = int(meta["stage"])
    passes_done = meta.get("passes_done")
    if passes_done is not None:
        passes_done = int(passes_done)
    if expect_config_json:
        stored_id = _config_identity(meta.get("config", ""))
        expect_id = _config_identity(expect_config_json)
        if stored_id is not None and expect_id is not None \
                and stored_id != expect_id:
            mgr.close()
            raise CheckpointConfigMismatch(
                f"checkpoint at {directory!r} was written by a different "
                f"experiment config; refusing to resume.\n"
                f"stored:  {stored_id}\ncurrent: {expect_id}")
        # compute_dtype is deliberately NOT a science field (params are f32
        # under either setting, so cross-dtype resume is legal), but it
        # changes the numerics of the remaining stages — flag the drift so a
        # mixed-precision trajectory is never silent (e.g. a pre-r5 f32
        # checkpoint resumed under the round-5 bfloat16 default)
        try:
            stored_dt = json.loads(meta.get("config", "") or "{}")
            cur_dt = json.loads(expect_config_json)
            if isinstance(stored_dt, dict) and isinstance(cur_dt, dict) \
                    and stored_dt.get("compute_dtype") != cur_dt.get("compute_dtype") \
                    and jax.process_index() == 0:
                print(f"note: checkpoint was trained with compute_dtype="
                      f"{stored_dt.get('compute_dtype')!r}; resuming under "
                      f"compute_dtype={cur_dt.get('compute_dtype')!r} — the "
                      f"remaining stages use the new dtype (each metrics row "
                      f"stamps its own 'bfloat16' flag)")
        except json.JSONDecodeError:
            pass
    tmpl = {
        "params": template.params,
        "opt_state": template.opt_state,
        "key": template.key,
        "step": template.step,
    }
    restored = mgr.restore(step, args=ocp.args.Composite(
        state=ocp.args.StandardRestore(tmpl),
    ))
    mgr.close()
    payload = restored["state"]
    state = TrainState(params=payload["params"], opt_state=payload["opt_state"],
                       key=payload["key"], step=payload["step"])
    return step, state, stage, passes_done
