"""Checkpoint / resume via Orbax (SURVEY.md §5: the reference only has
per-stage ``save_weights`` "just in case it stops" with no restore logic,
experiment_example.py:95; here a checkpoint is the full resumable state).

A checkpoint = model params + optimizer state + RNG key + step counter +
stage index (+ the experiment config JSON), written atomically by Orbax with
retention of the newest `keep` steps.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from iwae_replication_project_tpu.training.train_step import TrainState


def _config_identity(config_json: str) -> Optional[dict]:
    """The science-field subset of a stored config JSON (output dirs and
    execution knobs may legitimately differ between save and resume).

    Parses the raw JSON dict rather than constructing an ExperimentConfig so
    checkpoints written by older/newer config schemas still compare on the
    fields they share. Returns None (treated as no-information, not mismatch)
    for unparseable payloads."""
    import dataclasses
    import json

    from iwae_replication_project_tpu.utils.config import (
        SCIENCE_FIELDS,
        ExperimentConfig,
    )
    try:
        d = json.loads(config_json)
    except json.JSONDecodeError:
        return None
    if not isinstance(d, dict):
        return None
    defaults = dataclasses.asdict(ExperimentConfig())
    return {k: (list(v) if isinstance(v, (tuple, list)) else v)
            for k in SCIENCE_FIELDS
            for v in [d.get(k, defaults.get(k))]}


def _manager(directory: str, keep: int = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
    )


def save_checkpoint(directory: str, step: int, state: TrainState, stage: int,
                    config_json: str = "", keep: int = 3,
                    passes_done: Optional[int] = None) -> None:
    """`passes_done` = passes completed *within* `stage` at save time; None
    (and every pre-r5 checkpoint, whose meta lacks the field) means the stage
    finished — resume continues at the next stage."""
    mgr = _manager(directory, keep)
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "key": state.key,
        "step": state.step,
    }
    meta = {"config": config_json, "stage": stage}
    if passes_done is not None:
        meta["passes_done"] = int(passes_done)
    mgr.save(step, args=ocp.args.Composite(
        state=ocp.args.StandardSave(payload),
        meta=ocp.args.JsonSave(meta),
    ))
    mgr.wait_until_finished()
    mgr.close()


def stored_config_json(directory: str) -> Optional[str]:
    """The experiment-config JSON the newest checkpoint was written under
    (None when no checkpoint, or none stored). Lets consumers that only have
    a run directory — e.g. the serving engine's ``ServingEngine(ckpt_dir)``
    path — rebuild the architecture template before restoring weights."""
    step = latest_step(directory)
    if step is None:
        return None
    mgr = _manager(directory)
    meta = mgr.restore(step, args=ocp.args.Composite(
        meta=ocp.args.JsonRestore()))["meta"]
    mgr.close()
    return meta.get("config") or None


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_latest(directory: str, template: TrainState, *,
                   expect_config_json: Optional[str] = None
                   ) -> Optional[Tuple[int, TrainState, int, Optional[int]]]:
    """Restore ``(step, state, stage, passes_done)`` from the newest
    checkpoint, or None. ``passes_done`` is the number of passes completed
    within ``stage`` when the checkpoint was written — None when the stage
    had finished (also for pre-r5 checkpoints, which only saved at stage
    boundaries).

    `template` supplies the pytree structure/dtypes (an identically-constructed
    fresh TrainState). When `expect_config_json` is given, the stored config is
    compared against it and a mismatch raises instead of silently resuming a
    *different* experiment's weights (run-dir collision protection).
    """
    step = latest_step(directory)
    if step is None:
        return None
    mgr = _manager(directory)
    # meta first: the config-mismatch guard must fire BEFORE the state restore,
    # where a different architecture would die inside Orbax with a cryptic
    # pytree/shape error instead of the intended message
    meta = mgr.restore(step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))["meta"]
    stage = int(meta["stage"])
    passes_done = meta.get("passes_done")
    if passes_done is not None:
        passes_done = int(passes_done)
    if expect_config_json:
        stored_id = _config_identity(meta.get("config", ""))
        expect_id = _config_identity(expect_config_json)
        if stored_id is not None and expect_id is not None and stored_id != expect_id:
            mgr.close()
            raise ValueError(
                f"checkpoint at {directory!r} was written by a different "
                f"experiment config; refusing to resume.\n"
                f"stored:  {stored_id}\ncurrent: {expect_id}")
        # compute_dtype is deliberately NOT a science field (params are f32
        # under either setting, so cross-dtype resume is legal), but it
        # changes the numerics of the remaining stages — flag the drift so a
        # mixed-precision trajectory is never silent (e.g. a pre-r5 f32
        # checkpoint resumed under the round-5 bfloat16 default)
        import json
        try:
            stored_dt = json.loads(meta.get("config", "") or "{}")
            cur_dt = json.loads(expect_config_json)
            if isinstance(stored_dt, dict) and isinstance(cur_dt, dict) \
                    and stored_dt.get("compute_dtype") != cur_dt.get("compute_dtype") \
                    and jax.process_index() == 0:
                print(f"note: checkpoint was trained with compute_dtype="
                      f"{stored_dt.get('compute_dtype')!r}; resuming under "
                      f"compute_dtype={cur_dt.get('compute_dtype')!r} — the "
                      f"remaining stages use the new dtype (each metrics row "
                      f"stamps its own 'bfloat16' flag)")
        except json.JSONDecodeError:
            pass
    tmpl = {
        "params": template.params,
        "opt_state": template.opt_state,
        "key": template.key,
        "step": template.step,
    }
    restored = mgr.restore(step, args=ocp.args.Composite(
        state=ocp.args.StandardRestore(tmpl),
    ))
    mgr.close()
    payload = restored["state"]
    state = TrainState(params=payload["params"], opt_state=payload["opt_state"],
                       key=payload["key"], step=payload["step"])
    return step, state, stage, passes_done
