"""Checkpoint / resume via Orbax (SURVEY.md §5: the reference only has
per-stage ``save_weights`` "just in case it stops" with no restore logic,
experiment_example.py:95; here a checkpoint is the full resumable state).

A checkpoint = model params + optimizer state + RNG key + step counter +
stage index (+ the experiment config JSON), written atomically by Orbax with
retention of the newest `keep` steps.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from iwae_replication_project_tpu.training.train_step import TrainState


def _manager(directory: str, keep: int = 3) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
    )


def save_checkpoint(directory: str, step: int, state: TrainState, stage: int,
                    config_json: str = "", keep: int = 3) -> None:
    mgr = _manager(directory, keep)
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "key": state.key,
        "step": state.step,
    }
    mgr.save(step, args=ocp.args.Composite(
        state=ocp.args.StandardSave(payload),
        meta=ocp.args.JsonSave({"config": config_json, "stage": stage}),
    ))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_latest(directory: str, template: TrainState
                   ) -> Optional[Tuple[int, TrainState, int]]:
    """Restore ``(step, state, stage)`` from the newest checkpoint, or None.

    `template` supplies the pytree structure/dtypes (an identically-constructed
    fresh TrainState).
    """
    step = latest_step(directory)
    if step is None:
        return None
    mgr = _manager(directory)
    tmpl = {
        "params": template.params,
        "opt_state": template.opt_state,
        "key": template.key,
        "step": template.step,
    }
    restored = mgr.restore(step, args=ocp.args.Composite(
        state=ocp.args.StandardRestore(tmpl),
        meta=ocp.args.JsonRestore(),
    ))
    mgr.close()
    payload = restored["state"]
    stage = int(restored["meta"]["stage"])
    state = TrainState(params=payload["params"], opt_state=payload["opt_state"],
                       key=payload["key"], step=payload["step"])
    return step, state, stage
