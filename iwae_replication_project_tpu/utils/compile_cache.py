"""Warm-path execution engine: persistent XLA cache + AOT executable reuse.

VERDICT r5 measured ~90 s (14%) of the 630 s flagship dress rehearsal going to
XLA recompiles of programs that never change between stages or restarts. This
module makes every production entry point compile-once, run-warm:

* :func:`setup_persistent_cache` — one shared switch-on for JAX's persistent
  compilation cache (``jax_compilation_cache_dir``), keyed under the run's
  checkpoint directory by default so a preemption-resume pays zero recompiles.
  Every entry point (experiment driver, bench, dress rehearsal, graft entry)
  calls this instead of hand-rolling ``jax.config.update`` — a lint-guard test
  (tests/test_compile_cache.py) enforces it.

* an **AOT executable registry** (:func:`warm_callable`, :func:`aot_call`,
  :func:`aot_call_async` — the explicitly-async variant pipelined callers
  hold device results from) —
  ``.lower().compile()`` runs once per ``(program, static build key, arg
  shapes/dtypes/shardings)`` signature and the compiled executable is reused
  across the 8 Burda stages, across ``PASS_BLOCK`` dispatches, and across
  repeated ``run_experiment`` calls in one process (the driver rebuilds its
  jitted closures per run; the registry is module-level, so the rebuild is a
  registry hit instead of a retrace).

* :func:`cache_stats` — hits / misses / compile-seconds accounting, stamped
  into the per-stage metrics.jsonl rows by the experiment driver. "Misses" of
  the *persistent* cache are true XLA recompiles: a warm start records zero.

Resolution order for the cache directory: explicit argument (the config
field) > ``IWAE_COMPILE_CACHE`` env > an already-configured JAX cache dir
(e.g. tests/conftest.py or ``JAX_COMPILATION_CACHE_DIR``) > ``base_dir/
.jax_compile_cache``. The values ``off``/``none``/``0`` disable the cache.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Optional, Tuple

from iwae_replication_project_tpu.utils.faults import (
    SITE_AOT_CALL_ASYNC,
    fault_point,
)

#: default cache location relative to the entry point's persistent directory
#: (the checkpoint dir for the experiment driver — the one directory already
#: guaranteed to survive a preemption)
CACHE_SUBDIR = ".jax_compile_cache"

#: spellings of "disabled" accepted from config/env
_OFF = ("off", "none", "disabled", "0", "")

_lock = threading.Lock()
_state = {"dir": None, "listeners_installed": False}

#: process-global counters (monotonic; consumers diff snapshots)
_counters = {
    # persistent (on-disk) cache: a miss = a real XLA backend compile
    "persistent_cache_hits": 0,
    "persistent_cache_misses": 0,
    # every backend_compile call (incl. ones resolved from the on-disk cache)
    "backend_compiles": 0,
    "backend_compile_seconds": 0.0,
    # AOT registry
    "aot_hits": 0,
    "aot_misses": 0,
    "aot_compile_seconds": 0.0,
}

#: the AOT executable registry: signature -> jax.stages.Compiled
_executables: dict = {}

#: static cost record per registry entry (same key), stamped at compile
#: time by the trace-only analyzer (analysis/audit/cost.py): peak HBM
#: bytes, FLOPs, arithmetic intensity — the capacity-bounded executable
#: store's per-entry budget inputs (ROADMAP item 1). None when tracing
#: failed or ``IWAE_STATIC_COST=off`` disabled the stamp.
_static_costs: dict = {}


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

def _install_listeners() -> None:
    """Count persistent-cache hits/misses and backend compile time via JAX's
    monitoring events. Registered once per process; listener registration has
    no unregister API, so the counters are process-global and monotonic."""
    if _state["listeners_installed"]:
        return
    try:
        import jax._src.monitoring as mon
    except ImportError:  # monitoring moved/private API changed: degrade to
        _state["listeners_installed"] = True  # aot-only accounting
        return

    def _on_event(event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            _counters["persistent_cache_hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _counters["persistent_cache_misses"] += 1

    def _on_duration(event: str, duration_secs: float, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            _counters["backend_compiles"] += 1
            _counters["backend_compile_seconds"] += duration_secs

    mon.register_event_listener(_on_event)
    mon.register_event_duration_secs_listener(_on_duration)
    _state["listeners_installed"] = True


def resolve_cache_dir(explicit: Optional[str] = None,
                      base_dir: Optional[str] = None) -> Optional[str]:
    """The directory :func:`setup_persistent_cache` would leave active
    (None = disabled). Shared by setup itself, so the two cannot drift.

    Precedence: `explicit` (the config field) > ``IWAE_COMPILE_CACHE`` env >
    an already-configured JAX cache dir (kept untouched — first-wins) >
    ``base_dir/.jax_compile_cache`` > disabled.
    """
    path = explicit if explicit is not None \
        else os.environ.get("IWAE_COMPILE_CACHE")
    if path is not None:
        return None if path.strip().lower() in _OFF else path
    import jax
    current = getattr(jax.config, "jax_compilation_cache_dir", None) \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if current:
        return current
    if base_dir is not None:
        return os.path.join(base_dir, CACHE_SUBDIR)
    return None


def mesh_fingerprint(mesh) -> Optional[Tuple]:
    """Hashable identity of a mesh for registry build keys (axis extents +
    flat device ids) — the ONE definition both the experiment driver and the
    facade key the shared executable registry with."""
    if mesh is None:
        return None
    return (tuple(sorted(mesh.shape.items())),
            tuple(d.id for d in mesh.devices.flat))


def setup_persistent_cache(cache_dir: Optional[str] = None, *,
                           base_dir: Optional[str] = None,
                           min_compile_secs: float = 0.0) -> Optional[str]:
    """Enable JAX's persistent compilation cache; returns the active dir.

    `cache_dir` (the config field) and the ``IWAE_COMPILE_CACHE`` env always
    win (env fills in when the config leaves it None); either set to
    ``off``/``none``/``0`` disables the cache and returns None. Without an
    explicit dir, an already-configured JAX cache (conftest, or the
    ``JAX_COMPILATION_CACHE_DIR`` env JAX reads natively) is kept untouched —
    first-wins, so a wrapper script that configured the cache is not
    re-pointed by the driver it launches — and otherwise the cache lands
    under ``base_dir/.jax_compile_cache``.

    ``min_compile_secs=0.0`` caches *every* program: the warm-start contract
    is zero recompiles, not zero slow recompiles, and the driver's cheap
    programs (LR updates, host fetches) are exactly the ones that would
    otherwise recompile at every stage boundary on a resumed run.
    """
    import jax

    with _lock:
        _install_listeners()
        path = resolve_cache_dir(cache_dir, base_dir)
        if path is None:
            # "off" (or nothing configured anywhere) must actually disable:
            # clear any cache dir JAX already holds (a wrapper's env, an
            # earlier setup call), or XLA would keep serving deserialized
            # executables while cache_stats() claims the cache is off
            if getattr(jax.config, "jax_compilation_cache_dir", None):
                jax.config.update("jax_compilation_cache_dir", None)
            _state["dir"] = None
            return None
        current = getattr(jax.config, "jax_compilation_cache_dir", None)
        if path == current:
            _state["dir"] = current  # first-wins: keep thresholds untouched
            return current
        path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _state["dir"] = path
        return path


def donation_safe() -> bool:
    """Whether buffer donation may be combined with the active cache setup.

    On the XLA:CPU backend of jaxlib 0.4.x, executables deserialized from the
    persistent compilation cache mishandle input-output buffer aliasing when
    the caller donates: the staged driver with donation + a warm cache
    produces nondeterministic NaN/Inf results and heap corruption
    (``free(): invalid size`` / segfaults) — reproduced systematically while
    building this module (donation off OR cache off is stable across every
    run; donation + warm cache corrupts within a few runs). TPU/GPU
    executables round-trip donation through their native serialization paths
    and are unaffected. Until the CPU client is fixed upstream, the driver
    asks this predicate and quietly drops donation on CPU whenever the
    persistent cache is active — on CPU there is no HBM pressure for
    donation to relieve, so the cache is strictly the better half of the
    trade.
    """
    import jax

    if not getattr(jax.config, "jax_compilation_cache_dir", None):
        return True  # no cache -> nothing deserialized -> donation is fine
    return jax.default_backend() != "cpu"


# ---------------------------------------------------------------------------
# AOT executable registry
# ---------------------------------------------------------------------------

def _abstract_signature(args: Tuple) -> Tuple:
    """Hashable (treedef, per-leaf shape/dtype/sharding/weak) fingerprint of
    a call.

    Shardings are part of the signature: the same pytree placed under a
    different mesh (or re-placed single-device) must map to its own
    executable, not be fed to one compiled for other devices. Weak-typedness
    is part of it too — a weak-typed leaf traces a different program than its
    committed twin, so folding them into one slot would hand one caller the
    other's executable. The leaf grammar (array 4-tuple vs python-scalar
    2-tuple) is what analysis/audit's recompile-cardinality pass walks when
    it flags signatures that fragment this registry.
    """
    import jax

    leaves, treedef = jax.tree.flatten(args)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sharding = getattr(leaf, "sharding", None)
            sig.append((tuple(leaf.shape), str(leaf.dtype),
                        str(sharding) if sharding is not None else "",
                        bool(getattr(leaf, "weak_type", False))))
        else:  # python scalar etc. — weak-typed; key on type + value
            sig.append((type(leaf).__name__, repr(leaf)))
    return (str(treedef), tuple(sig))


def registry_signatures() -> list:
    """``(name, build_key, signature)`` for every registered executable.

    The audit CLI's recompile-cardinality pass walks these to flag python-
    scalar and weak-typed signature leaves — each of which mints one
    executable per distinct value and fragments this registry under serving
    traffic.
    """
    with _lock:
        return [(name, build_key, sig)
                for (name, build_key, sig) in _executables]


def static_cost_records() -> list:
    """``(name, build_key, signature, static_cost | None)`` per executable.

    ``static_cost`` is the trace-time cost record (peak HBM bytes, FLOPs,
    arithmetic intensity, per-axis collective counts, plus ``arg_bytes``
    sized from the dispatch signature itself) — what a capacity-bounded
    executable store budgets its LRU eviction with, and what ``iwae-cost
    --registry`` surfaces. Entries stamped None mean the fail-soft trace
    was skipped (``IWAE_STATIC_COST=off``) or failed.
    """
    with _lock:
        return [(*key, _static_costs.get(key)) for key in _executables]


def _signature_arg_bytes(sig) -> int:
    """Total dispatch-argument HBM bytes from one signature record, sized
    through the shared ``utils.dtypes`` byte-width table (the leaf grammar
    is :func:`_abstract_signature`'s: array leaves are 4-tuples carrying a
    dtype *string*; scalar/kwarg-name leaves carry no buffer)."""
    import math

    from iwae_replication_project_tpu.utils.dtypes import byte_width

    _, leaves = sig
    total = 0
    for leaf in leaves:
        if len(leaf) >= 4:
            shape, dtype = leaf[0], leaf[1]
            try:
                total += int(math.prod(shape)) * byte_width(dtype)
            except ValueError:
                pass  # an exotic dtype string: skip, never crash dispatch
    return total


def _trace_static_cost(name: str, jitted_fn: Callable, args: Tuple,
                       kwargs: dict, static_kwargs: Optional[dict],
                       sig) -> Optional[dict]:
    """Stamp a registry entry's static cost record at compile time.

    Trace-only (``jax.make_jaxpr`` — no second compile) and strictly
    fail-soft: a miss already pays seconds of XLA compile, so the extra
    trace is noise there, but ANY analyzer failure must degrade to a None
    record rather than poison the dispatch path. ``IWAE_STATIC_COST=off``
    disables the stamp wholesale.
    """
    flag = os.environ.get("IWAE_STATIC_COST")
    if flag is not None and flag.strip().lower() in _OFF:
        return None
    try:
        import functools

        import jax

        from iwae_replication_project_tpu.analysis.audit.cost import (
            CostAnalyzer)
        fn = functools.partial(jitted_fn, **(static_kwargs or {}))
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        rec, _ = CostAnalyzer().analyze_jaxpr(name, closed)
        cost = rec.to_dict()
        cost["arg_bytes"] = _signature_arg_bytes(sig)
        return cost
    except Exception:
        # fail-soft by contract: a cost-stamp failure must never break the
        # serving dispatch path; the entry simply carries no record
        return None


def _registry_get_or_compile(name: str, jitted_fn: Callable, args: Tuple,
                             kwargs: dict, static_kwargs: Optional[dict],
                             build_key: Tuple, count_hit: bool):
    """Resolve ``(name, build_key, signature)`` to a compiled executable,
    compiling (and accounting the miss) on first sight. `count_hit=False`
    lets warmup probes re-resolve without inflating the hit counters."""
    key = (name, build_key,
           _abstract_signature((args, tuple(sorted(kwargs.items(),
                                                   key=lambda kv: kv[0])))))
    exe = _executables.get(key)
    if exe is None:
        t0 = time.perf_counter()
        lowered = jitted_fn.lower(*args, **kwargs, **(static_kwargs or {}))
        exe = lowered.compile()
        # compile already cost seconds; the trace-only cost stamp rides the
        # miss (fail-soft, IWAE_STATIC_COST=off to disable)
        cost = _trace_static_cost(name, jitted_fn, args, kwargs,
                                  static_kwargs, key[2])
        with _lock:
            _executables[key] = exe
            if cost is not None:
                _static_costs[key] = cost
            _counters["aot_misses"] += 1
            _counters["aot_compile_seconds"] += time.perf_counter() - t0
    elif count_hit:
        with _lock:
            _counters["aot_hits"] += 1
    return exe


def aot_call_async(name: str, jitted_fn: Callable, args: Tuple = (),
                   kwargs: Optional[dict] = None,
                   static_kwargs: Optional[dict] = None,
                   build_key: Tuple = ()) -> Any:
    """Enqueue ``jitted_fn(*args, **kwargs, **static_kwargs)`` via the
    registry and return the resulting **device arrays without any host
    synchronization** — the explicitly-async AOT call path.

    JAX dispatch is asynchronous: the returned arrays are futures over
    device buffers, and the call returns as soon as the execution is queued.
    Callers that pipeline (the serving engine's dispatcher thread) hold the
    result and perform the blocking device→host fetch (``np.asarray``)
    elsewhere — overlapping the next dispatch with the in-flight compute.
    Shares the executable registry, the hit/miss accounting, and the
    ``aot/<name>`` span with :func:`aot_call` (the span time is enqueue, not
    device completion, by design).

    First call per ``(name, build_key, signature(args, kwargs))``:
    ``jitted_fn.lower(...).compile()`` (a registry *miss*; the lower+compile
    wall time — which collapses to deserialization on a persistent-cache hit
    — is accounted as ``aot_compile_seconds``). Every later call reuses the
    compiled executable (a *hit*) with zero tracing or cache-key hashing of
    the jaxpr. ``build_key`` must capture everything the caller baked into
    the closure (objective spec, model config, n_train, donation, mesh, ...):
    two distinct programs must never share a registry slot.
    """
    kwargs = kwargs or {}
    exe = _registry_get_or_compile(name, jitted_fn, args, kwargs,
                                   static_kwargs, build_key, count_hit=True)
    # chaos hook (utils/faults.py): every AOT dispatch passes this point,
    # so an injected raise here models the enqueue-time failure class
    # (OOM, poisoned runtime) for ANY program; off = one None check
    fault_point(SITE_AOT_CALL_ASYNC, name=name)
    # every AOT dispatch in the process funnels through here — the ONE span
    # site that covers training epochs, the fused eval suite, and serving
    # alike (the time recorded is enqueue, not device completion: async
    # dispatch returns as soon as the transfer program is queued)
    from iwae_replication_project_tpu.telemetry.spans import span
    with span(f"aot/{name}"):
        return exe(*args, **kwargs)


def aot_call(name: str, jitted_fn: Callable, args: Tuple = (),
             kwargs: Optional[dict] = None,
             static_kwargs: Optional[dict] = None,
             build_key: Tuple = ()) -> Any:
    """Call ``jitted_fn(*args, **kwargs, **static_kwargs)`` via the registry.

    The historical name for :func:`aot_call_async` — JAX dispatch has always
    been async, so the two are the same operation; use ``aot_call_async``
    where the no-host-sync contract is load-bearing (pipelined serving) and
    this name where the caller fetches (or chains) immediately.

    Donation declared on `jitted_fn` is preserved by the compiled executable.
    The executable is invoked with the dynamic arguments only
    (`static_kwargs` are compile-time constants, already burned into the
    program — pass statics that interleave positionally by keyword).
    """
    return aot_call_async(name, jitted_fn, args, kwargs=kwargs,
                          static_kwargs=static_kwargs, build_key=build_key)


def aot_warm(name: str, jitted_fn: Callable, args: Tuple = (),
             kwargs: Optional[dict] = None,
             static_kwargs: Optional[dict] = None,
             build_key: Tuple = ()) -> Any:
    """Populate the registry for this call signature WITHOUT executing.

    The bucket-warmup API for online serving (serving/engine.py): an engine
    pre-compiles one executable per (op, shape bucket, k, dtype) ladder rung
    at startup, so the first live request of every bucket is already a
    registry hit — no compile storm under ragged traffic. Returns the
    executable. A signature already present is a no-op (and is NOT counted
    as an aot hit: warmup probes must not skew the serving hit-rate metric).
    """
    return _registry_get_or_compile(name, jitted_fn, args, kwargs or {},
                                    static_kwargs, build_key, count_hit=False)


def warm_callable(name: str, jitted_fn: Callable,
                  build_key: Tuple = ()) -> Callable:
    """Wrap a jitted function so every call routes through :func:`aot_call`.

    Drop-in for the driver's epoch/step functions: same call signature, same
    results, but the compiled executable is shared process-wide per
    ``(name, build_key, arg signature)`` — across stages, across
    ``PASS_BLOCK`` blocks, and across `run_experiment` invocations.
    """
    def call(*args):
        return aot_call(name, jitted_fn, args, build_key=build_key)

    call.__name__ = f"warm_{name}"
    return call


@contextlib.contextmanager
def isolated_aot_registry():
    """Run with an EMPTY AOT executable registry; restore the previous one
    (dropping entries created inside) on exit.

    For tests that compare two driver runs: the registry is process-global
    and keyed by build signature only, so a run inside a test can silently
    reuse an executable an earlier test compiled under different cache /
    donation conditions — making the two compared runs asymmetric (one fresh
    compile, one reuse). Isolation restores the symmetry the comparison
    assumes.
    """
    with _lock:
        saved = dict(_executables)
        saved_costs = dict(_static_costs)
        _executables.clear()
        _static_costs.clear()
    try:
        yield
    finally:
        with _lock:
            _executables.clear()
            _executables.update(saved)
            _static_costs.clear()
            _static_costs.update(saved_costs)


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------

def cache_stats() -> dict:
    """Snapshot of the process-global warm-path counters.

    ``persistent_cache_misses`` counts true XLA backend compiles whose result
    was not in the on-disk cache — the number a warm start must hold at zero.
    ``aot_*`` count the executable-registry behavior; ``backend_compile_
    seconds`` is total time inside XLA's compile entry point (on a warm start
    it collapses to cache-deserialization time).
    """
    with _lock:
        snap = dict(_counters)
    snap["cache_dir"] = _state["dir"]
    snap["aot_executables"] = len(_executables)
    return snap


def stats_delta(before: dict, after: Optional[dict] = None) -> dict:
    """Numeric field-wise ``after - before`` of two :func:`cache_stats`
    snapshots (non-numeric fields are taken from `after`)."""
    if after is None:
        after = cache_stats()
    out = {}
    for k, v in after.items():
        b = before.get(k, 0)
        out[k] = v - b if isinstance(v, (int, float)) \
            and not isinstance(v, bool) and isinstance(b, (int, float)) else v
    return out
