"""Warm-path execution engine: persistent XLA cache + AOT executable reuse.

VERDICT r5 measured ~90 s (14%) of the 630 s flagship dress rehearsal going to
XLA recompiles of programs that never change between stages or restarts. This
module makes every production entry point compile-once, run-warm:

* :func:`setup_persistent_cache` — one shared switch-on for JAX's persistent
  compilation cache (``jax_compilation_cache_dir``), keyed under the run's
  checkpoint directory by default so a preemption-resume pays zero recompiles.
  Every entry point (experiment driver, bench, dress rehearsal, graft entry)
  calls this instead of hand-rolling ``jax.config.update`` — a lint-guard test
  (tests/test_compile_cache.py) enforces it.

* the **executable store** (:class:`ExecutableStore`; module-level default
  behind :func:`warm_callable`, :func:`aot_call`, :func:`aot_call_async` —
  the explicitly-async variant pipelined callers hold device results from) —
  ``.lower().compile()`` runs once per ``(model, program, static build key,
  arg shapes/dtypes/shardings)`` entry and the compiled executable is reused
  across the 8 Burda stages, across ``PASS_BLOCK`` dispatches, and across
  repeated ``run_experiment`` calls in one process (the driver rebuilds its
  jitted closures per run; the store is module-level, so the rebuild is a
  store hit instead of a retrace).

  The store is **capacity-bounded and multi-tenant** (ROADMAP item 1): each
  entry is billed the device bytes of its static cost record (the trace-time
  analysis stamped at compile, PR 11) and an explicit ``budget_bytes``
  (:func:`set_store_budget`, ``IWAE_STORE_BUDGET_BYTES``, ``iwae-serve
  --store-budget-mb``; None = unbounded, the historical behavior) caps the
  resident set with LRU eviction. Entries pinned by an in-flight dispatch
  are never evicted. Eviction is a **demotion, not a loss**: while the
  persistent XLA cache is active the serialized program stays on disk (the
  cold tier), so a re-requested entry is a fast cache-hit deserialize — a
  *readmit* — never a fresh XLA compile. One replica can therefore serve a
  whole model zoo under bounded device memory.

* :func:`cache_stats` — hits / misses / compile-seconds plus the store's
  eviction/demotion/readmit accounting, stamped into the per-stage
  metrics.jsonl rows by the experiment driver. "Misses" of the *persistent*
  cache are true XLA recompiles: a warm start — and a store readmit — records
  zero.

* the **donation gate** (:func:`donation_allowed` / :func:`donation_safe`)
  — the ONE owner of the donation-vs-persistent-cache CPU hazard
  (RESULTS.md §5): executable lifetime and the cache configuration both
  live here, so the store decides whether a caller's requested donation is
  safe to honor. Call sites (the experiment driver, the audit suite's
  program builders) ask; they no longer compose their own guards.

Resolution order for the cache directory: explicit argument (the config
field) > ``IWAE_COMPILE_CACHE`` env > an already-configured JAX cache dir
(e.g. tests/conftest.py or ``JAX_COMPILATION_CACHE_DIR``) > ``base_dir/
.jax_compile_cache``. The values ``off``/``none``/``0`` disable the cache.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from iwae_replication_project_tpu.utils.faults import (
    SITE_AOT_CALL_ASYNC,
    fault_point,
)

#: default cache location relative to the entry point's persistent directory
#: (the checkpoint dir for the experiment driver — the one directory already
#: guaranteed to survive a preemption)
CACHE_SUBDIR = ".jax_compile_cache"

#: spellings of "disabled" accepted from config/env
_OFF = ("off", "none", "disabled", "0", "")

#: "argument not passed" sentinel (None is a meaningful value for budgets)
_UNSET = object()

_lock = threading.Lock()
_state = {"dir": None, "listeners_installed": False}

#: process-global counters (monotonic; consumers diff snapshots)
_counters = {
    # persistent (on-disk) cache: a miss = a real XLA backend compile
    "persistent_cache_hits": 0,
    "persistent_cache_misses": 0,
    # every backend_compile call (incl. ones resolved from the on-disk cache)
    "backend_compiles": 0,
    "backend_compile_seconds": 0.0,
    # AOT registry
    "aot_hits": 0,
    "aot_misses": 0,
    "aot_compile_seconds": 0.0,
}

#: the default per-model label for callers that name no tenant (the
#: historical single-model entry points: the experiment driver, benches)
DEFAULT_MODEL = "default"


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

def _install_listeners() -> None:
    """Count persistent-cache hits/misses and backend compile time via JAX's
    monitoring events. Registered once per process; listener registration has
    no unregister API, so the counters are process-global and monotonic."""
    if _state["listeners_installed"]:
        return
    try:
        import jax._src.monitoring as mon
    except ImportError:  # monitoring moved/private API changed: degrade to
        _state["listeners_installed"] = True  # aot-only accounting
        return

    def _on_event(event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            _counters["persistent_cache_hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            _counters["persistent_cache_misses"] += 1

    def _on_duration(event: str, duration_secs: float, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            _counters["backend_compiles"] += 1
            _counters["backend_compile_seconds"] += duration_secs

    mon.register_event_listener(_on_event)
    mon.register_event_duration_secs_listener(_on_duration)
    _state["listeners_installed"] = True


def resolve_cache_dir(explicit: Optional[str] = None,
                      base_dir: Optional[str] = None) -> Optional[str]:
    """The directory :func:`setup_persistent_cache` would leave active
    (None = disabled). Shared by setup itself, so the two cannot drift.

    Precedence: `explicit` (the config field) > ``IWAE_COMPILE_CACHE`` env >
    an already-configured JAX cache dir (kept untouched — first-wins) >
    ``base_dir/.jax_compile_cache`` > disabled.
    """
    path = explicit if explicit is not None \
        else os.environ.get("IWAE_COMPILE_CACHE")
    if path is not None:
        return None if path.strip().lower() in _OFF else path
    import jax
    current = getattr(jax.config, "jax_compilation_cache_dir", None) \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if current:
        return current
    if base_dir is not None:
        return os.path.join(base_dir, CACHE_SUBDIR)
    return None


def mesh_fingerprint(mesh) -> Optional[Tuple]:
    """Hashable identity of a mesh for registry build keys (axis extents +
    flat device ids) — the ONE definition both the experiment driver and the
    facade key the shared executable registry with."""
    if mesh is None:
        return None
    return (tuple(sorted(mesh.shape.items())),
            tuple(d.id for d in mesh.devices.flat))


def setup_persistent_cache(cache_dir: Optional[str] = None, *,
                           base_dir: Optional[str] = None,
                           min_compile_secs: float = 0.0) -> Optional[str]:
    """Enable JAX's persistent compilation cache; returns the active dir.

    `cache_dir` (the config field) and the ``IWAE_COMPILE_CACHE`` env always
    win (env fills in when the config leaves it None); either set to
    ``off``/``none``/``0`` disables the cache and returns None. Without an
    explicit dir, an already-configured JAX cache (conftest, or the
    ``JAX_COMPILATION_CACHE_DIR`` env JAX reads natively) is kept untouched —
    first-wins, so a wrapper script that configured the cache is not
    re-pointed by the driver it launches — and otherwise the cache lands
    under ``base_dir/.jax_compile_cache``.

    ``min_compile_secs=0.0`` caches *every* program: the warm-start contract
    is zero recompiles, not zero slow recompiles, and the driver's cheap
    programs (LR updates, host fetches) are exactly the ones that would
    otherwise recompile at every stage boundary on a resumed run.
    """
    import jax

    with _lock:
        _install_listeners()
        path = resolve_cache_dir(cache_dir, base_dir)
        if path is None:
            # "off" (or nothing configured anywhere) must actually disable:
            # clear any cache dir JAX already holds (a wrapper's env, an
            # earlier setup call), or XLA would keep serving deserialized
            # executables while cache_stats() claims the cache is off
            if getattr(jax.config, "jax_compilation_cache_dir", None):
                jax.config.update("jax_compilation_cache_dir", None)
            _state["dir"] = None
            return None
        current = getattr(jax.config, "jax_compilation_cache_dir", None)
        if path == current:
            _state["dir"] = current  # first-wins: keep thresholds untouched
            return current
        path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _state["dir"] = path
        return path


@contextlib.contextmanager
def suspended_persistent_cache():
    """Temporarily disable the persistent XLA cache, restoring the prior
    configuration on exit — the sanctioned primitive for measuring TRUE
    fresh-compile cost (``bench.py --multi-model``'s cold-vs-readmit
    denominator). Lives here because this module is the single owner of
    the cache wiring (the ``cache-setup`` lint rule enforces that)."""
    import jax

    before = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def donation_safe() -> bool:
    """Whether buffer donation may be combined with the active cache setup.

    On the XLA:CPU backend of jaxlib 0.4.x, executables deserialized from the
    persistent compilation cache mishandle input-output buffer aliasing when
    the caller donates: the staged driver with donation + a warm cache
    produces nondeterministic NaN/Inf results and heap corruption
    (``free(): invalid size`` / segfaults) — reproduced systematically while
    building this module (donation off OR cache off is stable across every
    run; donation + warm cache corrupts within a few runs). TPU/GPU
    executables round-trip donation through their native serialization paths
    and are unaffected. Until the CPU client is fixed upstream, the driver
    asks this predicate and quietly drops donation on CPU whenever the
    persistent cache is active — on CPU there is no HBM pressure for
    donation to relieve, so the cache is strictly the better half of the
    trade.

    The decision itself is owned by the executable store
    (:meth:`ExecutableStore.donation_allowed` — executable lifetime and the
    cache wiring live there); this module-level name is the historical
    spelling of the unconditional ask.
    """
    return _store.donation_allowed(True)


def donation_allowed(requested: bool = True) -> bool:
    """The ONE donation gate call sites use: the caller's donation request
    (config flag, audit default) AND'd with the store-owned hazard check —
    ``donation_allowed(cfg.donate_buffers)`` replaces the per-site
    ``cfg.donate_buffers and donation_safe()`` composition."""
    return _store.donation_allowed(requested)


# ---------------------------------------------------------------------------
# the executable store (the AOT registry, capacity-bounded + multi-tenant)
# ---------------------------------------------------------------------------

def _cold_tier_active() -> bool:
    """Whether evicted executables have a serialized twin to fall back to:
    the persistent XLA cache as JAX actually sees it (first-wins semantics —
    a wrapper/conftest may have configured it without going through
    :func:`setup_persistent_cache`, and demotion accounting must follow the
    truth, not this module's setup record)."""
    import jax

    return bool(getattr(jax.config, "jax_compilation_cache_dir", None))


class _StoreEntry:
    """One resident executable: the compiled program plus its budget bill."""

    __slots__ = ("exe", "cost", "bytes", "pins", "cold")

    def __init__(self, exe, cost: Optional[dict], nbytes: int, cold: bool):
        self.exe = exe
        #: the static cost record stamped at compile (None = stamp skipped)
        self.cost = cost
        #: device bytes billed against the store budget
        self.bytes = int(nbytes)
        #: pin refcount: > 0 means an in-flight dispatch holds the entry
        self.pins = 0
        #: whether a serialized twin exists in the persistent XLA cache
        #: (compiled while the cache was active) — eviction then demotes
        #: instead of discarding
        self.cold = bool(cold)


class _PrefixPin:
    """Handle for a ``(model, name, build_key)``-prefix pin (release once)."""

    __slots__ = ("_store", "_prefix", "_released")

    def __init__(self, store: "ExecutableStore", prefix: Tuple):
        self._store = store
        self._prefix = prefix
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._store._unpin_prefix(self._prefix)


class _ModelPin:
    """Handle for a model-level placement pin (release once)."""

    __slots__ = ("_store", "_model", "_released")

    def __init__(self, store: "ExecutableStore", model: str):
        self._store = store
        self._model = model
        self._released = False

    @property
    def model(self) -> str:
        return self._model

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._store._unpin_model(self._model)


class ExecutableStore:
    """Capacity-bounded, multi-tenant AOT executable store.

    Entries are keyed ``(model, name, build_key, signature)`` — the model
    label names the tenant (zoo preset / checkpoint), ``name`` + ``build_key``
    the program, and the signature the arg shapes/dtypes/shardings. Admission
    and retention are governed by ``budget_bytes``: every entry is billed the
    ``peak_bytes`` of its static cost record (the trace-time analyzer stamp,
    analysis/audit/cost.py — exactly what :func:`static_cost_records`
    surfaces), falling back to the dispatch-argument bytes when the stamp is
    unavailable; past the budget the least-recently-used *unpinned* entries
    are evicted until the resident set fits (an entry larger than the whole
    budget is still admitted — refusing would refuse to serve — and evicts
    everything else unpinned).

    **Warm/cold tiers.** Residency here is the warm tier. While the
    persistent XLA cache is active, every compiled program also has a
    serialized twin on disk — the cold tier — so eviction *demotes*: a later
    request for the same entry re-enters through ``lower().compile()``, which
    collapses to a cache-hit deserialize (a *readmit*, counted; the
    ``persistent_cache_misses`` counter stays flat — the test- and
    smoke-pinned "evict → re-request → 0 fresh compiles" contract).

    **Pins.** :meth:`pin_prefix` marks every entry under a ``(model, name,
    build_key)`` prefix unevictable until released — the serving engines pin
    for the lifetime of each in-flight dispatch, so a budget squeeze can
    never pull an executable out from under work the device is running.

    The store is also the process's single owner of executable lifetime,
    which makes it the natural owner of the donation-vs-persistent-cache
    hazard: :meth:`donation_allowed` is THE gate (RESULTS.md §5).
    """

    COUNTER_NAMES = ("hits", "misses", "evictions", "demotions", "readmits")

    def __init__(self, budget_bytes: Optional[int] = None):
        # reentrant: _evict_over_budget acquires it itself so every write
        # is visibly guarded, and its callers already hold it
        self._lock = threading.RLock()
        #: key -> _StoreEntry in LRU order (last = most recently used)
        self._entries: "collections.OrderedDict[Tuple, _StoreEntry]" = \
            collections.OrderedDict()
        #: evicted-while-cold-tier-available keys -> their static cost
        #: record: a miss on one of these is a readmit (deserialize), not a
        #: first compile — and its cost stamp is reused instead of re-traced
        self._demoted: Dict[Tuple, Optional[dict]] = {}
        #: active (model, name, build_key) prefix pins (refcounted)
        self._prefix_pins: Dict[Tuple, int] = {}
        #: active model-level placement pins (refcounted): the fleet
        #: placement planner's residency decision — every entry under a
        #: pinned model is unevictable until release
        self._model_pins: Dict[str, int] = {}
        self._budget = int(budget_bytes) if budget_bytes is not None else None
        self._resident = 0
        self._counters = {n: 0 for n in self.COUNTER_NAMES}
        #: model -> {counter: n, resident_bytes implicit via entries}
        self._per_model: Dict[str, Dict[str, int]] = {}
        #: cached telemetry counter handles (one registry lookup per name
        #: per process, not per dispatch)
        self._tel_counters: Dict[str, Any] = {}

    # -- accounting plumbing -------------------------------------------------

    def _count(self, name: str, model: str, n: int = 1) -> None:
        """Caller holds the lock. Mirrors every count into the process
        telemetry registry (``store/<name>`` counters — the Prometheus
        surface; the registry has its own lock and never calls back into
        the store, so the store->registry lock order is acyclic)."""
        self._counters[name] += n
        per = self._per_model.setdefault(
            model, {k: 0 for k in self.COUNTER_NAMES})
        per[name] += n
        handle = self._tel_counters.get(name)
        if handle is None:
            from iwae_replication_project_tpu.telemetry.registry import (
                get_registry)
            handle = self._tel_counters.setdefault(
                name, get_registry().counter(f"store/{name}"))
        handle.inc(n)

    def _publish_resident(self) -> None:
        """Caller holds the lock: export the residency gauges. An unbounded
        budget publishes +Inf — so a dashboard comparing resident vs budget
        reads "infinite headroom", never "permanently over a 0 budget";
        the JSON snapshot surfaces keep the explicit None."""
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)
        reg = get_registry()
        reg.gauge("store/resident_bytes").set(float(self._resident))
        reg.gauge("store/budget_bytes").set(
            float(self._budget) if self._budget is not None
            else float("inf"))
        reg.gauge("store/entries").set(float(len(self._entries)))

    # -- budget --------------------------------------------------------------

    @property
    def budget_bytes(self) -> Optional[int]:
        with self._lock:
            return self._budget

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident

    def set_budget(self, budget_bytes: Optional[int]) -> None:
        """Set (or clear) the device-memory budget; an over-budget resident
        set is evicted down immediately (LRU, pins respected). A negative
        budget is a loud construction error at the ONE shared depth (CLI
        flag and programmatic callers alike) — it would silently put the
        store into permanent evict-everything mode; the env-var path
        (:func:`_budget_from_env`) degrades fail-soft instead because it
        runs at import time."""
        if budget_bytes is not None and int(budget_bytes) < 0:
            raise ValueError(f"store budget must be >= 0 bytes (or None "
                             f"for unbounded), got {int(budget_bytes)}")
        with self._lock:
            self._budget = int(budget_bytes) \
                if budget_bytes is not None else None
            self._evict_over_budget()
            self._publish_resident()

    def _pinned(self, key: Tuple, entry: _StoreEntry) -> bool:
        return entry.pins > 0 or key[:3] in self._prefix_pins \
            or key[0] in self._model_pins

    def _evict_over_budget(self) -> None:
        """Evict LRU unpinned entries until the resident set fits the
        budget (pinned entries are skipped — they are reconsidered at the
        next admission/budget change after release). The lock is reentrant,
        so callers already holding it nest cleanly."""
        with self._lock:
            if self._budget is None:
                return
            cache_active = _cold_tier_active()
            for key in [k for k in self._entries]:  # LRU -> MRU order
                if self._resident <= self._budget:
                    break
                entry = self._entries[key]
                if self._pinned(key, entry):
                    continue
                del self._entries[key]
                self._resident -= entry.bytes
                self._count("evictions", key[0])
                if entry.cold and cache_active:
                    # the serialized program survives in the persistent
                    # XLA cache: this is a demotion to the cold tier, and
                    # the next request readmits by deserializing — never a
                    # fresh compile
                    self._demoted[key] = entry.cost
                    self._count("demotions", key[0])

    # -- pins ----------------------------------------------------------------

    def pin_prefix(self, model: Optional[str], name: str,
                   build_key: Tuple) -> _PrefixPin:
        """Pin every entry (present or future) under ``(model, name,
        build_key)`` against eviction; returns the release handle. The
        serving engines hold one per in-flight dispatch."""
        prefix = (model if model is not None else DEFAULT_MODEL,
                  name, build_key)
        with self._lock:
            self._prefix_pins[prefix] = self._prefix_pins.get(prefix, 0) + 1
        return _PrefixPin(self, prefix)

    def _unpin_prefix(self, prefix: Tuple) -> None:
        with self._lock:
            n = self._prefix_pins.get(prefix, 0) - 1
            if n <= 0:
                self._prefix_pins.pop(prefix, None)
            else:
                self._prefix_pins[prefix] = n
            # a release may unblock a DEFERRED eviction — but only when the
            # resident set actually sits over a budget; the warm-hit fast
            # path (every aot_call pins) must not pay an eviction scan and
            # gauge publications for a no-op release
            if self._budget is not None and self._resident > self._budget:
                self._evict_over_budget()
                self._publish_resident()

    @contextlib.contextmanager
    def pinned(self, model: Optional[str], name: str, build_key: Tuple):
        pin = self.pin_prefix(model, name, build_key)
        try:
            yield
        finally:
            pin.release()

    def pin_model(self, model: Optional[str]) -> _ModelPin:
        """Pin every entry (present or future) under ``model`` against
        eviction; returns the release handle. This is the fleet placement
        planner's residency primitive: a model the cost-model bin-packer
        placed resident stays warm through budget pressure from other
        tenants' traffic until the next placement plan releases it. The
        per-dispatch :meth:`pin_prefix` pins compose independently —
        releasing a model pin never unpins in-flight work."""
        model = model if model is not None else DEFAULT_MODEL
        with self._lock:
            self._model_pins[model] = self._model_pins.get(model, 0) + 1
        return _ModelPin(self, model)

    def _unpin_model(self, model: str) -> None:
        with self._lock:
            n = self._model_pins.get(model, 0) - 1
            if n <= 0:
                self._model_pins.pop(model, None)
            else:
                self._model_pins[model] = n
            # same deferred-eviction rule as prefix-pin release: only pay
            # the scan when the resident set actually sits over budget
            if self._budget is not None and self._resident > self._budget:
                self._evict_over_budget()
                self._publish_resident()

    def model_pins(self) -> Dict[str, int]:
        """The active model-level pin refcounts (snapshot) — the placement
        smoke/tests assert the plan actually landed."""
        with self._lock:
            return dict(self._model_pins)

    def model_costs(self) -> Dict[str, int]:
        """Per-model resident cost: the sum of each resident entry's billed
        bytes (static-cost ``peak_bytes``, arg-bytes fallback) keyed by
        model label. The fleet placement planner's cost model: what one
        replica pays in store budget to keep a model's working set warm."""
        with self._lock:
            costs: Dict[str, int] = {}
            for key, e in self._entries.items():
                costs[key[0]] = costs.get(key[0], 0) + e.bytes
            return costs

    # -- resolution ----------------------------------------------------------

    def _entry_bytes(self, cost: Optional[dict], sig) -> int:
        """The budget bill of one entry: the static cost record's live-range
        peak device bytes (what :func:`static_cost_records` reports — budget
        accounting reconciles with it by construction), else the dispatch
        argument bytes sized from the signature."""
        if cost is not None and cost.get("peak_bytes"):
            return int(cost["peak_bytes"])
        return _signature_arg_bytes(sig)

    def get_or_compile(self, name: str, jitted_fn: Callable, args: Tuple,
                       kwargs: dict, static_kwargs: Optional[dict],
                       build_key: Tuple, count_hit: bool,
                       model: Optional[str] = None):
        """Resolve ``(model, name, build_key, signature)`` to a compiled
        executable, compiling (and accounting the miss) on first sight —
        on a readmit the compile collapses to a persistent-cache
        deserialize. ``count_hit=False`` lets warmup probes re-resolve
        without inflating the hit counters."""
        model = model if model is not None else DEFAULT_MODEL
        key = (model, name, build_key,
               _abstract_signature((args, tuple(sorted(kwargs.items(),
                                                       key=lambda kv: kv[0]))
                                    )))
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)      # MRU
                if count_hit:
                    self._count("hits", model)
                    _counters["aot_hits"] += 1
                return entry.exe
            readmit = key in self._demoted
            demoted_cost = self._demoted.get(key)
        # miss: compile OUTSIDE the lock (seconds of XLA work — or a fast
        # deserialize on a readmit — must not serialize other dispatches)
        t0 = time.perf_counter()
        lowered = jitted_fn.lower(*args, **kwargs, **(static_kwargs or {}))
        exe = lowered.compile()
        # compile already cost seconds; the trace-only cost stamp rides the
        # miss (fail-soft, IWAE_STATIC_COST=off to disable) — a readmit
        # reuses the record its demotion carried instead of re-tracing
        cost = demoted_cost if readmit else \
            _trace_static_cost(name, jitted_fn, args, kwargs,
                               static_kwargs, key[3])
        cold = _cold_tier_active()
        with self._lock:
            self._demoted.pop(key, None)
            prev = self._entries.pop(key, None)     # racing double-compile
            if prev is not None:
                self._resident -= prev.bytes
            entry = _StoreEntry(exe, cost, self._entry_bytes(cost, key[3]),
                                cold)
            self._entries[key] = entry
            self._resident += entry.bytes
            self._count("misses", model)
            if readmit:
                self._count("readmits", model)
            _counters["aot_misses"] += 1
            _counters["aot_compile_seconds"] += time.perf_counter() - t0
            self._evict_over_budget()
            self._publish_resident()
        return exe

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> List[Tuple]:
        """Entry keys, LRU -> MRU order (tests pin eviction order on it)."""
        with self._lock:
            return list(self._entries)

    def entries(self) -> List[dict]:
        """Resident-entry snapshot, LRU -> MRU: model/name/bytes/pins/cold
        per entry (the ``iwae-serve`` stats surface and the tests')."""
        with self._lock:
            return [{"model": key[0], "name": key[1], "bytes": e.bytes,
                     "pinned": self._pinned(key, e), "cold": e.cold}
                    for key, e in self._entries.items()]

    def scalar_stats(self) -> dict:
        """Counters + residency scalars only — no per-model aggregation
        (which walks every entry) — for :func:`cache_stats`, which the
        serving engine diffs TWICE per dispatched batch."""
        with self._lock:
            return {**self._counters,
                    "resident_bytes": self._resident,
                    "budget_bytes": self._budget,
                    "entries": len(self._entries)}

    def stats(self) -> dict:
        """Counters + residency, overall and per model."""
        with self._lock:
            per_model: Dict[str, dict] = {
                m: dict(c) for m, c in self._per_model.items()}
            for key, e in self._entries.items():
                per = per_model.setdefault(
                    key[0], {k: 0 for k in self.COUNTER_NAMES})
                per["resident_bytes"] = per.get("resident_bytes", 0) + e.bytes
                per["entries"] = per.get("entries", 0) + 1
            for per in per_model.values():
                per.setdefault("resident_bytes", 0)
                per.setdefault("entries", 0)
            return {**{k: v for k, v in self._counters.items()},
                    "resident_bytes": self._resident,
                    "budget_bytes": self._budget,
                    "entries": len(self._entries),
                    "demoted": len(self._demoted),
                    "model_pins": dict(self._model_pins),
                    "per_model": per_model}

    def signatures(self) -> List[Tuple]:
        """``(name, build_key, signature)`` per entry (the audit surface —
        see :func:`registry_signatures`)."""
        with self._lock:
            return [(name, build_key, sig)
                    for (_model, name, build_key, sig) in self._entries]

    def cost_records(self) -> List[Tuple]:
        """``(name, build_key, signature, static_cost | None)`` per entry
        (see :func:`static_cost_records`)."""
        with self._lock:
            return [(key[1], key[2], key[3], e.cost)
                    for key, e in self._entries.items()]

    def cost_for(self, model: Optional[str], name: str,
                 build_key: Tuple) -> Optional[dict]:
        """The static cost record of one ``(model, name, build_key)``
        program (signature-agnostic: a program's cost is per build, and
        the serving engines dispatch one signature per build key anyway).
        Demoted entries answer too — eviction is a residency decision,
        not a loss of the compile-time stamp, and the profiling plane's
        static ceiling (telemetry/profiling.py) must not go blind when a
        budget squeeze rotates a program to the cold tier.  None when no
        entry exists or its stamp was skipped/failed."""
        model = model if model is not None else DEFAULT_MODEL
        with self._lock:
            for key, e in self._entries.items():
                if key[:3] == (model, name, build_key) and e.cost is not None:
                    return e.cost
            for key, cost in self._demoted.items():
                if key[:3] == (model, name, build_key) and cost is not None:
                    return cost
        return None

    # -- donation gate -------------------------------------------------------

    def donation_allowed(self, requested: bool = True) -> bool:
        """THE donation-vs-cache gate: whether a caller's requested buffer
        donation may be honored under the active cache setup. The store owns
        executable lifetime AND the persistent-cache wiring, so this is the
        one place the jaxlib-0.4.x XLA:CPU hazard (donation + cache-
        deserialized executables corrupt memory — RESULTS.md §5) is decided;
        call sites pass their request instead of composing their own guard.
        """
        import jax

        if not requested:
            return False
        if not getattr(jax.config, "jax_compilation_cache_dir", None):
            return True  # no cache -> nothing deserialized -> donation fine
        return jax.default_backend() != "cpu"

    # -- state swap (test isolation) -----------------------------------------

    def _swap_state(self, entries=None, demoted=None, budget=_UNSET):
        """Replace entries/demoted (and optionally the budget), returning
        the previous triple — :func:`isolated_aot_registry`'s mechanism."""
        with self._lock:
            prev = (self._entries, self._demoted, self._budget)
            self._entries = entries if entries is not None \
                else collections.OrderedDict()
            self._demoted = demoted if demoted is not None else {}
            if budget is not _UNSET:
                self._budget = budget
            self._resident = sum(e.bytes for e in self._entries.values())
            self._evict_over_budget()
            self._publish_resident()
            return prev


def _budget_from_env() -> Optional[int]:
    """``IWAE_STORE_BUDGET_BYTES`` as the default store's budget. Fail-soft
    by design: this runs at import time, and a typo in an env var must
    degrade LOUDLY to the unbounded default rather than make the whole
    package unimportable."""
    raw = (os.environ.get("IWAE_STORE_BUDGET_BYTES") or "").strip()
    if raw.lower() in _OFF:
        return None
    try:
        value = int(float(raw))
    except ValueError:
        import warnings

        warnings.warn(f"IWAE_STORE_BUDGET_BYTES={raw!r} is not a number; "
                      f"executable-store budget left UNBOUNDED")
        return None
    if value < 0:
        import warnings

        warnings.warn(f"IWAE_STORE_BUDGET_BYTES={value} is negative; "
                      f"executable-store budget left UNBOUNDED")
        return None
    return value


#: the process-default store every module-level helper routes through
_store = ExecutableStore(budget_bytes=_budget_from_env())


def executable_store() -> ExecutableStore:
    """The process-default :class:`ExecutableStore` (the module-level AOT
    helpers' backing store)."""
    return _store


def set_store_budget(budget_bytes: Optional[int]) -> None:
    """Set the default store's device-memory budget (None = unbounded);
    evicts immediately when the resident set exceeds it."""
    _store.set_budget(budget_bytes)


def store_stats() -> dict:
    """The default store's counter/residency snapshot (overall + per
    model) — what ``ServingMetrics.snapshot()['store']`` and the
    multi-model bench/smoke read."""
    return _store.stats()


def _abstract_signature(args: Tuple) -> Tuple:
    """Hashable (treedef, per-leaf shape/dtype/sharding/weak) fingerprint of
    a call.

    Shardings are part of the signature: the same pytree placed under a
    different mesh (or re-placed single-device) must map to its own
    executable, not be fed to one compiled for other devices. Weak-typedness
    is part of it too — a weak-typed leaf traces a different program than its
    committed twin, so folding them into one slot would hand one caller the
    other's executable. The leaf grammar (array 4-tuple vs python-scalar
    2-tuple) is what analysis/audit's recompile-cardinality pass walks when
    it flags signatures that fragment this registry.
    """
    import jax

    leaves, treedef = jax.tree.flatten(args)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sharding = getattr(leaf, "sharding", None)
            sig.append((tuple(leaf.shape), str(leaf.dtype),
                        str(sharding) if sharding is not None else "",
                        bool(getattr(leaf, "weak_type", False))))
        else:  # python scalar etc. — weak-typed; key on type + value
            sig.append((type(leaf).__name__, repr(leaf)))
    return (str(treedef), tuple(sig))


def registry_signatures() -> list:
    """``(name, build_key, signature)`` for every resident executable.

    The audit CLI's recompile-cardinality pass walks these to flag python-
    scalar and weak-typed signature leaves — each of which mints one
    executable per distinct value and fragments the store under serving
    traffic. (The model label is deliberately absent: program fragmentation
    is per program, not per tenant.)
    """
    return _store.signatures()


def static_cost_records() -> list:
    """``(name, build_key, signature, static_cost | None)`` per resident
    executable.

    ``static_cost`` is the trace-time cost record (peak HBM bytes, FLOPs,
    arithmetic intensity, per-axis collective counts, plus ``arg_bytes``
    sized from the dispatch signature itself) — exactly what the store
    budgets its LRU eviction with (``peak_bytes``, arg-bytes fallback), and
    what ``iwae-cost --registry`` surfaces. Entries stamped None mean the
    fail-soft trace was skipped (``IWAE_STATIC_COST=off``) or failed.
    """
    return _store.cost_records()


def _signature_arg_bytes(sig) -> int:
    """Total dispatch-argument HBM bytes from one signature record, sized
    through the shared ``utils.dtypes`` byte-width table (the leaf grammar
    is :func:`_abstract_signature`'s: array leaves are 4-tuples carrying a
    dtype *string*; scalar/kwarg-name leaves carry no buffer)."""
    import math

    from iwae_replication_project_tpu.utils.dtypes import byte_width

    _, leaves = sig
    total = 0
    for leaf in leaves:
        if len(leaf) >= 4:
            shape, dtype = leaf[0], leaf[1]
            try:
                total += int(math.prod(shape)) * byte_width(dtype)
            except ValueError:
                # an exotic dtype string outside the shared byte-width
                # table: skip the leaf from the estimate, never crash
                # dispatch over an accounting detail
                continue
    return total


def _trace_static_cost(name: str, jitted_fn: Callable, args: Tuple,
                       kwargs: dict, static_kwargs: Optional[dict],
                       sig) -> Optional[dict]:
    """Stamp a registry entry's static cost record at compile time.

    Trace-only (``jax.make_jaxpr`` — no second compile) and strictly
    fail-soft: a miss already pays seconds of XLA compile, so the extra
    trace is noise there, but ANY analyzer failure must degrade to a None
    record rather than poison the dispatch path. ``IWAE_STATIC_COST=off``
    disables the stamp wholesale.
    """
    flag = os.environ.get("IWAE_STATIC_COST")
    if flag is not None and flag.strip().lower() in _OFF:
        return None
    try:
        import functools

        import jax

        from iwae_replication_project_tpu.analysis.audit.cost import (
            CostAnalyzer)
        fn = functools.partial(jitted_fn, **(static_kwargs or {}))
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        rec, _ = CostAnalyzer().analyze_jaxpr(name, closed)
        cost = rec.to_dict()
        cost["arg_bytes"] = _signature_arg_bytes(sig)
        return cost
    except Exception:
        # fail-soft by contract: a cost-stamp failure must never break the
        # serving dispatch path; the entry simply carries no record
        return None


def aot_call_async(name: str, jitted_fn: Callable, args: Tuple = (),
                   kwargs: Optional[dict] = None,
                   static_kwargs: Optional[dict] = None,
                   build_key: Tuple = (),
                   model: Optional[str] = None) -> Any:
    """Enqueue ``jitted_fn(*args, **kwargs, **static_kwargs)`` via the
    registry and return the resulting **device arrays without any host
    synchronization** — the explicitly-async AOT call path.

    JAX dispatch is asynchronous: the returned arrays are futures over
    device buffers, and the call returns as soon as the execution is queued.
    Callers that pipeline (the serving engine's dispatcher thread) hold the
    result and perform the blocking device→host fetch (``np.asarray``)
    elsewhere — overlapping the next dispatch with the in-flight compute.
    Shares the executable registry, the hit/miss accounting, and the
    ``aot/<name>`` span with :func:`aot_call` (the span time is enqueue, not
    device completion, by design).

    First call per ``(name, build_key, signature(args, kwargs))``:
    ``jitted_fn.lower(...).compile()`` (a registry *miss*; the lower+compile
    wall time — which collapses to deserialization on a persistent-cache hit
    — is accounted as ``aot_compile_seconds``). Every later call reuses the
    compiled executable (a *hit*) with zero tracing or cache-key hashing of
    the jaxpr. ``build_key`` must capture everything the caller baked into
    the closure (objective spec, model config, n_train, donation, mesh, ...):
    two distinct programs must never share a store slot. ``model`` labels the
    tenant (zoo preset / checkpoint) the entry belongs to — the store's
    per-model accounting and eviction attribution; None = the single-model
    default label. The entry is pinned against eviction for the duration of
    the resolve + enqueue.
    """
    kwargs = kwargs or {}
    with _store.pinned(model, name, build_key):
        exe = _store.get_or_compile(name, jitted_fn, args, kwargs,
                                    static_kwargs, build_key, count_hit=True,  # iwaelint: disable=key-reuse -- build_key is a program-identity tuple, not a PRNG key: handing it to both the pin and the resolver is the contract, no randomness is consumed
                                    model=model)
        # chaos hook (utils/faults.py): every AOT dispatch passes this point,
        # so an injected raise here models the enqueue-time failure class
        # (OOM, poisoned runtime) for ANY program; off = one None check
        fault_point(SITE_AOT_CALL_ASYNC, name=name)
        # every AOT dispatch in the process funnels through here — the ONE
        # span site that covers training epochs, the fused eval suite, and
        # serving alike (the time recorded is enqueue, not device
        # completion: async dispatch returns as soon as the transfer
        # program is queued)
        from iwae_replication_project_tpu.telemetry.spans import span
        with span(f"aot/{name}"):
            return exe(*args, **kwargs)


def aot_call(name: str, jitted_fn: Callable, args: Tuple = (),
             kwargs: Optional[dict] = None,
             static_kwargs: Optional[dict] = None,
             build_key: Tuple = (),
             model: Optional[str] = None) -> Any:
    """Call ``jitted_fn(*args, **kwargs, **static_kwargs)`` via the registry.

    The historical name for :func:`aot_call_async` — JAX dispatch has always
    been async, so the two are the same operation; use ``aot_call_async``
    where the no-host-sync contract is load-bearing (pipelined serving) and
    this name where the caller fetches (or chains) immediately.

    Donation declared on `jitted_fn` is preserved by the compiled executable.
    The executable is invoked with the dynamic arguments only
    (`static_kwargs` are compile-time constants, already burned into the
    program — pass statics that interleave positionally by keyword).
    """
    return aot_call_async(name, jitted_fn, args, kwargs=kwargs,
                          static_kwargs=static_kwargs, build_key=build_key,
                          model=model)


def aot_warm(name: str, jitted_fn: Callable, args: Tuple = (),
             kwargs: Optional[dict] = None,
             static_kwargs: Optional[dict] = None,
             build_key: Tuple = (),
             model: Optional[str] = None) -> Any:
    """Populate the store for this call signature WITHOUT executing.

    The bucket-warmup API for online serving (serving/engine.py): an engine
    pre-compiles one executable per (op, shape bucket, k, dtype) ladder rung
    at startup, so the first live request of every bucket is already a
    store hit — no compile storm under ragged traffic. Returns the
    executable. A signature already present is a no-op (and is NOT counted
    as an aot hit: warmup probes must not skew the serving hit-rate metric).
    """
    return _store.get_or_compile(name, jitted_fn, args, kwargs or {},
                                 static_kwargs, build_key, count_hit=False,
                                 model=model)


def warm_callable(name: str, jitted_fn: Callable,
                  build_key: Tuple = (),
                  model: Optional[str] = None) -> Callable:
    """Wrap a jitted function so every call routes through :func:`aot_call`.

    Drop-in for the driver's epoch/step functions: same call signature, same
    results, but the compiled executable is shared process-wide per
    ``(model, name, build_key, arg signature)`` — across stages, across
    ``PASS_BLOCK`` blocks, and across `run_experiment` invocations.
    """
    def call(*args):
        return aot_call(name, jitted_fn, args, build_key=build_key,
                        model=model)

    call.__name__ = f"warm_{name}"
    return call


@contextlib.contextmanager
def isolated_aot_registry(budget_bytes=_UNSET):
    """Run with an EMPTY executable store; restore the previous contents
    (dropping entries created inside) on exit. ``budget_bytes`` optionally
    sets a store budget for the duration (the multi-model bench/tests
    exercise eviction this way without disturbing the process default).

    For tests that compare two driver runs: the store is process-global
    and keyed by build signature only, so a run inside a test can silently
    reuse an executable an earlier test compiled under different cache /
    donation conditions — making the two compared runs asymmetric (one fresh
    compile, one reuse). Isolation restores the symmetry the comparison
    assumes.
    """
    prev_entries, prev_demoted, prev_budget = _store._swap_state(
        budget=budget_bytes)
    try:
        yield
    finally:
        _store._swap_state(entries=prev_entries, demoted=prev_demoted,
                           budget=prev_budget)


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------

def cache_stats() -> dict:
    """Snapshot of the process-global warm-path counters.

    ``persistent_cache_misses`` counts true XLA backend compiles whose result
    was not in the on-disk cache — the number a warm start (and a store
    readmit) must hold at zero. ``aot_*`` count the executable-store
    behavior; ``backend_compile_seconds`` is total time inside XLA's compile
    entry point (on a warm start it collapses to cache-deserialization
    time). ``store_*`` are the capacity-bound counters: evictions under the
    budget, demotions to the persistent-cache cold tier, and readmits
    (deserializing re-entries of demoted programs).
    """
    with _lock:
        snap = dict(_counters)
    snap["cache_dir"] = _state["dir"]
    st = _store.scalar_stats()
    snap["aot_executables"] = st["entries"]
    for name in ExecutableStore.COUNTER_NAMES:
        snap[f"store_{name}"] = st[name]
    snap["store_resident_bytes"] = st["resident_bytes"]
    snap["store_budget_bytes"] = st["budget_bytes"]
    return snap


def stats_delta(before: dict, after: Optional[dict] = None) -> dict:
    """Numeric field-wise ``after - before`` of two :func:`cache_stats`
    snapshots (non-numeric fields are taken from `after`)."""
    if after is None:
        after = cache_stats()
    out = {}
    for k, v in after.items():
        b = before.get(k, 0)
        out[k] = v - b if isinstance(v, (int, float)) \
            and not isinstance(v, bool) and isinstance(b, (int, float)) else v
    return out
