"""Experiment configuration: one dataclass + CLI binding.

The reference has no config system — hyperparameters are ctor kwargs plus
constants edited in the script (experiment_example.py:35-58). This dataclass
covers that whole surface, adds the TPU-execution knobs (mesh, dtype,
backend), and round-trips to JSON for checkpoint metadata.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Optional, Tuple

from iwae_replication_project_tpu.models.iwae import ModelConfig
from iwae_replication_project_tpu.objectives.estimators import ObjectiveSpec

#: the config fields that define an experiment's identity (see science_fields)
SCIENCE_FIELDS = (
    "dataset", "n_hidden_encoder", "n_hidden_decoder",
    "n_latent_encoder", "n_latent_decoder", "loss_function", "k", "p",
    "alpha", "beta", "k2", "batch_size", "adam_eps",
    "seed", "switch_stage", "switch_loss", "switch_k", "likelihood",
    "passes_scale")


@dataclasses.dataclass
class ExperimentConfig:
    # data (experiment_example.py:25-31)
    dataset: str = "binarized_mnist"
    data_dir: str = "data"
    allow_synthetic: bool = True

    # architecture (experiment_example.py:48-51 defaults: the 2L flagship)
    n_hidden_encoder: Tuple[int, ...] = (200, 100)
    n_hidden_decoder: Tuple[int, ...] = (100, 200)
    n_latent_encoder: Tuple[int, ...] = (100, 50)
    n_latent_decoder: Tuple[int, ...] = (100, 784)

    # objective (experiment_example.py:54-58)
    loss_function: str = "IWAE"
    k: int = 50
    p: float = 1.0
    alpha: float = 1.0
    beta: float = 0.5
    k2: int = 1  # MIWAE/PIWAE outer count

    # training (experiment_example.py:35-40; PDF §3.4)
    batch_size: int = 100
    n_stages: int = 8
    adam_eps: float = 1e-4
    seed: int = 0
    # Burda-schedule length multiplier: stage i trains
    # max(1, round(3^(i-1) * passes_scale)) passes. 1.0 = the paper's 3280-pass
    # schedule (tuned for 50k-image MNIST). Small datasets overfit under it
    # (digits, 1.5k images, peaks around stage 5-6 — RESULTS.md §2); a
    # proportional scale keeps the geometric LR/passes structure while
    # matching total optimization to dataset size.
    passes_scale: float = 1.0

    # objective switching (PDF Table 10, p.13): from `switch_stage` on, train
    # with `switch_loss` (and `switch_k` if given) instead of `loss_function`.
    switch_stage: Optional[int] = None
    switch_loss: Optional[str] = None
    switch_k: Optional[int] = None

    # evaluation (flexible_IWAE.py:496-526)
    eval_k: int = 50
    nll_k: int = 5000
    # streaming-NLL chunk: 250 since round 4 (~30% faster at k=5000 than the
    # 100 used through round 3, RESULTS.md §4). The chunk size determines the
    # eval RNG stream, so every metrics.jsonl row records the nll_chunk it was
    # computed under; pre-r4 artifacts (chunk 100) carry it in their
    # checkpoint config.json instead.
    nll_chunk: int = 250
    # 500 since round 5 (was 200 in r4, 100 before): the r5 sweep under the
    # bf16 default measured 13.3k img/s at 500 vs 12.2k at 200 (+9%,
    # RESULTS.md §4) — batches past the Pallas kernel's forward VMEM gate
    # run the unfused XLA path, and above ~400 the fewer/larger dispatches
    # win over the fused small-batch path; 2500+ regresses again. Like
    # nll_chunk, the eval batch versions the per-batch eval RNG folding —
    # every metrics.jsonl row stamps the effective `eval_batch`; older
    # artifacts carry their value in their checkpoint config.json.
    eval_batch_size: int = 500
    activity_samples: int = 1000

    # execution
    backend: str = "jax"          # "jax" | "torch" (eager CPU oracle) | "tf2" (gated)
    mesh_dp: Optional[int] = None  # None -> all devices
    mesh_sp: int = 1
    # join a jax.distributed cluster before any device computation
    # (multi-host jobs; parallel/multihost.py). coordinator/num_processes/
    # process_id stay None on TPU pods (auto-detected); set all three
    # explicitly elsewhere (e.g. "host:1234", 2, rank).
    multihost: bool = False
    coordinator: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # None (f32 matmuls) | "bfloat16" (bf16 matmul operands, f32 accumulation
    # and parameters). Default bfloat16 since round 5: the scaled-schedule
    # digits seed study (results/summary_seeds_scaled_bf16.json, RESULTS.md
    # §2b) shows final NLLs within -0.36..+0.04 nats of f32 — inside every
    # config's f32 seed spread (0.4-1.8 nats) — and throughput is
    # neutral-to-positive (increasingly favorable at MXU-filling widths).
    # compute_dtype is an execution knob, not a science field: stored config
    # JSONs pin their own value, so pre-r5 checkpoints/configs reproduce
    # their f32 numbers exactly; every metrics row stamps `bfloat16`.
    compute_dtype: Optional[str] = "bfloat16"
    # serving precision policy (ISSUE 16): None (the historical fp32 path)
    # | "fp32" | "bf16" | "int8" — the per-model policy zoo.serving_engine
    # hands the ServingEngine when this config is served. A SERVING knob:
    # training/eval never read it, and it is not a science field (does not
    # change run_name()). Typos die in __post_init__, the same
    # loud-unknown contract as compute_dtype — a misspelled policy must
    # never silently serve fp32.
    serving_precision: Optional[str] = None

    def __post_init__(self):
        # now that bf16 must be actively turned OFF, the opt-out must not
        # depend on typos silently meaning f32: only these values are legal,
        # and "float32" normalizes to None (the ModelConfig f32 encoding)
        if self.compute_dtype == "float32":
            self.compute_dtype = None
        if self.compute_dtype not in (None, "bfloat16"):
            raise ValueError(
                f"compute_dtype must be None, 'float32' or 'bfloat16', got "
                f"{self.compute_dtype!r}")
        if self.serving_precision is not None:
            from iwae_replication_project_tpu.utils.dtypes import (
                validate_precision)
            validate_precision(self.serving_precision)
        if self.checkpoint_every_passes < 0:
            raise ValueError(
                f"checkpoint_every_passes must be >= 0 (0 = stage boundaries "
                f"only), got {self.checkpoint_every_passes}")
    # "logits" is the exact Bernoulli log-likelihood x*l - softplus(l) — the
    # fast path bench.py measures, and the default since round 3 (NLL-
    # neutrality vs "clamp" on a trained model is asserted by
    # tests/test_convergence.py::test_likelihood_modes_nll_neutral).
    # "clamp" reproduces the reference's sigmoid+clamp bit-for-bit
    # (flexible_IWAE.py:102) and remains selectable for parity work.
    # NOTE the FlexibleModel facade defaults to "clamp" instead
    # (backends/jax_backend.py ctor) — intentional: the facade is the
    # reference-parity surface, this config is the production one.
    likelihood: str = "logits"
    # blocked hot-loop dispatcher (ops/hot_loop): the decoder scoring block
    # fused over (k, batch) tiles, with per-shape blocked-scan / unfused
    # fallback. None = auto: enabled on TPU when likelihood == "logits".
    fused_likelihood: Optional[bool] = None

    # warm-path execution (utils/compile_cache.py). compile_cache_dir: None =
    # the default — JAX's persistent compilation cache lands under
    # `<checkpoint_dir>/.jax_compile_cache`, so a preemption-resume pays zero
    # recompiles; a path overrides the location; "off" disables. The
    # IWAE_COMPILE_CACHE env fills in whenever the field is left None.
    # Execution knob, not a science field (does not change run_name()).
    compile_cache_dir: Optional[str] = None
    # donate the train-state buffers to each epoch dispatch (the old state is
    # dead the moment the new one returns, so XLA may update parameters and
    # Adam moments in place instead of holding both copies live). Escape
    # hatch: --no-donate-buffers / donate_buffers=False reproduces the
    # round-<=5 donate=False driver behavior. Per-leaf bit-identity between
    # the two modes is pinned by tests/test_compile_cache.py. NOTE the driver
    # additionally gates this on compile_cache.donation_safe(): jaxlib-0.4.x
    # XLA:CPU corrupts memory when donated programs are deserialized from the
    # persistent cache, so on CPU with the cache active donation is dropped.
    donate_buffers: bool = True

    # observability / persistence
    # on-device estimator diagnostics (telemetry/diagnostics.py): ESS /
    # log-weight variance / KL / active units per eval, gradient SNR over the
    # trailing snr_window train steps. Pure in-graph reductions — zero extra
    # host syncs; --no-diagnostics restores the byte-identical pre-telemetry
    # programs (bench.py --telemetry pins the off-mode as free). Execution
    # knob, not a science field (does not change run_name()).
    diagnostics: bool = True
    snr_window: int = 50
    save_figures: bool = True  # per-stage sample/reconstruction PNG grids
    log_dir: str = "runs"
    checkpoint_dir: str = "checkpoints"
    checkpoint_keep: int = 3
    resume: bool = True
    # also checkpoint every N passes *inside* a stage (0 = stage boundaries
    # only). Stage 8 alone is 2187 of the schedule's 3280 passes — without
    # this a preemption near the end of a real run loses two thirds of the
    # work. Saves land on dispatch boundaries (single passes, or PASS_BLOCK
    # multiples during the fused late stages), so the cadence is "at the
    # first boundary >= N passes since the last save". Resume restarts
    # mid-stage bit-identically (the whole-epoch scan carries the RNG key).
    checkpoint_every_passes: int = 0
    # preemption grace (experiment.py + utils/faults.PreemptionGuard): absorb
    # SIGTERM/SIGINT, finish the in-flight pass, force-save a mid-stage
    # checkpoint, and exit with the distinct PREEMPTED_EXIT_CODE (75) so the
    # scheduler re-runs the same command and resume continues bitwise.
    # --no-preemption-grace restores die-immediately. Execution knob, not a
    # science field (does not change run_name()).
    preemption_grace: bool = True

    def model_config(self) -> ModelConfig:
        fused = self.fused_likelihood
        if fused is None:
            from iwae_replication_project_tpu.models.iwae import _on_tpu
            fused = self.likelihood == "logits" and _on_tpu()
        return ModelConfig(
            n_hidden_enc=tuple(self.n_hidden_encoder),
            n_latent_enc=tuple(self.n_latent_encoder),
            n_hidden_dec=tuple(self.n_hidden_decoder),
            n_latent_dec=tuple(self.n_latent_decoder),
            likelihood=self.likelihood,
            compute_dtype=self.compute_dtype,
            fused_likelihood=bool(fused),
        )

    def diagnostics_config(self):
        """The telemetry DiagnosticsConfig this run trains/evals under, or
        None when diagnostics are off (the gate every jitted call site keys
        its program variant on)."""
        if not self.diagnostics:
            return None
        from iwae_replication_project_tpu.telemetry.diagnostics import (
            DiagnosticsConfig)
        return DiagnosticsConfig(enabled=True, snr_window=self.snr_window)

    def objective_spec(self, stage: Optional[int] = None) -> ObjectiveSpec:
        """The objective in effect at `stage` (1-based; None -> the base one)."""
        name, k = self.loss_function, self.k
        if (self.switch_stage is not None and stage is not None
                and stage >= self.switch_stage):
            name = self.switch_loss or name
            k = self.switch_k if self.switch_k is not None else k
        return ObjectiveSpec(name=name, k=k, p=self.p, alpha=self.alpha,
                             beta=self.beta, k2=self.k2)

    def science_fields(self) -> dict:
        """The fields that define the *experiment identity* — everything that
        changes what is being trained/measured, excluding output paths,
        execution knobs (mesh/backend/dtype do not change the science), and
        `n_stages` (extending the schedule and resuming is the intended
        workflow)."""
        return {f: getattr(self, f) for f in SCIENCE_FIELDS}

    def run_name(self) -> str:
        """`IWAE-2L-k_50-binarized_mnist-s0-1a2b3c4d`-style tag.

        Extends the reference's `{loss}-{L}L-k_{k}` naming
        (experiment_example.py:67,95) with dataset, seed, and a hash of every
        science field, so presets that differ only in alpha/beta/p/k2/switch_*
        cannot collide in checkpoint_dir/log_dir (a collision plus resume=True
        would silently restore the wrong experiment's weights)."""
        import hashlib
        ident = hashlib.sha1(
            json.dumps(self.science_fields(), sort_keys=True, default=list)
            .encode()).hexdigest()[:8]
        return (f"{self.loss_function}-{len(self.n_hidden_encoder)}L-k_{self.k}"
                f"-{self.dataset}-s{self.seed}-{ident}")

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "ExperimentConfig":
        d = json.loads(s)
        for key in ("n_hidden_encoder", "n_hidden_decoder", "n_latent_encoder",
                    "n_latent_decoder"):
            d[key] = tuple(d[key])
        return ExperimentConfig(**d)


def _int_list(s: str) -> Tuple[int, ...]:
    return tuple(int(v) for v in s.split(",") if v)


def build_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="iwae_replication_project_tpu",
        description="TPU-native IWAE framework experiment runner")
    d = ExperimentConfig()
    ap.add_argument("--config", type=str, default=None,
                    help="JSON config file; CLI flags override it")
    ap.add_argument("--preset", type=str, default=None,
                    help="named experiment from the zoo (reference Tables 1-10);"
                         " CLI flags override it")
    ap.add_argument("--list-presets", action="store_true", default=False,
                    help="print all zoo preset names and exit")
    ap.add_argument("--dataset", default=None, type=str)
    ap.add_argument("--data-dir", dest="data_dir", default=None, type=str)
    ap.add_argument("--loss-function", dest="loss_function", default=None, type=str)
    ap.add_argument("--k", default=None, type=int)
    ap.add_argument("--k2", default=None, type=int)
    ap.add_argument("--p", default=None, type=float)
    ap.add_argument("--alpha", default=None, type=float)
    ap.add_argument("--beta", default=None, type=float)
    ap.add_argument("--batch-size", dest="batch_size", default=None, type=int)
    ap.add_argument("--n-stages", dest="n_stages", default=None, type=int)
    ap.add_argument("--passes-scale", dest="passes_scale", default=None,
                    type=float)
    ap.add_argument("--seed", default=None, type=int)
    ap.add_argument("--backend", default=None, type=str)
    ap.add_argument("--mesh-dp", dest="mesh_dp", default=None, type=int)
    ap.add_argument("--mesh-sp", dest="mesh_sp", default=None, type=int)
    ap.add_argument("--multihost", dest="multihost", default=None,
                    action="store_true",
                    help="join the jax.distributed cluster before building "
                         "the mesh (TPU pods: coordinator auto-detected)")
    ap.add_argument("--coordinator", default=None, type=str,
                    help="jax.distributed coordinator host:port (omit on "
                         "TPU pods)")
    ap.add_argument("--num-processes", dest="num_processes", default=None,
                    type=int)
    ap.add_argument("--process-id", dest="process_id", default=None, type=int)
    ap.add_argument("--compute-dtype", dest="compute_dtype", default=None, type=str)
    ap.add_argument("--serving-precision", dest="serving_precision",
                    default=None, type=str,
                    help="serving precision policy for this config "
                         "(fp32 | bf16 | int8); read by zoo.serving_engine "
                         "/ iwae-serve, never by training")
    ap.add_argument("--likelihood", default=None, type=str)
    ap.add_argument("--fused-likelihood", dest="fused_likelihood",
                    action="store_true", default=None)
    ap.add_argument("--no-fused-likelihood", dest="fused_likelihood",
                    action="store_false", default=None)
    ap.add_argument("--log-dir", dest="log_dir", default=None, type=str)
    ap.add_argument("--checkpoint-dir", dest="checkpoint_dir", default=None, type=str)
    ap.add_argument("--checkpoint-every-passes", dest="checkpoint_every_passes",
                    default=None, type=int,
                    help="also checkpoint every N passes inside a stage "
                         "(0 = stage boundaries only)")
    ap.add_argument("--compile-cache-dir", dest="compile_cache_dir",
                    default=None, type=str,
                    help="persistent XLA compilation cache directory "
                         "(default: <checkpoint-dir>/.jax_compile_cache; "
                         "'off' disables; IWAE_COMPILE_CACHE env also honored)")
    ap.add_argument("--no-donate-buffers", dest="donate_buffers",
                    action="store_false", default=None,
                    help="disable train-state buffer donation in the staged "
                         "driver (the pre-warm-path behavior)")
    ap.add_argument("--no-diagnostics", dest="diagnostics",
                    action="store_false", default=None,
                    help="disable the on-device estimator diagnostics "
                         "(ESS / log-weight variance / grad SNR) — restores "
                         "the byte-identical pre-telemetry programs")
    ap.add_argument("--snr-window", dest="snr_window", default=None, type=int,
                    help="trailing train steps in the gradient-SNR estimate")
    ap.add_argument("--no-preemption-grace", dest="preemption_grace",
                    action="store_false", default=None,
                    help="die immediately on SIGTERM/SIGINT instead of "
                         "finishing the pass, force-saving a mid-stage "
                         "checkpoint, and exiting 75 (EX_TEMPFAIL)")
    ap.add_argument("--no-resume", dest="resume", action="store_false", default=None)
    ap.add_argument("--no-figures", dest="save_figures", action="store_false",
                    default=None)
    ap.add_argument("--hidden-encoder", dest="n_hidden_encoder", default=None, type=_int_list)
    ap.add_argument("--hidden-decoder", dest="n_hidden_decoder", default=None, type=_int_list)
    ap.add_argument("--latent-encoder", dest="n_latent_encoder", default=None, type=_int_list)
    ap.add_argument("--latent-decoder", dest="n_latent_decoder", default=None, type=_int_list)
    ap.add_argument("--eval-k", dest="eval_k", default=None, type=int)
    ap.add_argument("--nll-k", dest="nll_k", default=None, type=int)
    ap.add_argument("--nll-chunk", dest="nll_chunk", default=None, type=int)
    return ap


def config_from_args(argv=None) -> ExperimentConfig:
    ap = build_argparser()
    ns = ap.parse_args(argv)
    if ns.list_presets:
        from iwae_replication_project_tpu import zoo
        for name in zoo.configs():
            print(name)
        raise SystemExit(0)
    if ns.preset:
        from iwae_replication_project_tpu import zoo
        cfg = zoo.get(ns.preset)
    elif ns.config:
        with open(ns.config) as f:
            cfg = ExperimentConfig.from_json(f.read())
    else:
        cfg = ExperimentConfig()
    for field in dataclasses.fields(ExperimentConfig):
        v = getattr(ns, field.name, None)
        if v is not None:
            setattr(cfg, field.name, v)
    # CLI overrides bypass construction — re-run the field validation
    # (normalizes --compute-dtype float32 to None, rejects typos)
    cfg.__post_init__()
    return cfg
