"""The ONE dtype -> byte-width mapping (ISSUE 11 satellite).

Three independent consumers previously spelled this out ad hoc — the fused
kernel's VMEM probe read ``dtype.itemsize`` directly, the AOT registry's
signature records carried dtype *strings* with no way back to bytes, and the
cost analyzer (analysis/audit/cost.py) needs bytes for every aval it sizes.
One table, shared, so "how many bytes is a bf16 row" has exactly one answer
in the codebase:

* :func:`byte_width` — bytes per element for anything dtype-shaped: a numpy
  dtype, a jax/aval dtype (including the extended PRNG-key dtypes, sized by
  their uint32 lanes), a dtype *string* as stored in
  ``compile_cache._abstract_signature`` records, or a weak-typed python
  scalar's inferred dtype (plain ``int``/``float``/``bool``/``complex``
  names map to the x64-off production widths: i32/f32/bool/c64);
* :func:`aval_bytes` — total buffer bytes of one abstract value;
* :data:`PRECISIONS` / :func:`validate_precision` — the serving precision
  policy vocabulary (ISSUE 16): ``fp32`` (the exact oracle), ``bf16``
  (bf16 operands, fp32 accumulation), ``int8`` (weight-only symmetric
  per-output-channel quantization, fp32 scales + accumulation). The byte
  widths above are what make the policy *billable*: an int8-quantized
  executable's signature carries ``int8`` weight leaves plus small fp32
  per-channel scale vectors, so ``compile_cache._signature_arg_bytes`` and
  the cost analyzer size it at its true (smaller) bytes with no special
  casing.

Production numerics are x64-off bf16/f32 (the dtype-promotion lint rule),
so the table is small and explicit; anything unrecognized falls back to
``numpy.dtype`` rather than guessing.
"""

from __future__ import annotations

import math
from typing import Any

#: canonical dtype-name -> bytes per element. Covers the production set
#: (f32/bf16/i32/bool + the RNG plumbing's unsigned ints) plus the python
#: scalar names weak-typed leaves carry under x64-off promotion rules.
BYTE_WIDTHS = {
    "bool": 1, "int8": 1, "uint8": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "float32": 4, "int32": 4, "uint32": 4,
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "complex128": 16,
    # weak-typed python scalars at x64-off (the signature-record leaf
    # grammar stores these as type names)
    "int": 4, "float": 4, "complex": 8,
}


#: the serving precision policies (ISSUE 16). Order is documentation only;
#: ``fp32`` is the default and the statistical-parity oracle.
PRECISIONS = ("fp32", "bf16", "int8")


def validate_precision(precision: Any) -> str:
    """Shared unknown-precision check: one of :data:`PRECISIONS` or
    ValueError (the typed ``bad_request``).

    One implementation for every boundary a precision policy crosses —
    experiment config, engine construction, zoo presets, the ``iwae-serve
    --precision`` CLI, and the wire protocol — so a typo'd policy string
    dies loudly at the first boundary it crosses and is NEVER a silent
    fp32 fallback (which would quietly serve different numerics than the
    tenant asked for).
    """
    if not isinstance(precision, str) or not precision:
        raise ValueError(f"precision must be a non-empty string, got "
                         f"{type(precision).__name__}")
    if precision not in PRECISIONS:
        raise ValueError(f"unknown precision {precision!r}; serving "
                         f"precision policies are {list(PRECISIONS)}")
    return precision


def byte_width(dtype: Any) -> int:
    """Bytes per element of `dtype` (dtype object, aval dtype, or name).

    JAX's extended PRNG-key dtypes (``key<fry>`` etc.) size as their
    underlying uint32 lanes — the bytes the buffer actually occupies.
    """
    name = dtype if isinstance(dtype, str) else getattr(dtype, "name", None)
    if name is not None:
        w = BYTE_WIDTHS.get(str(name))
        if w is not None:
            return w
    # extended dtypes (PRNG keys): the impl declares its uint32 key lanes
    impl = getattr(dtype, "_impl", None)
    key_shape = getattr(impl, "key_shape", None)
    if key_shape is not None:
        return int(math.prod(key_shape)) * 4
    import numpy as np

    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:
        itemsize = getattr(dtype, "itemsize", None)
        if itemsize:
            return int(itemsize)
        raise ValueError(f"no byte width known for dtype {dtype!r}")


def aval_bytes(aval: Any) -> int:
    """Total buffer bytes of one abstract value (0 for shapeless tokens)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(math.prod(shape)) * byte_width(dtype)
