"""Deterministic fault injection: the chaos layer under the failure model.

Every robustness claim in this stack (router reroute, typed rejections,
client retry, preemption resume, checkpoint fallback) is only as good as
the faults it has actually been exercised against. This module is the ONE
seeded, deterministic way to inject them: a :class:`FaultSchedule` of
(site, trigger, action) rules installed process-wide, fired from explicit
:func:`fault_point` hooks threaded through the serving dispatcher, the
replica router, the tier server, ``RemoteEngine``, ``aot_call_async``, and
the experiment driver.

Determinism is the design constraint — a chaos run must be a *repro*, not
a dice roll:

* triggers are **visit counts**, not probabilities: a rule fires on the
  Nth matched visit of its site (``after`` skips the first N, ``times``
  bounds total firings), so the same code path under the same traffic
  produces the same fault sequence every run;
* the schedule's ``seed`` feeds per-rule ``random.Random`` streams used
  only where an action wants jitter (:func:`delay`) — same seed, same
  jitter;
* every firing is appended to :attr:`FaultSchedule.log` — the audit trail
  the chaos smoke commits next to its pass/fail verdict.

Off mode is the production mode: :func:`fault_point` with no schedule
installed is one module-global load and a ``None`` check — it never
touches the ``ctx`` kwargs beyond building the dict, runs entirely on the
host, and is invisible to tracing, so compiled programs are byte-identical
with the hooks present (pinned by tests/test_faults.py).

:class:`PreemptionGuard` lives here too: the resilience half of the
SIGTERM story (catch the signal, finish the current pass, let the driver
checkpoint and exit with a distinct code) that the :func:`sigterm` action
exists to exercise.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "FaultInjected", "FaultContext", "FaultRule", "FaultSchedule",
    "fault_point", "install", "clear", "installed", "active",
    "raise_fault", "raise_error", "delay", "sigterm", "call",
    "PreemptionGuard",
    "SITE_AOT_CALL_ASYNC", "SITE_TRAIN_PASS", "SITE_CKPT_SAVE",
]

#: generic (non-serving) fault sites — the serving-layer site names live in
#: serving/faults.py next to their rule builders
SITE_AOT_CALL_ASYNC = "aot.call_async"   # utils/compile_cache.aot_call_async
SITE_TRAIN_PASS = "train.pass"           # experiment driver, after each pass
SITE_CKPT_SAVE = "train.checkpoint.save"  # utils/checkpoint.save_checkpoint


class FaultInjected(RuntimeError):
    """An injected fault. Deliberately NOT one of the typed serving errors:
    the failure model must route it like any unexpected replica exception
    (``internal`` at the wire), which is exactly what a real crash looks
    like."""

    def __init__(self, message: str = "injected fault", site: str = ""):
        super().__init__(message)
        self.site = site


@dataclasses.dataclass
class FaultContext:
    """What an action sees when its rule fires."""

    site: str
    count: int                 # 1-based matched-visit number for the rule
    ctx: Dict[str, Any]        # the fault_point call's keyword arguments
    rng: random.Random         # per-rule deterministic stream (seeded)


Action = Callable[[FaultContext], None]


@dataclasses.dataclass
class FaultRule:
    """One (site, trigger, action) entry of a schedule.

    ``site`` must match the fault point's name exactly; ``match`` (over the
    fault point's ctx kwargs) narrows to e.g. one engine instance or one
    program name. The trigger is count-based: the rule fires on matched
    visits ``after+1 .. after+times`` (``times=None`` = every matched visit
    past ``after``). Counters live in the owning schedule, so one rule
    object may appear in several schedules without cross-talk.
    """

    site: str
    action: Action
    after: int = 0
    times: Optional[int] = 1
    match: Optional[Callable[[Dict[str, Any]], bool]] = None
    name: str = ""             # label for the firing log (default: site)


class FaultSchedule:
    """A seeded, deterministic set of fault rules plus firing state.

    Thread-safe: trigger bookkeeping happens under one lock; actions run
    OUTSIDE it (they may sleep, raise, or close sockets — holding the lock
    through that would serialize unrelated fault points). An action that
    raises propagates out of the instrumented site — that IS the injected
    crash; any later rule matched at the same visit is skipped, like real
    code after a raise.
    """

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._counts = [0] * len(self.rules)
        self._fired = [0] * len(self.rules)
        # per-rule deterministic streams; integer mixing (not tuple
        # seeding, which Python deprecated) keeps replays stable
        self._rngs = [random.Random(self.seed * 1_000_003 + i)
                      for i in range(len(self.rules))]
        #: firing audit trail: (rule name, site, matched-visit count)
        self.log: List[Tuple[str, str, int]] = []

    def fire(self, site: str, **ctx) -> None:
        """Evaluate every rule against one visit of `site`; run the ones
        that trigger (in rule order, outside the lock). Each firing is
        committed (counted + logged) immediately BEFORE its action runs:
        when an earlier action raises — propagating out of the
        instrumented site, like real code after a crash — the later due
        rules are neither logged nor have their ``times`` budget spent, so
        the log never claims a fault that was not actually injected."""
        due: List[Tuple[int, FaultRule, FaultContext]] = []
        with self._lock:
            for i, r in enumerate(self.rules):
                if r.site != site:
                    continue
                if r.match is not None and not r.match(ctx):
                    continue
                self._counts[i] += 1
                if self._counts[i] <= r.after:
                    continue
                if r.times is not None and self._fired[i] >= r.times:
                    continue
                due.append((i, r,
                            FaultContext(site=site, count=self._counts[i],
                                         ctx=ctx, rng=self._rngs[i])))
        for i, r, fc in due:
            with self._lock:
                if r.times is not None and self._fired[i] >= r.times:
                    continue    # a concurrent visit spent the budget first
                self._fired[i] += 1
                self.log.append((r.name or r.site, fc.site, fc.count))
            r.action(fc)

    def fired(self, name: Optional[str] = None) -> int:
        """Total firings (of one rule name, or overall) — smoke accounting."""
        with self._lock:
            return len(self.log) if name is None else \
                sum(1 for n, _, _ in self.log if n == name)


#: the process-wide installed schedule; None = off (the production state)
_ACTIVE: Optional[FaultSchedule] = None


def active() -> Optional[FaultSchedule]:
    return _ACTIVE


def install(schedule: FaultSchedule) -> FaultSchedule:
    """Install `schedule` process-wide (replacing any previous one)."""
    global _ACTIVE
    _ACTIVE = schedule
    return schedule


def clear() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def installed(schedule: FaultSchedule):
    """``with installed(FaultSchedule([...])) as s:`` — scoped install."""
    install(schedule)
    try:
        yield schedule
    finally:
        clear()


def fault_point(site: str, **ctx) -> None:
    """The zero-overhead-when-off hook instrumented code calls.

    Off (no schedule installed): one global load + None check. On: the
    schedule's matching rules run their actions on the calling thread — a
    raising action propagates from HERE, i.e. from inside the instrumented
    site, exactly like an organic failure at that point.
    """
    sched = _ACTIVE
    if sched is not None:
        sched.fire(site, **ctx)


# ---------------------------------------------------------------------------
# action factories
# ---------------------------------------------------------------------------

def raise_fault(message: str = "injected fault") -> Action:
    """Raise :class:`FaultInjected` (reads as an internal crash upstream)."""
    def act(fc: FaultContext) -> None:
        raise FaultInjected(f"{message} [site={fc.site} visit={fc.count}]",
                            site=fc.site)
    return act


def raise_error(make: Callable[[FaultContext], BaseException]) -> Action:
    """Raise an arbitrary exception built from the firing context — for
    injecting *typed* failures (e.g. ``OSError`` at a socket send)."""
    def act(fc: FaultContext) -> None:
        raise make(fc)
    return act


def delay(seconds: float, jitter_s: float = 0.0) -> Action:
    """Sleep on the calling thread (plus deterministic seeded jitter) — the
    slow-replica / slow-network fault."""
    def act(fc: FaultContext) -> None:
        time.sleep(seconds + (fc.rng.uniform(0.0, jitter_s)
                              if jitter_s > 0 else 0.0))
    return act


def sigterm(signum: int = signal.SIGTERM) -> Action:
    """Deliver a signal to this process (synchronously when fired on the
    main thread) — the preemption fault :class:`PreemptionGuard` absorbs."""
    def act(fc: FaultContext) -> None:
        signal.raise_signal(signum)
    return act


def call(fn: Callable[[FaultContext], None]) -> Action:
    """Adapter for ad-hoc actions (the schedule stays declarative)."""
    return fn


# ---------------------------------------------------------------------------
# preemption grace
# ---------------------------------------------------------------------------

class PreemptionGuard:
    """Absorb SIGTERM/SIGINT into a checked flag instead of an immediate
    death: the experiment driver polls :attr:`requested` at pass boundaries,
    force-saves a mid-stage checkpoint, and exits with its distinct code —
    so a preempted week-long run loses at most one pass.

    Context manager; handlers are installed on ``__enter__`` and the
    previous ones restored on ``__exit__``. Signal handlers can only be
    installed from the main thread — off the main thread the guard is
    inert (``requested`` stays False) rather than raising, so driver code
    runs unchanged under test runners that use worker threads. A second
    signal during the grace window restores the previous handler and
    re-raises it: the operator's escalation path stays available.
    """

    def __init__(self, signums: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self._signums = signums
        self._old: Dict[int, Any] = {}
        self._evt = threading.Event()
        self.signum: Optional[int] = None

    @property
    def requested(self) -> bool:
        return self._evt.is_set()

    def _handle(self, signum, frame) -> None:
        if self._evt.is_set():
            # escalation: the first signal is grace, the second is now —
            # hand control back to the previous disposition immediately
            self._restore()
            signal.raise_signal(signum)
            return
        self.signum = signum
        self._evt.set()

    def _restore(self) -> None:
        for s, old in self._old.items():
            with contextlib.suppress(ValueError, OSError, TypeError):
                signal.signal(s, old)
        self._old = {}

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self          # inert off the main thread (see docstring)
        for s in self._signums:
            self._old[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        self._restore()
