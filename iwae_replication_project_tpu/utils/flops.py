"""Analytic matmul-FLOP accounting: the MFU roofline numerator + peak table.

MFU is a first-class bench metric (ISSUE 6): every phase (train / eval /
serving) reports ``achieved FLOP/s / peak chip FLOP/s`` with BOTH sides of
the ratio recorded. The numerator is *analytic matmul FLOPs only* — counted
from the model architecture (2 FLOPs per MAC, backward ~= 2x forward for
dense stacks), never from a profiler — so it is an honest lower bound on
work: elementwise ops, sampling, and reductions ride along for free, and a
fused kernel cannot inflate its own MFU by doing more work. Until this
module the formulas were hard-coded in bench.py for the flagship dims only;
here they derive from any :class:`~..models.iwae.ModelConfig`, so the
width-scaling sweep, the paper config, and future architectures share one
accounting.

The denominator comes from :func:`peak_flops_for_kind`: per-chip dense bf16
peaks for the published TPU generations, matched against
``jax.Device.device_kind``. Unknown chips get ``(None, reason)`` — the bench
reports ``mfu: null`` with the reason stamped and a documented
``--peak-flops`` / ``BENCH_PEAK_FLOPS`` override, never a fabricated
denominator (ADVICE r2).
"""

from __future__ import annotations

from typing import Optional, Tuple

#: per-chip dense bf16 peak FLOP/s by ``device_kind`` substring, matched in
#: order (more specific first: "v5p" must win over "v5"). Sources: Google's
#: published per-chip specs — v2 45T, v3 123T, v4 275T, v5e 197T, v5p 459T,
#: v6e/Trillium 918T.
PEAK_BF16_FLOPS: Tuple[Tuple[str, float], ...] = (
    ("v6e", 918e12), ("v6 lite", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


#: per-chip HBM bandwidth (bytes/s) by ``device_kind`` substring, matched in
#: the same order discipline as the FLOPs table. Sources: Google's published
#: per-chip specs — v2 700GB/s, v3 900GB/s, v4 1228GB/s, v5e 819GB/s,
#: v5p 2765GB/s, v6e/Trillium 1640GB/s. The roofline ridge point
#: (peak_flops / hbm_bytes_per_s) is what the static cost analyzer
#: (analysis/audit/cost.py) compares arithmetic intensity against.
PEAK_HBM_BYTES: Tuple[Tuple[str, float], ...] = (
    ("v6e", 1640e9), ("v6 lite", 1640e9), ("trillium", 1640e9),
    ("v5p", 2765e9), ("v5e", 819e9), ("v5 lite", 819e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)


def peak_flops_for_kind(kind: str) -> Tuple[Optional[float], str]:
    """``(peak_flops | None, source)`` for one ``device_kind`` string."""
    low = kind.lower()
    for sub, val in PEAK_BF16_FLOPS:
        if sub in low:
            return val, f"bf16 peak table: matched {sub!r} in device_kind {kind!r}"
    return None, f"no peak-FLOPs table entry for device_kind {kind!r}"


def peak_hbm_bytes_for_kind(kind: str) -> Tuple[Optional[float], str]:
    """``(hbm_bytes_per_s | None, source)`` for one ``device_kind`` string."""
    low = kind.lower()
    for sub, val in PEAK_HBM_BYTES:
        if sub in low:
            return val, f"HBM BW table: matched {sub!r} in device_kind {kind!r}"
    return None, f"no HBM-bandwidth table entry for device_kind {kind!r}"


def param_count(cfg) -> int:
    """Exact parameter count of the architecture (weights + biases), derived
    from the same block structure as the MAC tables above."""

    def stochastic(in_dim, hidden, latent):
        return (stochastic_block_macs(in_dim, hidden, latent)
                + 2 * hidden + 2 * latent)

    L = cfg.n_stochastic
    n = stochastic(cfg.x_dim, cfg.n_hidden_enc[0], cfg.n_latent_enc[0])
    in_dim = cfg.n_latent_enc[0]
    for i in range(1, L):
        n += stochastic(in_dim, cfg.n_hidden_enc[i], cfg.n_latent_enc[i])
        in_dim = cfg.n_latent_enc[i]
    in_dim = cfg.n_latent_enc[-1]
    for i in range(L - 1):
        n += stochastic(in_dim, cfg.n_hidden_dec[i], cfg.n_latent_dec[i])
        in_dim = cfg.n_latent_dec[i]
    n += (output_block_macs(in_dim, cfg.n_hidden_dec[-1], cfg.x_dim)
          + 2 * cfg.n_hidden_dec[-1] + cfg.x_dim)
    return n


def model_param_bytes(cfg, dtype="float32") -> int:
    """HBM bytes of one parameter pytree — the resident floor every program
    in the suite pays before a single activation (the train step pays 3x:
    params + both Adam moments). `dtype` resolves through the shared
    ``utils.dtypes`` byte-width table (params are f32 in production; a
    bf16 zoo entry halves this). Cross-checked bit-exactly against the
    traced train step's input bytes in tests/test_cost.py, and stamped
    into bench.py's static-cost block."""
    from iwae_replication_project_tpu.utils.dtypes import byte_width
    return param_count(cfg) * byte_width(dtype)


def largest_divisor_leq(n: int, cap: int) -> int:
    """Largest divisor of `n` not exceeding `cap` — chunk/slab/tile sizing
    shared by the eval drivers (evaluation/metrics, parallel/eval) and the
    hot-loop blocked scan (ops/hot_loop). Homed here because ops/ cannot
    import evaluation/ (layering: evaluation -> models -> ops)."""
    return max(d for d in range(1, min(cap, n) + 1) if n % d == 0)


def stochastic_block_macs(in_dim: int, hidden: int, latent: int) -> int:
    """Matmul MACs of one stochastic block per row: 2 hidden + mu/std heads
    (models.mlp.stochastic_block_apply)."""
    return in_dim * hidden + hidden * hidden + 2 * hidden * latent


def output_block_macs(in_dim: int, hidden: int, out_dim: int) -> int:
    """Matmul MACs of the decoder output block per row: 2 hidden + logit
    layer (models.mlp.output_block_apply) — the hot-loop kernel's region."""
    return in_dim * hidden + hidden * hidden + hidden * out_dim


def per_row_macs(cfg) -> Tuple[int, int]:
    """``(macs_per_batch_row, macs_per_(k x batch)_row)`` for one forward.

    The first encoder block runs before the k fan-out (no k axis); every
    other block — encoder layers 2..L, the decoder stochastic chain, and the
    output block — scales with k (models/iwae.py shape conventions).
    """
    L = cfg.n_stochastic
    no_k = stochastic_block_macs(cfg.x_dim, cfg.n_hidden_enc[0],
                                 cfg.n_latent_enc[0])
    per_k = 0
    in_dim = cfg.n_latent_enc[0]
    for i in range(1, L):
        per_k += stochastic_block_macs(in_dim, cfg.n_hidden_enc[i],
                                       cfg.n_latent_enc[i])
        in_dim = cfg.n_latent_enc[i]
    in_dim = cfg.n_latent_enc[-1]
    for i in range(L - 1):
        per_k += stochastic_block_macs(in_dim, cfg.n_hidden_dec[i],
                                       cfg.n_latent_dec[i])
        in_dim = cfg.n_latent_dec[i]
    per_k += output_block_macs(in_dim, cfg.n_hidden_dec[-1], cfg.x_dim)
    return no_k, per_k


def forward_flops(cfg, batch: int, k: int) -> float:
    """Analytic matmul FLOPs of one log-weights forward (MACs * 2)."""
    no_k, per_k = per_row_macs(cfg)
    return 2.0 * (batch * no_k + batch * k * per_k)


def train_step_flops(cfg, batch: int, k: int) -> float:
    """Per optimizer step: forward + ~2x-forward backward for dense stacks."""
    return 3.0 * forward_flops(cfg, batch, k)


def eval_suite_flops_per_image(cfg, k: int, nll_k: int,
                               nll_chunk: int) -> float:
    """Per test image through evaluation.metrics.dataset_scalars: the k-sample
    metric pass, the streaming nll_k-sample NLL (each chunk re-runs the
    k-independent encoder layer), and the 1-sample reconstruction
    (approximated as one k=1 forward). Forward-only — eval takes no grads.
    """
    no_k, per_k = per_row_macs(cfg)
    nll = 2.0 * ((nll_k // nll_chunk) * no_k + nll_k * per_k)
    return forward_flops(cfg, 1, k) + nll + forward_flops(cfg, 1, 1)


def serving_score_flops_per_row(cfg, k: int) -> float:
    """Per served ``score`` request: one k-sample forward (serving/programs)."""
    return forward_flops(cfg, 1, k)
