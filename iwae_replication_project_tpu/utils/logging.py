"""Observability: JSONL metrics + a dependency-free TensorBoard event writer.

Parity target: the reference logs 7 scalars per eval via ``tf.summary.scalar``
(flexible_IWAE.py:529-545) into a timestamped logdir
(experiment_example.py:67-70). TensorFlow is not a dependency of this
framework, so the TensorBoard event-file format (length-prefixed, masked-
crc32c-framed Event protos) is emitted directly — ~60 lines of wire-format
encoding replaces the whole TF summary stack, and any stock TensorBoard can
read the result. A JSONL stream of the same scalars is always written
alongside (grep-able, diff-able, no tooling needed).
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import Dict, Optional

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven — needed for TB record framing
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    tbl = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire encoding for tensorboard Event/Summary
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _encode_event(wall_time: float, step: int, tag: Optional[str] = None,
                  value: Optional[float] = None,
                  file_version: Optional[str] = None) -> bytes:
    ev = bytearray()
    ev += _field(1, 1) + struct.pack("<d", wall_time)          # wall_time: double
    if step:
        ev += _field(2, 0) + _varint(step)                      # step: int64
    if file_version is not None:
        fv = file_version.encode()
        ev += _field(3, 2) + _varint(len(fv)) + fv              # file_version
    if tag is not None:
        tag_b = tag.encode()
        val = (_field(1, 2) + _varint(len(tag_b)) + tag_b       # Value.tag
               + _field(2, 5) + struct.pack("<f", value))       # Value.simple_value
        summ = _field(1, 2) + _varint(len(val)) + val           # Summary.value
        ev += _field(5, 2) + _varint(len(summ)) + summ          # Event.summary
    return bytes(ev)


def _record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", _masked_crc(header))
            + data + struct.pack("<I", _masked_crc(data)))


class TensorBoardWriter:
    """Append-only `events.out.tfevents.*` writer readable by TensorBoard."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.iwae_tpu"
        self._f = open(os.path.join(logdir, fname), "ab")
        self._f.write(_record(_encode_event(time.time(), 0,
                                            file_version="brain.Event:2")))
        self._f.flush()

    def scalar(self, tag: str, value: float, step: int):
        self._f.write(_record(_encode_event(time.time(), step, tag=tag,
                                            value=float(value))))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class MetricsLogger:
    """JSONL + TensorBoard scalar logging with the reference's 7-scalar schema
    (flexible_IWAE.py:539-545) plus anything else handed to :meth:`log`.

    ``flush_every`` is the disk-sync cadence in rows: the default (1) keeps
    the historical flush-per-row behavior the staged driver's 8 rows/run
    never noticed, while high-frequency telemetry export (per-step rows,
    serving snapshots) sets it higher so each ``log`` call does not pay two
    fsync-ish flushes; :meth:`close` (and :meth:`flush`) always drain, so no
    cadence loses rows on an orderly shutdown.
    """

    def __init__(self, logdir: str, run_name: str = "run",
                 tensorboard: bool = True, flush_every: int = 1):
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.dir = os.path.join(logdir, run_name)
        os.makedirs(self.dir, exist_ok=True)
        self._jsonl = open(os.path.join(self.dir, "metrics.jsonl"), "a")
        self._tb = TensorBoardWriter(self.dir) if tensorboard else None
        self.flush_every = flush_every
        self._since_flush = 0

    def log(self, metrics: Dict[str, float], step: int):
        rec = {"step": int(step), "time": time.time()}
        rec.update({k: float(v) for k, v in metrics.items()
                    if isinstance(v, (int, float)) or hasattr(v, "item")})
        self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            for k, v in rec.items():
                if k in ("step", "time"):
                    continue
                self._tb.scalar(k, v, step)
        self._since_flush += 1
        if self._since_flush >= self.flush_every:
            self.flush()

    def log_registry(self, registry, step: int, prefix: str = ""):
        """Stamp a telemetry-registry snapshot (telemetry/registry.py) as one
        flat row: counters/gauges verbatim, histograms as ``name/stat`` —
        the registry's JSONL/TensorBoard exporter."""
        self.log(registry.rows(prefix=prefix), step=step)

    def flush(self):
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()
        self._since_flush = 0

    def close(self):
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
