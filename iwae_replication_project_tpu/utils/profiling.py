"""Tracing / profiling / debugging utilities (SURVEY.md §5).

The reference has no profiling or sanitizer hooks at all (its nearest artifact
is an unused ``plot_model`` import, flexible_IWAE.py:6). Here:

* :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard-profile-plugin trace of everything dispatched inside;
* :class:`StepTimer` — lightweight wall-clock stats for steps/epochs with a
  one-line summary (p50/p95/max), for spotting dispatch stalls without a full
  trace;
* :func:`nan_guard` — context manager flipping ``jax_debug_nans`` so the first
  NaN-producing primitive raises with a stack trace (the single-threaded
  JAX analog of the race-detector/sanitizer slot in the survey table);
* :func:`assert_finite_tree` — chex-based all-finite check over a pytree
  (params/grads), for use at stage boundaries or in tests.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional

import chex
import jax

from iwae_replication_project_tpu.telemetry.registry import Histogram


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device+host profile viewable in TensorBoard's profile tab."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def nan_guard(enable: bool = True):
    """Raise at the first NaN produced by any primitive inside the context.

    Costs extra device syncs — debugging only, not for the hot loop.
    """
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def assert_finite_tree(tree, label: str = "tree") -> None:
    """Raise AssertionError naming `label` if any leaf has a NaN/inf."""
    try:
        chex.assert_tree_all_finite(tree)
    except AssertionError as e:
        raise AssertionError(f"non-finite values in {label}: {e}") from e


class StepTimer:
    """Wall-clock timing for repeated steps; cheap enough to leave on.

    A context-manager view over the telemetry registry's log-spaced
    :class:`~..telemetry.registry.Histogram` (the tree's one
    histogram/percentile implementation): O(1) per step at any count, same
    ~one-bin quantile resolution as the serving latency and span metrics,
    exact max. Same summary schema as before the telemetry layer.
    """

    def __init__(self, sync_fn=None):
        self._sync = sync_fn
        self._hist = Histogram()
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sync is not None:
            self._sync()
        self._hist.record(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    @property
    def count(self) -> int:
        return self._hist.n

    def summary(self) -> Dict[str, float]:
        s = self._hist.summary()
        if not s["count"]:
            return {"count": 0}
        return {
            "count": s["count"],
            "total_s": self._hist.total,
            "mean_s": s["mean"],
            "p50_s": s["p50"],
            "p95_s": s["p95"],
            "max_s": s["max"],
        }

    def reset(self):
        self._hist = Histogram()
