"""Qualitative figures: sample and reconstruction grids as PNGs.

The reference ships result images in its README (README.md:19-22) and the
report's Figures 3-5 (reconstructions / generations, PDF pp.16-17). Here the
same artifacts are written per evaluation stage from the model's
`generate_x` / `reconstruct_probs` (flexible_IWAE.py:107-118, 249-254).

PNG encoding goes through PIL (in the image alongside matplotlib); the grid
assembly is plain numpy so there is no figure-backend dependency.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def image_grid(images: np.ndarray, ncols: int = 10,
               img_hw: Tuple[int, int] = (28, 28), pad: int = 2) -> np.ndarray:
    """Tile ``[N, H*W]`` probabilities in [0,1] into one uint8 grid image."""
    images = np.asarray(images, dtype=np.float32)
    n = images.shape[0]
    h, w = img_hw
    ncols = min(ncols, n)
    nrows = (n + ncols - 1) // ncols
    grid = np.ones((nrows * (h + pad) + pad, ncols * (w + pad) + pad),
                   dtype=np.float32)
    for i in range(n):
        r, c = divmod(i, ncols)
        top = pad + r * (h + pad)
        left = pad + c * (w + pad)
        grid[top:top + h, left:left + w] = images[i].reshape(h, w)
    return (np.clip(grid, 0.0, 1.0) * 255).astype(np.uint8)


def save_png(array_u8: np.ndarray, path: str) -> None:
    from PIL import Image

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    Image.fromarray(array_u8, mode="L").save(path)


def save_stage_figures(params, cfg, key: jax.Array, x_test: np.ndarray,
                       out_dir: str, stage: int, n_samples: int = 100,
                       n_recon: int = 20,
                       img_hw: Optional[Tuple[int, int]] = None) -> list:
    """Write `samples` (ancestral generations from the prior) and `recons`
    (original/reconstruction pairs) grids for one evaluation stage.

    Returns the written paths. Mirrors the reference's Figures 3-5 outputs:
    generations via Decoder.generate_x from h_L ~ N(0, I), reconstructions
    via the 1-sample encode/decode round trip.
    """
    from iwae_replication_project_tpu.models import iwae as model

    if img_hw is None:
        side = int(round(float(np.sqrt(cfg.x_dim))))
        img_hw = (side, cfg.x_dim // side)
    k_gen, k_rec = jax.random.split(key)

    # fetch: replicated outputs under a process-spanning mesh are not fully
    # addressable (plain np.asarray raises); single-process it is equivalent
    from iwae_replication_project_tpu.parallel.multihost import fetch

    h_top = jax.random.normal(k_gen, (1, n_samples, cfg.n_latent_enc[-1]))
    gen = np.asarray(fetch(model.generate_x(params, cfg,
                                            jax.random.fold_in(k_gen, 1),
                                            h_top)[0]))

    x = jnp.asarray(x_test[:n_recon].reshape(n_recon, -1), jnp.float32)
    rec = np.asarray(fetch(model.reconstruct_probs(params, cfg, k_rec, x)[0]))
    # interleave original / reconstruction column pairs
    paired = np.empty((2 * n_recon, cfg.x_dim), dtype=np.float32)
    paired[0::2] = np.asarray(x)
    paired[1::2] = rec

    fig_dir = os.path.join(out_dir, "figures")
    paths = []
    for name, arr, ncols in (("samples", gen, 10), ("recons", paired, 10)):
        p = os.path.join(fig_dir, f"stage_{stage:02d}_{name}.png")
        save_png(image_grid(arr, ncols=ncols, img_hw=img_hw), p)
        paths.append(p)
    return paths


def latent_scatter(params, cfg, key: jax.Array, x: np.ndarray, path: str,
                   labels: Optional[np.ndarray] = None, layer: int = -1,
                   n_samples: int = 64) -> np.ndarray:
    """Posterior-mean scatter of one stochastic layer projected onto its top-2
    principal components — the reference report's qualitative latent-space
    view (PDF pp.16-17; the PCA machinery mirrors flexible_IWAE.py:284-291).

    ``labels`` (optional, e.g. data.digits_labels()) colors the points by
    class. Returns the ``[B, 2]`` projection; writes a PNG to ``path``.
    """
    from iwae_replication_project_tpu.models import iwae as model

    from iwae_replication_project_tpu.parallel.multihost import fetch

    x = jnp.asarray(np.asarray(x, np.float32).reshape(len(x), -1))
    h, _, _ = model.encode(params, cfg, key, x, n_samples)
    means = np.asarray(fetch(jnp.mean(h[layer], axis=0)))  # E_q[h|x], [B, d]
    if means.shape[1] < 2:
        raise ValueError(
            f"latent_scatter needs a >=2-dim stochastic layer to project; "
            f"layer {layer} has dimension {means.shape[1]}")
    centered = means - means.mean(axis=0)
    cov = centered.T @ centered / len(centered)
    _, vecs = np.linalg.eigh(cov)
    proj = centered @ vecs[:, -2:][:, ::-1]  # [B, 2], PC1 first

    # object-oriented figure: no pyplot, so the process-global matplotlib
    # backend (e.g. an interactive one in a notebook) is left untouched
    from matplotlib.figure import Figure

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fig = Figure(figsize=(5, 5), dpi=120)
    ax = fig.add_subplot()
    if labels is None:
        ax.scatter(proj[:, 0], proj[:, 1], s=8, alpha=0.7)
    else:
        sc = ax.scatter(proj[:, 0], proj[:, 1], s=8, alpha=0.8,
                        c=np.asarray(labels), cmap="tab10")
        fig.colorbar(sc, ax=ax, ticks=np.unique(np.asarray(labels)),
                     fraction=0.046)
    ax.set_xlabel("PC 1")
    ax.set_ylabel("PC 2")
    layer_n = layer if layer >= 0 else len(cfg.n_latent_enc) + layer
    ax.set_title(f"posterior means, stochastic layer {layer_n + 1}")
    fig.tight_layout()
    fig.savefig(path)
    return proj
