"""Experiment zoo: one named preset per published result in the reference.

Every row of the reference report's Tables 1-10 (see /root/repo/BASELINE.md)
plus the extended baseline configs (PIWAE/DReG/STL, BASELINE.json configs 4-5)
is reproducible as ``python -m iwae_replication_project_tpu --preset <name>``.
Architectures follow the report (PDF §3.3): the 1-stochastic-layer model uses
two 200-wide deterministic layers and a 50-d latent; the 2-layer model is the
experiment_example.py:48-51 stack. Training protocol for every preset: Adam
(eps=1e-4), batch 100, the 8-stage Burda LR schedule (PDF §3.4).
"""

from __future__ import annotations

from typing import Dict

from iwae_replication_project_tpu.utils.config import ExperimentConfig

_ARCH_1L = dict(n_hidden_encoder=(200,), n_latent_encoder=(50,),
                n_hidden_decoder=(200,), n_latent_decoder=(784,))
_ARCH_2L = dict(n_hidden_encoder=(200, 100), n_latent_encoder=(100, 50),
                n_hidden_decoder=(100, 200), n_latent_decoder=(100, 784))


def _cfg(dataset: str, layers: int, **kw) -> ExperimentConfig:
    arch = _ARCH_1L if layers == 1 else _ARCH_2L
    return ExperimentConfig(dataset=dataset, **arch, **kw)


def configs() -> Dict[str, ExperimentConfig]:
    zoo: Dict[str, ExperimentConfig] = {}

    # Tables 1 (fixed-bin MNIST) and 2 (stochastic-bin MNIST): VAE/IWAE grid
    for table, dataset in (("table1", "binarized_mnist"), ("table2", "mnist")):
        for loss in ("VAE", "IWAE"):
            for L in (1, 2):
                for k in (1, 5, 50):
                    zoo[f"{table}-{loss.lower()}-{L}l-k{k}"] = _cfg(
                        dataset, L, loss_function=loss, k=k)

    # Table 3 (Omniglot): k in {1, 50}
    for loss in ("VAE", "IWAE"):
        for L in (1, 2):
            for k in (1, 50):
                zoo[f"table3-{loss.lower()}-{L}l-k{k}"] = _cfg(
                    "omniglot", L, loss_function=loss, k=k)

    # Table 4 (Fashion-MNIST): L=1, k in {1, 50}
    for loss in ("VAE", "IWAE"):
        for k in (1, 50):
            zoo[f"table4-{loss.lower()}-1l-k{k}"] = _cfg(
                "fashion_mnist", 1, loss_function=loss, k=k)

    # Table 5: L_alpha, alpha in {0, 0.25, 0.5}, L=1, k=50, fixed-bin
    for alpha in (0.0, 0.25, 0.5):
        zoo[f"table5-alpha{alpha}"] = _cfg(
            "binarized_mnist", 1, loss_function="L_alpha", k=50, alpha=alpha)

    # Table 6: L_median, k=50
    zoo["table6-median-k50"] = _cfg("binarized_mnist", 1,
                                    loss_function="L_median", k=50)

    # Table 7: L_power_p, p in {0.5, 2, 3, 5}
    for p in (0.5, 2.0, 3.0, 5.0):
        zoo[f"table7-power{p}"] = _cfg("binarized_mnist", 1,
                                       loss_function="L_power_p", k=50, p=p)

    # Table 8: CIWAE, beta in {0.05, 0.25, 0.5}, stochastic-bin MNIST
    for beta in (0.05, 0.25, 0.5):
        zoo[f"table8-ciwae-beta{beta}"] = _cfg(
            "mnist", 1, loss_function="CIWAE", k=50, beta=beta)

    # Table 9: MIWAE (k1, k2) with k1*k2 = 50, stochastic-bin MNIST.
    # Our spec stores k = k1*k2 and k2 = outer-average count (PDF §2.4).
    for k1, k2 in ((1, 50), (5, 10), (10, 5), (50, 1)):
        zoo[f"table9-miwae-{k1}x{k2}"] = _cfg(
            "mnist", 1, loss_function="MIWAE", k=k1 * k2, k2=k2)

    # Table 10: objective switching at mid-schedule (stage 5 of 8)
    zoo["table10-iwae-to-vae-k50"] = _cfg(
        "binarized_mnist", 1, loss_function="IWAE", k=50,
        switch_stage=5, switch_loss="VAE", switch_k=50)
    zoo["table10-iwae-to-vae-k1"] = _cfg(
        "binarized_mnist", 1, loss_function="IWAE", k=50,
        switch_stage=5, switch_loss="VAE", switch_k=1)
    zoo["table10-vae-k50-to-iwae"] = _cfg(
        "binarized_mnist", 1, loss_function="VAE", k=50,
        switch_stage=5, switch_loss="IWAE", switch_k=50)
    zoo["table10-vae-k1-to-iwae"] = _cfg(
        "binarized_mnist", 1, loss_function="VAE", k=1,
        switch_stage=5, switch_loss="IWAE", switch_k=50)

    # Extended baseline configs (BASELINE.json 4-5): PIWAE / DReG / STL
    for k1, k2 in ((10, 5), (50, 1)):
        zoo[f"piwae-{k1}x{k2}"] = _cfg("mnist", 1, loss_function="PIWAE",
                                       k=k1 * k2, k2=k2)
    for loss in ("DReG", "STL"):
        zoo[f"{loss.lower()}-k50-fashion"] = _cfg(
            "fashion_mnist", 1, loss_function=loss, k=50)

    # the BASELINE.json north-star row
    zoo["northstar-iwae-2l-k50"] = _cfg("binarized_mnist", 2,
                                        loss_function="IWAE", k=50)

    # real-data evidence presets (this repo's offline replication protocol,
    # RESULTS.md): digits = fixed-binarization, digits-gray = PDF Table 2's
    # per-epoch stochastic binarization; the "scaled" variants shrink the
    # Burda schedule to the 1.5k-image dataset (final == best stage,
    # RESULTS.md §2)
    for loss, k in (("VAE", 1), ("IWAE", 50)):
        zoo[f"digits-{loss.lower()}-1l-k{k}"] = _cfg(
            "digits", 1, loss_function=loss, k=k)
        zoo[f"digits-gray-{loss.lower()}-1l-k{k}"] = _cfg(
            "digits_gray", 1, loss_function=loss, k=k)
        zoo[f"digits-scaled-{loss.lower()}-1l-k{k}"] = _cfg(
            "digits", 1, loss_function=loss, k=k, passes_scale=0.2)
    return zoo


def serving_engine(config_or_name, *, checkpoint_dir: str = None,
                   k: int = None, **knobs):
    """A :class:`~.serving.ServingEngine` for a zoo preset (by name or
    :class:`ExperimentConfig`).

    With `checkpoint_dir` (the experiment run directory), the engine serves
    the trained weights and the stored config's architecture; without it,
    weights are freshly initialized from the preset — untrained, which is
    what load tests and the ``iwae-serve`` synthetic profile want. `k`
    defaults to the preset's training k (every score/encode request then
    pays the same importance-sample budget the model was trained under).
    A config carrying ``serving_precision`` serves under that policy
    unless an explicit ``precision=`` kwarg overrides it.
    """
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    if checkpoint_dir is not None:
        # k=None -> the stored config's training k (ServingEngine resolves)
        return ServingEngine(checkpoint_dir, k=k, **knobs)
    import jax

    from iwae_replication_project_tpu.training import create_train_state
    cfg = get(config_or_name) if isinstance(config_or_name, str) \
        else config_or_name
    if knobs.get("precision") is None and cfg.serving_precision is not None:
        knobs["precision"] = cfg.serving_precision
    state = create_train_state(jax.random.PRNGKey(cfg.seed),
                               cfg.model_config())
    return ServingEngine(params=state.params,
                         model_config=cfg.model_config(),
                         k=cfg.k if k is None else k, **knobs)


def serving_engines(names, *, replicas_per_model: int = 1, k: int = None,
                    checkpoint_dirs: Dict[str, str] = None,
                    precisions=None, **knobs):
    """Multi-model replica set from a zoo manifest: one (or
    ``replicas_per_model``) model-labeled :class:`~.serving.ServingEngine`
    per preset name, ready to hand a :class:`~.serving.frontend.ServingTier`
    — the ``iwae-serve --models`` construction path.

    Every engine is labeled ``model=<name>``, so its executables land under
    that tenant in the process executable store (capacity-bounded,
    utils/compile_cache.py), its latency histograms carry the model label,
    and the tier's router classifies ``model``-tagged requests onto it.
    Replicas of the same model share one set of weights (initialized once).
    ``checkpoint_dirs`` optionally maps preset names to experiment run
    directories (trained weights); unmapped names serve fresh inits, which
    is what load tests and benches want. ``precisions`` sets the serving
    precision policy (ISSUE 16): one string applies fleet-wide, a
    ``{name: precision}`` dict configures per model (unmapped names serve
    the historical fp32 path). Unknown precision strings — and dict keys
    naming no requested preset — raise here, at the zoo boundary: a typo'd
    policy must never silently become an fp32 engine.
    """
    from iwae_replication_project_tpu.serving.buckets import (
        validate_precision)

    if isinstance(precisions, str):
        validate_precision(precisions)
    elif precisions:
        unknown = sorted(set(precisions) - set(names))
        if unknown:
            raise ValueError(f"precisions maps models not in this "
                             f"manifest: {unknown}; serving {list(names)}")
        for p in precisions.values():
            validate_precision(p)
    engines = []
    for name in names:
        get(name)                   # unknown preset fails loudly up front
        ckpt = (checkpoint_dirs or {}).get(name)
        prec = precisions if isinstance(precisions, str) \
            else (precisions or {}).get(name)
        first = serving_engine(name, checkpoint_dir=ckpt, k=k,
                               model=name, precision=prec, **knobs)
        engines.append(first)
        from iwae_replication_project_tpu.serving.engine import ServingEngine
        for _ in range(1, max(1, int(replicas_per_model))):
            engines.append(ServingEngine(
                params=first._params, model_config=first.cfg, k=first.k,
                k_max=first.k_max, model=name, precision=prec, **knobs))
    return engines


def get(name: str) -> ExperimentConfig:
    zoo = configs()
    if name not in zoo:
        import difflib
        hint = difflib.get_close_matches(name, zoo, n=3)
        raise KeyError(f"unknown preset {name!r}"
                       + (f"; did you mean {hint}?" if hint else ""))
    return zoo[name]
