"""Adaptive-k scoring + bulk offline lane smoke stage for scripts/check.py.

One short CPU process that proves the accuracy-targeted serving path's
hard invariants on a warm engine behind a REAL socket tier:

1. **ragged (batch, target) stream, zero recompiles** — mixed
   ``score_adaptive`` targets (``target_se`` / ``ess_floor``), caps, and
   plain fixed-k ``score`` traffic interleave over one warm tier with 0
   AOT misses and 0 XLA recompiles: targets are dynamic scalars, never
   program keys;
2. **early-stop == fixed-k prefix, over the wire** — a pinned-seed
   adaptive request's ``[log_px, se, k_used]`` has ``log_px`` bitwise
   equal to a plain ``score`` request at ``k = k_used`` under the same
   seed (the determinism contract: stopping early IS the fixed-k program,
   truncated), and re-requesting on a NEW connection reproduces the
   triple bitwise (routing/connection independence);
3. **typed bad_request at the wire for malformed targets** — wrong type,
   non-positive, unreachable ``ess_floor``, targets on a fixed op, and a
   target-less adaptive call each come back as typed ``bad_request``
   *responses* on a surviving connection;
4. **the bulk lane yields to interactive traffic** — with a dataset-sized
   job running in the background lane, interactive p50 stays within the
   stated bound (``max(1 s, 8 x idle p50)`` on this CPU box), and the
   job's results equal the offline twin bitwise (background pacing never
   touches bits);
5. **checkpoint + bitwise resume** — a checkpointed job interrupted
   mid-run by a full tier shutdown resumes on a FRESH tier from its
   manifest-sealed prefix and finishes bitwise identical to the
   uninterrupted reference.

Tiny architecture by design: the smoke checks contracts, not throughput —
``bench.py --adaptive-k`` owns the numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# sp-merge coverage needs more than one device (conftest.py's convention)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=4"

D = 16
K_CHUNK = 16
K_MAX = 256
JOB_SEED = 7


def _build_tier(model, jax, np, make_mesh, ShardedScoreEngine, ServingTier,
                cfg, params, bulk_headroom):
    mesh = make_mesh()
    eng = ShardedScoreEngine(params=params, model_config=cfg, mesh=mesh,
                             k_chunk=K_CHUNK, k_max=K_MAX, k=16,
                             max_batch=8, timeout_s=120.0)
    tier = ServingTier([eng], port=0, tracing=False,
                       bulk_headroom=bulk_headroom)
    tier.start()
    tier.warmup()
    return tier, eng, mesh


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.parallel import make_mesh
    from iwae_replication_project_tpu.parallel.eval import (
        sharded_score_adaptive_offline)
    from iwae_replication_project_tpu.serving import ShardedScoreEngine
    from iwae_replication_project_tpu.serving.frontend.client import (
        TierClient, TierError)
    from iwae_replication_project_tpu.serving.frontend.server import (
        ServingTier)
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    tier, eng, mesh = _build_tier(model, jax, np, make_mesh,
                                  ShardedScoreEngine, ServingTier,
                                  cfg, params, bulk_headroom=2)
    cli = TierClient("127.0.0.1", tier.port, timeout_s=120.0)

    info = cli.info()
    assert "score_adaptive" in info["ops"], info["ops"]
    assert info["adaptive_ops"] == ["score_adaptive"], info["adaptive_ops"]

    rng = np.random.RandomState(0)
    x = (rng.rand(8, D) > 0.5).astype(np.float32)
    rows = [r.tolist() for r in x]

    # -- 1. ragged (batch, target) stream: zero recompiles ------------------
    s0 = cache_stats()
    ids = []
    for i, (n, kw) in enumerate([
            (3, dict(k=K_MAX, target_se=0.5)),
            (1, dict(k=64, target_se=0.05)),
            (4, dict(k=K_MAX, ess_floor=32.0)),
            (2, dict(k=128, target_se=0.2, ess_floor=8.0)),
            (2, dict(k=16)),                      # plain fixed-k score
            (1, dict(k=K_MAX, target_se=1e-6))]):  # cap-limited row
        op = "score" if "target_se" not in kw and "ess_floor" not in kw \
            else "score_adaptive"
        for r in rows[:n]:
            ids.append(cli.submit(op, r, **kw))
    resp = cli.drain(ids)
    for rid, r in resp.items():
        assert r.get("ok"), f"stream request {rid} failed: {r}"
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"ragged (batch, target) stream missed: {d}"
    assert d["persistent_cache_misses"] == 0, f"XLA recompiled: {d}"

    # -- 2. early-stop == fixed-k prefix + connection independence ----------
    for seed, tse in ((11, 0.4), (12, 0.15)):
        triple = cli.score_adaptive(rows[0], k=K_MAX, seed=seed,
                                    target_se=tse)[0]
        log_px, se, k_used = triple
        assert 0 < k_used <= K_MAX and se <= tse or k_used == K_MAX, triple
        fixed = cli.score(rows[0], k=int(k_used), seed=seed)[0]
        assert fixed == log_px, \
            f"adaptive log_px != fixed-k prefix at k_used={k_used}: " \
            f"{log_px} vs {fixed}"
        cli2 = TierClient("127.0.0.1", tier.port, timeout_s=120.0)
        again = cli2.score_adaptive(rows[0], k=K_MAX, seed=seed,
                                    target_se=tse)[0]
        cli2.close()
        assert again == triple, \
            f"new-connection re-request changed bits: {again} vs {triple}"

    # -- 3. typed bad_request for malformed targets, connection survives ----
    bad = [dict(op="score_adaptive", x=rows[0], k=16, target_se="x"),
           dict(op="score_adaptive", x=rows[0], k=16, target_se=-1.0),
           dict(op="score_adaptive", x=rows[0], k=16, ess_floor=1e9),
           dict(op="score_adaptive", x=rows[0], k=16),
           dict(op="score", x=rows[0], k=16, target_se=0.5)]
    for req in bad:
        try:
            cli.request(req.pop("op"), req.pop("x"), **req)
        except TierError as e:
            assert e.code == "bad_request", (e.code, str(e), req)
        else:
            raise AssertionError(f"malformed request was served: {req}")
    assert np.isfinite(cli.score(rows[0], k=16)[0]), \
        "connection did not survive the bad_request volley"

    # -- 4. bulk lane yields to interactive traffic -------------------------
    def p50(n=20):
        lat = []
        for _ in range(n):
            t0 = time.monotonic()
            cli.score(rows[0], k=16)
            lat.append(time.monotonic() - t0)
        return statistics.median(lat)

    idle_p50 = p50()
    n_job = 48
    jx = (np.random.RandomState(1).rand(n_job, D) > 0.5).astype(np.float32)
    doc = cli.submit_job([r.tolist() for r in jx], job_op="score_adaptive",
                         k=K_MAX, target_se=1e-6, seed=JOB_SEED)
    job_id = doc["job"]
    burst_p50 = p50()
    mid = cli.job_status(job_id)
    bound = max(1.0, 8.0 * idle_p50)
    assert burst_p50 <= bound, \
        f"interactive p50 under bulk {burst_p50:.3f}s exceeds the stated " \
        f"bound {bound:.3f}s (idle p50 {idle_p50:.3f}s)"
    deadline = time.monotonic() + 300
    while True:
        st = cli.job_status(job_id, results=True)
        if st["state"] in ("done", "failed"):
            break
        assert time.monotonic() < deadline, f"job stalled: {st}"
        time.sleep(0.02)
    assert st["state"] == "done", st
    seeds = np.array([(JOB_SEED + i) % 2 ** 31 for i in range(n_job)],
                     np.int32)
    ref = np.asarray(sharded_score_adaptive_offline(
        params, eng.cfg, mesh, eng._base_key, seeds, jx, k_cap=K_MAX,
        target_se=1e-6, k_chunk=K_CHUNK))
    got = np.asarray(st["results"], np.float32)
    assert np.array_equal(got, ref), \
        "bulk job results != offline twin (background pacing touched bits)"
    assert "work_estimates" in cli.stats(), "stats lost work_estimates"

    # -- 5. checkpoint mid-run, resume bitwise on a FRESH tier --------------
    with tempfile.TemporaryDirectory(prefix="iwae-job-ckpt-") as ckpt:
        n_ck = 24
        cx = (np.random.RandomState(2).rand(n_ck, D) > 0.5).astype(
            np.float32)
        crows = [r.tolist() for r in cx]
        doc = cli.submit_job(crows, job_op="score_adaptive", k=K_MAX,
                             target_se=1e-6, seed=JOB_SEED,
                             checkpoint_dir=ckpt, checkpoint_every=4)
        cid = doc["job"]
        deadline = time.monotonic() + 300
        while True:
            st = cli.job_status(cid)
            if st["checkpointed"] >= 4:
                break
            assert st["state"] in ("running", "done"), st
            assert time.monotonic() < deadline, f"no checkpoint: {st}"
            time.sleep(0.002)
        interrupted_at = st["checkpointed"]
        cli.close()
        tier.stop()         # mid-run interruption: the pump dies with it

        tier2, eng2, mesh2 = _build_tier(model, jax, np, make_mesh,
                                         ShardedScoreEngine, ServingTier,
                                         cfg, params, bulk_headroom=2)
        cli = TierClient("127.0.0.1", tier2.port, timeout_s=120.0)
        doc = cli.submit_job(crows, job_op="score_adaptive", k=K_MAX,
                             target_se=1e-6, seed=JOB_SEED,
                             checkpoint_dir=ckpt, checkpoint_every=4,
                             resume=True)
        assert doc["completed"] >= interrupted_at, \
            f"resume lost the checkpointed prefix: {doc}"
        rid = doc["job"]
        deadline = time.monotonic() + 300
        while True:
            st = cli.job_status(rid, results=True)
            if st["state"] in ("done", "failed"):
                break
            assert time.monotonic() < deadline, f"resumed job stalled: {st}"
            time.sleep(0.02)
        assert st["state"] == "done", st
        seeds = np.array([(JOB_SEED + i) % 2 ** 31 for i in range(n_ck)],
                         np.int32)
        ref = np.asarray(sharded_score_adaptive_offline(
            params, eng2.cfg, mesh2, eng2._base_key, seeds, cx,
            k_cap=K_MAX, target_se=1e-6, k_chunk=K_CHUNK))
        got = np.asarray(st["results"], np.float32)
        assert np.array_equal(got, ref), \
            "resumed job != uninterrupted reference (resume broke bits)"
        cli.close()
        tier2.stop()

    print(f"adaptive-k smoke OK: ragged (batch, target) stream 0 recompiles,"
          f" early-stop == fixed-k prefix bitwise, typed bad_request x"
          f"{len(bad)}, bulk p50 {burst_p50 * 1e3:.1f}ms "
          f"(idle {idle_p50 * 1e3:.1f}ms, bound {bound:.2f}s, "
          f"{mid['completed']}/{n_job} rows done mid-burst), "
          f"checkpoint at {interrupted_at} rows resumed bitwise on mesh "
          f"{dict(mesh.shape)}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"adaptive-k smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
