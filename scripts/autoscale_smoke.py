"""Elastic-fleet smoke stage for scripts/check.py: the autoscaler, live.

One short CPU process that runs a real tiny-engine tier under the
SLO-driven autoscaler (serving/fleet/) with a seeded chaos schedule, and
proves the ISSUE's composed claims end to end:

1. **burn breach -> warm scale-up** — a burst against a deliberately
   unbeatable latency objective pushes the fast+slow burn windows past
   the threshold; the controller decides "up" (rule ``burn-breach``) and
   the joined replica is built over the SHARED params and warmed through
   the process executable store + persistent caches: the ``cache_stats``
   delta across the ENTIRE elastic run — both joins included — shows
   **zero fresh compiles** (``aot_misses == 0``,
   ``persistent_cache_misses == 0``);
2. **replica killed mid-scale-event** — the chaos schedule crashes the
   freshly-joined replica on its FIRST serving launch, exactly when the
   post-scale-event burst leans on it (the PR 10 fault shape; the
   pre-join warmup never touches the launch site, so the join itself
   lands); the router reroutes its work with the ORIGINAL admission
   seeds, the controller sees the shrunken live fleet still burning and
   scales up AGAIN (a second warm join), and not one request is lost;
3. **idle -> drain-based scale-down** — once the burn windows rotate
   clean and nothing is outstanding, the controller decides "down" (rule
   ``idle``); the victim leaves through the router's drain contract and
   the shrunk fleet keeps serving;
4. **bitwise parity vs a static fleet** — every response of the elastic
   run equals, bitwise, a fixed single-replica tier's response for the
   same admission order: seeds are minted at admission, so fleet shape
   moves warmth and capacity, never results.

The decision log, placement log, and fault log are committed to
``results/autoscale_smoke.json``. Exit 0 on success, 1 with a message on
the first failed check.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEED = 1234
N_PHASE = 12          # requests per burst phase (4 phases = 48 total)

# short real-time burn windows so idle actually rotates the violations
# out within the smoke's budget; labels stay "5m"/"1h" — the controller
# addresses windows by label, and these ARE its fast/slow pair here
FAST_S, SLOW_S = 2.0, 4.0


def _tiny_fleet():
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                            n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, max_inflight=2, timeout_s=30.0)

    return engine, D


def _burst(cli, rows, lo, hi):
    """Pipeline rows[lo:hi] on one connection (admission order == submit
    order) and return their responses in submit order."""
    ids = [cli.submit("score", [rows[i].tolist()]) for i in range(lo, hi)]
    done = cli.drain(ids)
    return [done[rid] for rid in ids]


def _wait(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"smoke timed out waiting for {msg}")
        time.sleep(0.01)


def main() -> int:
    import numpy as np

    from iwae_replication_project_tpu.serving import faults
    from iwae_replication_project_tpu.serving.fleet import (
        AutoscaleConfig, FleetManager)
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.telemetry.slo import (
        SLOMonitor, SLOObjective)
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, setup_persistent_cache, stats_delta)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving programs instead of recompiling
    setup_persistent_cache(base_dir=REPO)

    engine, D = _tiny_fleet()
    rng = np.random.RandomState(0)
    n = 4 * N_PHASE
    rows = (rng.rand(n, D) > 0.5).astype(np.float32)

    # -- static reference fleet: same rows, same admission order ----------
    static = ServingTier([engine()], monitor_interval_s=0.05)
    static.warmup(ops=("score",))
    static.start()
    try:
        with TierClient("127.0.0.1", static.port) as cli:
            ref_resps = _burst(cli, rows, 0, n)
    finally:
        static.stop(timeout_s=30)
    assert all(r["ok"] for r in ref_resps), "static reference run errored"
    ref = [r["result"][0] for r in ref_resps]

    # -- elastic fleet: 1 replica + autoscaler, chaos installed -----------
    # an unbeatable latency objective: every request violates, so the
    # burst drives burn = 1.0 / (1 - target) = 100 >> threshold
    slo = SLOMonitor(default=SLOObjective(latency_s=1e-6),
                     windows=((FAST_S, "5m"), (SLOW_S, "1h")))
    tier = ServingTier([engine()], slo=slo, monitor_interval_s=0.05)
    tier.warmup(ops=("score",))
    tier.start()

    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3,
                          scale_up_burn=1.0, scale_down_burn=0.25,
                          up_cooldown_s=0.0, down_cooldown_s=0.5,
                          seed=SEED)
    mgr = FleetManager(tier, engine, cfg, warmup_ops=("score",),
                       drain_timeout_s=20.0)

    # chaos: the FIRST replica the autoscaler joins is crashed on its
    # first serving launch — dead exactly when the post-scale-event burst
    # leans on it (times=None keeps it down; probes fail too). after=0,
    # not after=1: a fully cache-warm fleet coalesces a whole pipelined
    # burst into ONE launch per replica, so a second launch on the victim
    # is not guaranteed — the first one is (the pre-join warmup never
    # passes the launch site, so the join itself always lands)
    joined: list = []
    factory = mgr._factory

    def tracked_factory():
        e = factory()
        joined.append(e)
        return e

    mgr._factory = tracked_factory
    schedule = faults.FaultSchedule([faults.FaultRule(
        site=faults.SITE_ENGINE_LAUNCH, after=0, times=None,
        match=lambda ctx: bool(joined) and ctx.get("engine") is joined[0],
        name="crash_replica",
        action=faults.raise_fault("replica crash (chaos)"))], seed=SEED)

    # everything from here runs against the warm store: the delta at the
    # end covers both scale-up joins (warmup + serving dispatches)
    s0 = cache_stats()

    resps = []
    summary = {"seed": SEED, "requests": n, "ok": False}
    try:
        with faults.installed(schedule):
            with TierClient("127.0.0.1", tier.port, timeout_s=60.0) as cli:
                # phase 1: breach burst on the 1-replica fleet
                resps += _burst(cli, rows, 0, N_PHASE)
                d1 = mgr.step()
                assert d1.action == "up" and d1.rule == "burn-breach", \
                    f"breach burst did not scale up: {d1}"
                assert len(tier.router.engines) == 2, "join did not land"

                # phase 2: steer the burst's affinity group at the joined
                # replica (the placement-hint primitive the planner uses),
                # so the chaos rule deterministically kills it mid-burst —
                # one successful launch, then dead on the next
                assert tier.router.prime_affinity(None, "score", None, 1)
                resps += _burst(cli, rows, N_PHASE, 2 * N_PHASE)
                _wait(lambda: schedule.fired("crash_replica") >= 1,
                      msg="chaos crash on the joined replica")

                # the controller sees a 1-live fleet still burning: up
                # again — the SECOND warm join, mid-chaos
                d2 = mgr.step()
                assert d2.action == "up", \
                    f"post-crash breach did not re-scale: {d2}"
                _wait(lambda: sum(1 for s in tier.router.replica_states()
                                  if s["healthy"] and not s["draining"])
                      == 2, msg="second join live")

                # phase 3: burst across the healed fleet
                resps += _burst(cli, rows, 2 * N_PHASE, 3 * N_PHASE)

                # idle: let the burn windows rotate clean, then the
                # controller must shrink through the drain contract
                time.sleep(FAST_S + 0.8)
                d3 = mgr.step()
                assert d3.action == "down" and d3.rule == "idle", \
                    f"idle fleet did not scale down: {d3}"
                live = [s for s in tier.router.replica_states()
                        if s["healthy"] and not s["draining"]]
                assert len(live) == 1, f"drain left extra live: {live}"
                assert len(mgr.retired) == 1 and \
                    mgr.retired[0] not in tier.router.engines

                # phase 4: the shrunk fleet keeps serving
                resps += _burst(cli, rows, 3 * N_PHASE, n)
            stats = tier.stats()
    finally:
        mgr.stop()
        tier.stop(timeout_s=30)

    # -- verdicts ---------------------------------------------------------
    assert len(resps) == n, f"lost responses: {len(resps)}/{n}"
    assert all(r["ok"] for r in resps), \
        [r for r in resps if not r["ok"]][:3]
    got = [r["result"][0] for r in resps]
    assert got == ref, \
        "elastic-fleet results differ bitwise from the static fleet"
    assert tier.router.outstanding == 0, "drain left requests outstanding"

    d = stats_delta(s0)
    assert d["aot_misses"] == 0, \
        f"scale-up joins compiled fresh programs: {d}"
    assert d.get("persistent_cache_misses", 0) == 0, \
        f"scale-up joins missed the persistent cache: {d}"

    r = stats["router"]
    assert r["router/replica_failures"] >= 1, r
    assert r["router/reroutes"] >= 1, r
    actions = [rec["action"] for rec in mgr.decision_log]
    assert actions.count("up") >= 2 and actions.count("down") >= 1, actions
    assert any(p["event"] == "rebalance" for p in mgr.placement_log)

    summary.update({
        "ok": True,
        "bitwise_parity_vs_static_fleet": True,
        "fresh_compiles": {k: d.get(k, 0) for k in (
            "aot_misses", "persistent_cache_misses")},
        "router": {k: r[k] for k in ("router/routed",
                                     "router/replica_failures",
                                     "router/reroutes")},
        "decisions": mgr.decision_log,
        "placements": mgr.placement_log,
        "fault_log": [list(e) for e in schedule.log],
    })
    out = os.path.join(REPO, "results", "autoscale_smoke.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(f"autoscale smoke OK: {n}/{n} bitwise == static fleet; "
          f"2 warm joins (0 fresh compiles), 1 chaos kill absorbed, "
          f"1 drain-based scale-down -> {os.path.relpath(out, REPO)}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"autoscale smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
