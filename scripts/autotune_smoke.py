"""Autotune + lifted-serving-gate smoke stage for scripts/check.py.

Exercises, in one short CPU process (``JAX_PLATFORMS=cpu``):

1. a REAL (tiny-shape) autotune search: measured candidates, a persisted
   winner, and the once-per-fleet warm-cache contract — the second tuning
   run over the same key must be a pure lookup (zero searches, zero probe
   compiles, the injected-measure hook never needed);
2. the winner cache round-trip: a fresh in-memory store re-reads the same
   winner from disk, a corrupt file falls back LOUDLY to the hand-picked
   tiles, and a version bump invalidates silently;
3. fused-vs-reference serving parity through REAL engines: the probe-gated
   auto engine and the forced blocked-scan (fused) engine must return
   request-by-request bitwise-identical results to the historically pinned
   reference engine, with the per-(op, bucket, k) kernel stamps telling
   them apart, and a persisted serving winner steering the gate.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs of the
    # parity engines below should hit the persistent cache
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.ops import autotune as at
    from iwae_replication_project_tpu.ops import hot_loop as hl
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    from iwae_replication_project_tpu.training import create_train_state

    tmp = tempfile.mkdtemp(prefix="iwae_autotune_smoke_")
    cache = os.path.join(tmp, "autotune_cache.json")

    def counter(name):
        return get_registry().counter(f"autotune/{name}").value

    # 1) tiny REAL search + the warm-cache contract
    shape = (4, 8, 10, 16, 20)      # (k, rows, h1_dim, hid, n_pixels)
    rec = at.tune("serving_row", *shape, path=cache, reps=1)
    assert rec["cache"] == "tuned", rec
    assert rec["measured_candidates"] >= 2, rec
    at.reload_store()
    probes0, searches0 = counter("probe_compiles"), counter("searches")
    rec2 = at.tune("serving_row", *shape, path=cache, reps=1)
    assert rec2["cache"] == "hit" and rec2["path"] == rec["path"], rec2
    assert counter("probe_compiles") == probes0, "warm tune probed"
    assert counter("searches") == searches0, "warm tune searched"

    # 2) cache round-trip + corrupt fallback + version invalidation
    at.reload_store()
    assert at.winner_for("serving_row", *shape, None,
                         path=cache) is not None, "winner lost on reload"
    doc = json.load(open(cache))
    doc["version"] = at.AUTOTUNE_VERSION + 1
    json.dump(doc, open(cache, "w"))
    at.reload_store()
    assert at.winner_for("serving_row", *shape, None, path=cache) is None, \
        "version bump did not invalidate"
    with open(cache, "w") as f:
        f.write("{corrupt")
    at.reload_store()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert at.winner_for("serving_row", *shape, None,
                             path=cache) is None
    assert any("corrupt" in str(w.message) for w in caught), \
        "corrupt cache did not warn"

    # 3) fused-vs-reference parity through real engines + gate steering
    cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                      n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                      likelihood="logits")
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    x = (np.random.RandomState(7).rand(9, 12) > 0.5).astype(np.float32)

    def serve_all(eng):
        return np.concatenate([eng.score(x[:n]) for n in (1, 3, 7, 2)])

    mk = lambda force: ServingEngine(params=params, model_config=cfg, k=4,
                                     max_batch=8, timeout_s=None,
                                     kernel_path=force)
    ref = serve_all(mk("reference"))
    auto_eng, scan_eng = mk(None), mk("blocked_scan")
    assert np.array_equal(serve_all(auto_eng), ref), \
        "probe-gated auto engine diverged from the pinned path"
    assert np.array_equal(serve_all(scan_eng), ref), \
        "fused (blocked_scan) engine diverged from the pinned path"
    stamps = scan_eng.metrics.snapshot()["kernel"]
    assert stamps["score/b4/k4"]["path"] == "blocked_scan", stamps
    assert auto_eng.metrics.snapshot()["kernel"]["score/b4/k4"][
        "path"] == "reference"

    # a persisted serving winner steers a fresh engine's gate — still
    # bitwise identical (the blocked-scan forward is bitwise-equal)
    key = at.entry_key("serving_row", 4, 4, 4, 16, 12, None)
    at._save_store(cache, {key: {"path": "blocked_scan", "block_k": 2}})
    os.environ["IWAE_AUTOTUNE_CACHE"] = cache
    at.reload_store()
    try:
        steered = ServingEngine(params=params, model_config=cfg, k=4,
                                max_batch=8, timeout_s=None)
        got = serve_all(steered)
        assert np.array_equal(got, ref), "winner-steered engine diverged"
        assert steered.metrics.snapshot()["kernel"]["score/b4/k4"][
            "path"] == "blocked_scan", "persisted winner did not steer"
    finally:
        os.environ.pop("IWAE_AUTOTUNE_CACHE", None)
        at.reload_store()

    print("autotune smoke: ok")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"autotune smoke FAILED: {e}")
        sys.exit(1)
