"""Chaos smoke stage for scripts/check.py: the failure model, exercised.

One short CPU process that runs the stack under a SEEDED fault schedule
(utils/faults.py + serving/faults.py) and proves the composed resilience
claims end to end — the standing chaos gate ISSUE 10 asked for:

1. **replica crash mid-burst + transient AOT failure + dropped client
   connection** — a retrying client (RetryPolicy: backoff + reconnect)
   drives single-row score requests with EXPLICIT seeds through a
   two-replica tier while one replica is crashed permanently, one AOT
   dispatch raises transiently, and one response is dropped on the wire.
   Every request still completes, and every result is bitwise identical
   to a fault-free direct-engine run of the same (row, seed) pairs —
   zero lost futures, zero silence, 100% eventual completion;
2. **slow replica -> hedge** — one replica's dispatcher stalls; a client
   with ``hedge_after_s`` re-sends on a second connection, first response
   wins (bitwise equal, and far sooner than the stall);
3. **SIGTERM mid-stage + resume** — a sigterm action fires at a chosen
   training pass; the preemption guard absorbs it, a mid-stage checkpoint
   is force-saved, run raises TrainingPreempted; the resumed run's final
   params are bitwise identical to an uninterrupted run;
4. **truncated-checkpoint fallback** — the newest checkpoint of the
   preempted run is truncated (the canonical kill-mid-write corruption);
   resume warns loudly, falls back to the newest intact retained step,
   and STILL reproduces the uninterrupted run's final params bitwise.

The schedule and retry jitter are seeded, and serving parity rests on
explicit per-request seeds (results are pure functions of (weights,
payload, seed, k)) — so a red run is a repro, not a flake. The summary
(per-stage verdicts + the schedules' firing logs) is committed to
``results/chaos_smoke.json``.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import sys
import time
from contextlib import redirect_stderr, redirect_stdout

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEED = 1234


def _tiny_engines():
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                            n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, max_inflight=2, timeout_s=30.0)

    return engine, D


def stage_crash_burst(summary: dict) -> None:
    """Stage 1: crash + AOT fault + dropped connection vs a retry client."""
    import numpy as np

    from iwae_replication_project_tpu.serving import faults
    from iwae_replication_project_tpu.serving.frontend import (
        RetryPolicy, ServingTier, TierClient)

    engine, D = _tiny_engines()
    rng = np.random.RandomState(0)
    n = 24
    x = (rng.rand(n, D) > 0.5).astype(np.float32)

    # fault-free reference: ONE direct engine, explicit seeds 0..n-1
    direct = engine()
    direct.warmup(ops=("score",))
    futs = [direct.submit("score", x[i], seed=i) for i in range(n)]
    direct.flush()
    ref = np.asarray([f.result() for f in futs])
    direct.stop()

    victim, healthy = engine(), engine()
    tier = ServingTier([victim, healthy], affinity_slack=0,
                       monitor_interval_s=0.05)
    tier.warmup(ops=("score",))
    tier.start()
    schedule = faults.FaultSchedule([
        # replica 0 dies at its 3rd dispatch and STAYS down (probes fail)
        faults.crash_replica(victim, after=2, times=None),
        # one transient enqueue-time failure anywhere in the serving fleet
        faults.crash_aot_dispatch(after=10, times=1),
        # one response vanishes on the wire mid-delivery
        faults.drop_tier_connection(after=5, times=1),
    ], seed=SEED)
    policy = RetryPolicy(max_attempts=8, base_delay_s=0.02,
                         deadline_s=30.0, seed=SEED)
    try:
        with faults.installed(schedule):
            with TierClient("127.0.0.1", tier.port, retry=policy) as cli:
                out = np.asarray([cli.score([x[i].tolist()], seed=i)[0]
                                  for i in range(n)], dtype=ref.dtype)
                retry_stats = dict(cli.retry_stats)
            stats = tier.stats()
    finally:
        tier.stop(timeout_s=30)

    assert np.array_equal(out, ref), \
        "results under chaos differ bitwise from the fault-free run"
    assert tier.router.outstanding == 0, "drain left requests outstanding"
    r = stats["router"]
    assert r["router/replica_failures"] >= 1, r
    assert r["router/reroutes"] >= 1, r
    assert retry_stats["reconnects"] >= 1, \
        f"dropped connection never forced a reconnect: {retry_stats}"
    assert schedule.fired("crash_replica") >= 1, schedule.log
    assert schedule.fired("drop_connection") == 1, schedule.log
    summary["crash_burst"] = {
        "requests": n, "bitwise_parity": True,
        "router": {k: r[k] for k in ("router/replica_failures",
                                     "router/reroutes", "router/routed")},
        "client_retry_stats": retry_stats,
        "fault_log": [list(e) for e in schedule.log],
    }
    print(f"chaos stage 1 OK: {n}/{n} requests bitwise == fault-free run "
          f"under crash+aot+drop ({retry_stats})")


def stage_slow_replica_hedge(summary: dict) -> None:
    """Stage 2: a stalled dispatcher; the hedge wins long before it."""
    import numpy as np

    from iwae_replication_project_tpu.serving import faults
    from iwae_replication_project_tpu.serving.frontend import (
        RetryPolicy, ServingTier, TierClient)

    engine, D = _tiny_engines()
    rng = np.random.RandomState(3)
    row = (rng.rand(D) > 0.5).astype(np.float32)

    direct = engine()
    direct.warmup(ops=("score",))
    f = direct.submit("score", row, seed=0)
    direct.flush()
    ref = float(f.result())
    direct.stop()

    slow, fast = engine(), engine()
    stall_s = 3.0
    tier = ServingTier([slow, fast], affinity_slack=0,
                       monitor_interval_s=0.05)
    tier.warmup(ops=("score",))
    tier.start()
    schedule = faults.FaultSchedule(
        [faults.slow_replica(slow, delay_s=stall_s, times=1)], seed=SEED)
    policy = RetryPolicy(max_attempts=4, hedge_after_s=0.15,
                         deadline_s=30.0, seed=SEED)
    try:
        with faults.installed(schedule):
            with TierClient("127.0.0.1", tier.port, retry=policy) as cli:
                t0 = time.monotonic()
                got = cli.score([row.tolist()], seed=0)[0]
                wall = time.monotonic() - t0
                retry_stats = dict(cli.retry_stats)
    finally:
        tier.stop(timeout_s=30)

    assert float(got) == ref, "hedged result differs from the reference"
    assert retry_stats["hedges"] >= 1, retry_stats
    assert retry_stats["hedge_wins"] >= 1, retry_stats
    assert wall < stall_s - 0.5, \
        f"hedge did not beat the {stall_s}s stall (took {wall:.2f}s)"
    summary["slow_replica_hedge"] = {
        "stall_s": stall_s, "wall_s": round(wall, 3),
        "bitwise_parity": True, "client_retry_stats": retry_stats,
        "fault_log": [list(e) for e in schedule.log],
    }
    print(f"chaos stage 2 OK: hedge beat a {stall_s}s stall in {wall:.2f}s, "
          f"bitwise == reference ({retry_stats})")


def _tiny_train_cfg(root: str, tag: str):
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    return ExperimentConfig(
        dataset="binarized_mnist", data_dir=os.path.join(root, "data"),
        n_hidden_encoder=(16,), n_hidden_decoder=(16,),
        n_latent_encoder=(4,), n_latent_decoder=(784,),
        loss_function="IWAE", k=4, batch_size=32, n_stages=3,
        eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
        activity_samples=8, save_figures=False,
        checkpoint_every_passes=2,
        log_dir=os.path.join(root, f"runs_{tag}"),
        checkpoint_dir=os.path.join(root, f"ckpt_{tag}"))


def _params_equal(a, b) -> bool:
    import jax
    import numpy as np

    flat_a = jax.tree.leaves(a)
    flat_b = jax.tree.leaves(b)
    return len(flat_a) == len(flat_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(flat_a, flat_b))


def stage_preempt_resume(summary: dict, scratch: str) -> None:
    """Stages 3+4: SIGTERM mid-stage, resume parity, then resume parity
    AGAIN with the newest checkpoint truncated (integrity fallback)."""
    from iwae_replication_project_tpu.experiment import (
        TrainingPreempted, run_experiment)
    from iwae_replication_project_tpu.utils import faults
    from iwae_replication_project_tpu.utils.checkpoint import (
        truncate_newest_checkpoint)

    kill_stage, kill_pass = 3, 4

    # uninterrupted reference
    cfg_a = _tiny_train_cfg(scratch, "ref")
    state_a, _ = run_experiment(cfg_a, max_batches_per_pass=2,
                                eval_subset=16)

    # SIGTERM at (stage 3, pass 4): the guard absorbs it at the pass
    # boundary, force-saves, and raises TrainingPreempted
    cfg_b = _tiny_train_cfg(scratch, "chaos")
    schedule = faults.FaultSchedule([faults.FaultRule(
        site=faults.SITE_TRAIN_PASS, action=faults.sigterm(), times=1,
        match=lambda ctx: ctx.get("stage") == kill_stage
        and ctx.get("done") == kill_pass,
        name="sigterm_mid_stage")], seed=SEED)
    preempted = False
    with faults.installed(schedule):
        try:
            run_experiment(cfg_b, max_batches_per_pass=2, eval_subset=16)
        except TrainingPreempted as e:
            preempted = True
            assert e.stage == kill_stage and e.passes_done == kill_pass, e
    assert preempted, "sigterm action did not preempt the run"

    # snapshot the preempted checkpoint tree BEFORE resuming, so the
    # truncation variant replays from the identical state
    run_dir = os.path.join(cfg_b.checkpoint_dir, cfg_b.run_name())
    cfg_c = _tiny_train_cfg(scratch, "chaos_trunc")
    shutil.copytree(run_dir,
                    os.path.join(cfg_c.checkpoint_dir, cfg_c.run_name()))

    # stage 3 verdict: plain resume is bitwise identical to uninterrupted
    buf = io.StringIO()
    with redirect_stdout(buf):
        state_b, _ = run_experiment(cfg_b, max_batches_per_pass=2,
                                    eval_subset=16)
    assert f"stage {kill_stage}, pass {kill_pass + 1}" in buf.getvalue(), \
        f"resume did not continue mid-stage: {buf.getvalue()[-500:]}"
    assert _params_equal(state_a.params, state_b.params), \
        "SIGTERM'd-then-resumed params differ from the uninterrupted run"

    # stage 4 verdict: truncate the newest checkpoint; resume must warn,
    # fall back to the newest intact step, and STILL match bitwise
    mutilated = truncate_newest_checkpoint(
        os.path.join(cfg_c.checkpoint_dir, cfg_c.run_name()))
    assert mutilated is not None, "nothing to truncate?"
    buf = io.StringIO()
    err = io.StringIO()
    with redirect_stdout(buf), redirect_stderr(err):
        state_c, _ = run_experiment(cfg_c, max_batches_per_pass=2,
                                    eval_subset=16)
    assert "failed integrity verification" in buf.getvalue(), \
        f"no integrity warning on a truncated checkpoint: " \
        f"{buf.getvalue()[-500:]}"
    assert _params_equal(state_a.params, state_c.params), \
        "truncated-checkpoint fallback broke bitwise resume parity"

    summary["preempt_resume"] = {
        "kill_at": {"stage": kill_stage, "pass": kill_pass},
        "resume_bitwise_parity": True,
        "fault_log": [list(e) for e in schedule.log],
    }
    summary["truncated_checkpoint_fallback"] = {
        "truncated_file": os.path.relpath(mutilated, scratch),
        "integrity_warning_seen": True,
        "resume_bitwise_parity": True,
    }
    print("chaos stage 3 OK: SIGTERM absorbed, mid-stage save, resumed "
          "params bitwise == uninterrupted run")
    print("chaos stage 4 OK: truncated newest checkpoint detected, fell "
          "back to intact step, resumed params bitwise == uninterrupted run")


def main() -> int:
    import tempfile

    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving/training programs instead of recompiling
    setup_persistent_cache(base_dir=REPO)

    summary = {"seed": SEED, "ok": False}
    stage_crash_burst(summary)
    stage_slow_replica_hedge(summary)
    with tempfile.TemporaryDirectory(prefix="iwae_chaos_") as scratch:
        stage_preempt_resume(summary, scratch)
    summary["ok"] = True

    out = os.path.join(REPO, "results", "chaos_smoke.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")
    print(f"chaos smoke OK -> {os.path.relpath(out, REPO)}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"chaos smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
