"""The single CI gate: static lint, then tier-1 tests (with sanitizers).

``python scripts/check.py`` runs, in order:

1. **iwaelint** over the production tree (``[tool.iwaelint]`` paths) — the
   8-rule JAX correctness suite (analysis/), including the ``cache-setup``
   guard on every entry point (the ``iwae-serve`` CLI among them);
2. **telemetry smoke** (scripts/telemetry_smoke.py) — registry export,
   span nesting, jitted ESS identities, and all three exporter surfaces
   (JSONL/TB, Prometheus text, /metrics HTTP) under ``JAX_PLATFORMS=cpu``;
3. **serving smoke** (scripts/serving_smoke.py) — the pipelined dispatch
   path on a warm engine under a ragged burst: zero recompiles after
   warmup, zero lost futures through a mid-burst ``stop()``, in-flight
   window drained;
4. **hot-loop smoke** (scripts/hot_loop_smoke.py) — interpret-mode parity
   of the blocked (k, batch) kernel (fwd + grads), bitwise blocked-scan
   fallback, forced-path dispatch parity with kernel_path telemetry, and
   the one-probe-per-shape cache;
5. **tier-1 pytest** (the fast profile, ``-m 'not slow'``) with ``--sanitize``
   armed, so the marked subset additionally runs under
   ``jax.transfer_guard("disallow")`` + ``jax.debug_nans``. The serving
   subsystem's fast tests (tests/test_serving.py: batcher policy,
   padded-bucket parity, shed/timeout robustness, warm-path zero-compile)
   ride this stage; only the end-to-end synthetic load sweep is ``slow``
   (run it via ``pytest -m slow tests/test_serving.py`` or
   ``bench.py --serving``).

Exit status is nonzero if EITHER stage fails; the lint stage does not
short-circuit the test stage (CI reports both). ``--lint-only`` /
``--tests-only`` select a single stage; extra args after ``--`` are passed
through to pytest.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_lint() -> int:
    print("== iwaelint: static analysis ".ljust(72, "="))
    return subprocess.call(
        [sys.executable, "-m", "iwae_replication_project_tpu.analysis"],
        cwd=REPO)


def run_telemetry_smoke() -> int:
    print("== telemetry smoke: registry export + span nesting ".ljust(72, "="))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.call(
        [sys.executable, os.path.join("scripts", "telemetry_smoke.py")],
        cwd=REPO, env=env)


def run_serving_smoke() -> int:
    print("== serving smoke: pipelined dispatch, warm engine ".ljust(72, "="))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.call(
        [sys.executable, os.path.join("scripts", "serving_smoke.py")],
        cwd=REPO, env=env)


def run_hot_loop_smoke() -> int:
    print("== hot-loop smoke: blocked kernel parity + probe cache ".ljust(72, "="))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.call(
        [sys.executable, os.path.join("scripts", "hot_loop_smoke.py")],
        cwd=REPO, env=env)


def run_tests(extra) -> int:
    print("== pytest: tier-1 (fast profile) + sanitizers ".ljust(72, "="))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
           "--sanitize", "-p", "no:cacheprovider",
           "--continue-on-collection-errors"] + list(extra)
    return subprocess.call(cmd, cwd=REPO, env=env)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    passthrough = []
    if "--" in argv:
        split = argv.index("--")
        argv, passthrough = argv[:split], argv[split + 1:]
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--lint-only", action="store_true")
    ap.add_argument("--tests-only", action="store_true")
    args = ap.parse_args(argv)

    single_stage = args.lint_only or args.tests_only
    rc_lint = 0 if args.tests_only else run_lint()
    # the smoke stages ride the full gate only: --lint-only / --tests-only
    # keep their single-stage contract
    rc_smoke = 0 if single_stage else run_telemetry_smoke()
    rc_serve = 0 if single_stage else run_serving_smoke()
    rc_hot = 0 if single_stage else run_hot_loop_smoke()
    rc_tests = 0 if args.lint_only else run_tests(passthrough)

    print("== check summary ".ljust(72, "="))
    if not args.tests_only:
        print(f"lint : {'ok' if rc_lint == 0 else f'FAILED (rc={rc_lint})'}")
    if not single_stage:
        print(f"smoke: {'ok' if rc_smoke == 0 else f'FAILED (rc={rc_smoke})'}")
        print(f"serve: {'ok' if rc_serve == 0 else f'FAILED (rc={rc_serve})'}")
        print(f"hot  : {'ok' if rc_hot == 0 else f'FAILED (rc={rc_hot})'}")
    if not args.lint_only:
        print(f"tests: {'ok' if rc_tests == 0 else f'FAILED (rc={rc_tests})'}")
    return 1 if (rc_lint or rc_smoke or rc_serve or rc_hot or rc_tests) else 0


if __name__ == "__main__":
    sys.exit(main())
