"""The single CI gate: lint -> audit -> cost -> smokes -> tier-1, with a
machine-readable summary.

``python scripts/check.py`` runs, in order:

1. **iwaelint** over the production tree (``[tool.iwaelint]`` paths) — the
   AST rule suite (analysis/rules/), including the concurrency checker
   (lock-order / unlocked-shared-state / blocking-call-under-lock over
   the serving engine and the metric registry) and the
   ``useless-suppression`` meta-rule;
2. **iwae-race** (analysis/race/) — the static future/span/pin leak pass
   over the serving control plane (every acquisition provably completed/
   finished/released on all exception paths) plus the lockset +
   happens-before race detector's seeded self-test battery;
3. **iwae-audit** (analysis/audit/) — the jaxpr-level program auditor:
   donation safety, padding taint, in-graph host transfers, and recompile
   cardinality over the repo's real traced programs (train step, k=5000
   eval scorer, the three serving programs, all hot-loop paths);
4. **iwae-cost** (analysis/audit/cost.py) — the jaxpr-level cost analyzer
   over the same traced suite: live-range peak HBM bytes, FLOP/byte
   roofline accounting, and per-mesh-axis collective profiles, writing
   the committed ``results/cost_report.json`` (memory-blowup and
   accidental-allgather findings fail the gate like lint findings);
5. **telemetry smoke** (scripts/telemetry_smoke.py);
6. **serving smoke** (scripts/serving_smoke.py);
7. **serving tier smoke** (scripts/serving_tier_smoke.py) — the network
   tier over a real socket with a replica killed mid-burst: zero lost
   responses, zero recompiles, bitwise parity with a direct engine;
8. **large-k smoke** (scripts/large_k_smoke.py) — a k=5000 score request
   through the warm mesh-backed engine: bitwise parity with the offline
   ``parallel/eval`` scorer and zero recompiles over a ragged (batch, k)
   stream;
9. **hot-loop smoke** (scripts/hot_loop_smoke.py);
10. **autotune smoke** (scripts/autotune_smoke.py) — a real tiny tile/remat
   search with the warm-cache (zero probe compiles) contract, winner-cache
   round-trip/corruption fallback, and fused-vs-reference serving parity
   through the lifted engine gate;
11. **chaos smoke** (scripts/chaos_smoke.py) — the failure model under a
   seeded fault schedule: replica crash + AOT fault + dropped connection
   vs a retrying client (bitwise parity, zero lost futures), a slow
   replica beaten by a client hedge, SIGTERM-mid-stage + resume and
   truncated-checkpoint fallback both bitwise-identical to an
   uninterrupted run; summary committed to ``results/chaos_smoke.json``;
12. **autoscale smoke** (scripts/autoscale_smoke.py) — the elastic fleet
   (serving/fleet/) live: a burn-breach burst scales up with a WARM join
   (zero fresh compiles across both joins), the chaos schedule kills the
   joined replica mid-scale-event (work rerouted, scaled up again), idle
   scales down through the drain contract, and every response is bitwise
   identical to a static fleet with the same admission order; decision +
   placement + fault logs committed to ``results/autoscale_smoke.json``;
13. **multi-model smoke** (scripts/multi_model_smoke.py) — a two-model zoo
   behind one tier over a real socket with the executable-store budget
   squeezed to one model's worth: forced eviction churn mid-burst, every
   response bitwise-correct vs dedicated single-model engines, zero
   fresh compiles once warm (evictions demote to the persistent cache
   and readmit by deserialization);
14. **precision parity smoke** (scripts/precision_parity_smoke.py) — the
   low-precision serving contract: bf16/int8 legs pass the statistical
   acceptance gate (telemetry/parity.py) while a corrupted leg is
   rejected, explicit-fp32 policy stays bitwise, one tier serves fp32 +
   bf16 tenants of the same model with zero fresh compiles once warm,
   and int8 admission is honest (forced path stamps ``int8``; auto with
   no measured win serves the exact fp32 program);
15. **trace smoke** (scripts/trace_smoke.py) — end-to-end request tracing
   over a real socket: a ragged burst with a replica killed mid-burst
   plus a hedged request, every request yielding ONE coherent trace tree
   (client -> tier -> router attempts -> engine stages) in the
   tail-sampled flight recorder, results bitwise identical to a
   tracing-off tier, the ``traces`` wire op valid in raw and Chrome
   formats, and SLO burn-rate gauges live on the Prometheus page;
16. **race smoke** (scripts/race_smoke.py) — the race detector's
   instrumented-sync layer over the REAL tier/router/engine stack under
   >= 50 seeded perturbation schedules with a replica killed mid-burst:
   zero races, zero runtime leaks (open spans, store pins, undone
   futures), and results bitwise identical to an uninstrumented run;
17. **prof smoke** (scripts/prof_smoke.py) — the continuous profiling
   plane on a real warm engine: profiling on/off bitwise identical, a
   clean run forms the EWMA baseline with the measured-MFU gauge live
   and zero drift, a 2x-slowdown fake clock trips a typed ``prof/drift``
   finding naming the program, and ``/metrics`` + ``/prof`` +
   ``/healthz`` serve it over HTTP;
18. **adaptive-k smoke** (scripts/adaptive_k_smoke.py) — accuracy-
   targeted scoring + the bulk offline lane over a real socket tier: a
   ragged (batch, target) stream with zero recompiles, early-stopped
   rows bitwise equal to the fixed-k prefix, typed ``bad_request`` for
   malformed targets on a surviving connection, a background job
   yielding to an interactive burst within the stated p50 bound, and a
   checkpointed job interrupted mid-run resuming bitwise on a fresh
   tier;
19. **perf gate** (``iwae-prof --diff``, analysis/regress.py) — the
   statistical perf-regression gate: every committed
   ``results/*_bench.json`` diffed against the committed
   ``results/perf_baseline.json`` (paired medians + rank test + noise
   floor from recorded spreads); a regressed artifact without a baseline
   refresh fails the gate;
20. **tier-1 pytest** (the fast profile, ``-m 'not slow'``) with
   ``--sanitize`` armed.

Every full-gate run writes ``results/check_summary.json`` (per-stage status,
exit code, wall time, and — for the analyzers — finding counts) so CI and
the bench rounds can diff gate results across PRs instead of scraping logs.
Single-stage runs (``--lint-only`` / ``--tests-only``) skip the default
write — a partial record must never clobber, or pose as, the full-gate one
— but honor an explicit ``--summary`` path.

Analyzer exit codes are *classified*, not just tested for nonzero: the lint
and audit CLIs exit **1** for findings and **2** for internal errors, and
the summary records ``findings`` vs ``internal-error`` accordingly — an
analyzer crash must never masquerade as (or hide behind) a findings list.
Either fails the gate. The stages do not short-circuit each other; exit
status is nonzero if ANY stage fails. ``--lint-only`` / ``--tests-only``
select a single stage; extra args after ``--`` pass through to pytest.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env() -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def classify_analyzer_rc(rc: int) -> str:
    """Map an analyzer CLI's exit code onto a summary status. 0 = clean,
    1 = findings; ANYTHING else is the analyzer itself failing (exit 2 is
    the CLIs' declared internal-error code, and a signal/exception exit is
    equally not a findings list) — treating those as findings would report
    a crashed analyzer as a lint problem and hide the crash."""
    if rc == 0:
        return "ok"
    if rc == 1:
        return "findings"
    return "internal-error"


def run_analyzer(label: str, module: str, extra_args=()) -> dict:
    """Run a findings-producing CLI with ``--format json``, classify its
    exit code, and re-print its findings human-readably.

    The analyzers inherit the HOST environment (no CPU pin): the audit is
    env-sensitive by design — on a TPU host the train step traces its
    donating variant and donation-safety audits the real program; pinning
    CPU here would make the gate audit a program production never runs.
    The smoke/test stages keep the CPU pin (their fixtures force it anyway).
    """
    print(f"== {label} ".ljust(72, "="))
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", module, "--format", "json", *extra_args],
        cwd=REPO, capture_output=True, text=True)
    wall = time.perf_counter() - t0
    status = classify_analyzer_rc(proc.returncode)
    counts, total = {}, None
    if status == "internal-error":
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        print(f"{label}: INTERNAL ERROR (rc={proc.returncode}) — the "
              f"analyzer crashed; this is NOT a findings failure")
    else:
        try:
            payload = json.loads(proc.stdout)
            counts = payload.get("counts", {})
            total = payload.get("total", 0)
            for f in payload.get("findings", []):
                loc = f.get("path") or f.get("program", "?")
                line = f.get("line")
                at = f.get("location") or (f"{line}:{f.get('col', 0)}"
                                           if line is not None else "")
                print(f"{loc}:{at}: [{f['rule']}] {f['message']}")
            print(f"{label}: {'clean' if total == 0 else f'{total} finding(s)'}")
        except (json.JSONDecodeError, KeyError) as e:
            status = "internal-error"
            sys.stdout.write(proc.stdout)
            sys.stderr.write(proc.stderr)
            print(f"{label}: unparseable analyzer output ({e})")
    return {"name": label, "status": status, "rc": proc.returncode,
            "wall_seconds": round(wall, 3), "findings": total,
            "counts": counts}


def run_step(label: str, cmd: list) -> dict:
    print(f"== {label} ".ljust(72, "="))
    t0 = time.perf_counter()
    rc = subprocess.call(cmd, cwd=REPO, env=_cpu_env())
    return {"name": label, "status": "ok" if rc == 0 else "failed",
            "rc": rc, "wall_seconds": round(time.perf_counter() - t0, 3)}


def run_lint() -> dict:
    return run_analyzer("lint", "iwae_replication_project_tpu.analysis")


def run_audit() -> dict:
    return run_analyzer("audit", "iwae_replication_project_tpu.analysis.audit")


def run_race() -> dict:
    """The iwae-race stage: the static future/span/pin leak pass over the
    serving control plane, plus the lockset+happens-before detector's
    self-test battery (exit 2 — internal-error — when the battery fails:
    a broken detector must not pose as a clean or findings run)."""
    return run_analyzer(
        "race", "iwae_replication_project_tpu.analysis.race",
        extra_args=("--self-test",))


def run_cost() -> dict:
    """The iwae-cost stage: same exit-code classification as lint/audit
    (0 clean / 1 findings / anything else = analyzer crash), plus the
    committed per-program cost report — peak HBM bytes, FLOPs, arithmetic
    intensity, and per-mesh-axis collective counts — so cost drift diffs
    across PRs exactly like finding counts do."""
    return run_analyzer(
        "cost", "iwae_replication_project_tpu.analysis.audit.cost",
        extra_args=("--report", os.path.join("results", "cost_report.json")))


def run_telemetry_smoke() -> dict:
    return run_step("telemetry smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "telemetry_smoke.py")])


def run_serving_smoke() -> dict:
    return run_step("serving smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "serving_smoke.py")])


def run_serving_tier_smoke() -> dict:
    return run_step("serving tier smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "serving_tier_smoke.py")])


def run_large_k_smoke() -> dict:
    return run_step("large-k smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "large_k_smoke.py")])


def run_hot_loop_smoke() -> dict:
    return run_step("hot-loop smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "hot_loop_smoke.py")])


def run_autotune_smoke() -> dict:
    return run_step("autotune smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "autotune_smoke.py")])


def run_chaos_smoke() -> dict:
    return run_step("chaos smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "chaos_smoke.py")])


def run_autoscale_smoke() -> dict:
    return run_step("autoscale smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "autoscale_smoke.py")])


def run_multi_model_smoke() -> dict:
    return run_step("multi-model smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "multi_model_smoke.py")])


def run_precision_parity_smoke() -> dict:
    return run_step("precision parity smoke",
                    [sys.executable, os.path.join(
                        "scripts", "precision_parity_smoke.py")])


def run_trace_smoke() -> dict:
    return run_step("trace smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "trace_smoke.py")])


def run_race_smoke() -> dict:
    return run_step("race smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "race_smoke.py")])


def run_prof_smoke() -> dict:
    return run_step("prof smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "prof_smoke.py")])


def run_adaptive_k_smoke() -> dict:
    return run_step("adaptive-k smoke",
                    [sys.executable, os.path.join("scripts",
                                                  "adaptive_k_smoke.py")])


def run_perf_gate() -> dict:
    """The statistical perf-regression gate (analysis/regress.py): diff
    every committed ``results/*_bench.json`` against the committed
    baseline bundle. Exit 1 (a bench artifact regressed without a
    baseline refresh via ``iwae-prof --collect``) fails the gate."""
    import glob
    artifacts = sorted(glob.glob(os.path.join(REPO, "results",
                                              "*_bench.json")))
    return run_step("perf gate", [
        sys.executable, "-m", "iwae_replication_project_tpu.analysis.regress",
        "--diff", os.path.join(REPO, "results", "perf_baseline.json"),
    ] + artifacts)


def run_tests(extra) -> dict:
    return run_step("tier-1 tests", [
        sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
        "--sanitize", "-p", "no:cacheprovider",
        "--continue-on-collection-errors"] + list(extra))


def write_summary(path: str, summary: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(summary, f, indent=2)
        f.write("\n")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    passthrough = []
    if "--" in argv:
        split = argv.index("--")
        argv, passthrough = argv[:split], argv[split + 1:]
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--lint-only", action="store_true",
                    help="static analyzers only (lint + race + audit + cost)")
    ap.add_argument("--tests-only", action="store_true")
    ap.add_argument("--summary", default=None,
                    help="where to write the machine-readable stage summary "
                         "(repo-relative; default results/check_summary.json"
                         " — single-stage runs skip the default write so a "
                         "partial record never clobbers the full-gate one)")
    args = ap.parse_args(argv)

    single_stage = args.lint_only or args.tests_only
    stages = []
    if not args.tests_only:
        stages.append(run_lint())
        stages.append(run_race())
        stages.append(run_audit())
        stages.append(run_cost())
    if not single_stage:
        stages.append(run_telemetry_smoke())
        stages.append(run_serving_smoke())
        stages.append(run_serving_tier_smoke())
        stages.append(run_large_k_smoke())
        stages.append(run_hot_loop_smoke())
        stages.append(run_autotune_smoke())
        stages.append(run_chaos_smoke())
        stages.append(run_autoscale_smoke())
        stages.append(run_multi_model_smoke())
        stages.append(run_precision_parity_smoke())
        stages.append(run_trace_smoke())
        stages.append(run_race_smoke())
        stages.append(run_prof_smoke())
        stages.append(run_adaptive_k_smoke())
        stages.append(run_perf_gate())
    if not args.lint_only:
        stages.append(run_tests(passthrough))

    # gate on STATUS, not raw rc: an analyzer that exited 0 but produced
    # unparseable output is recorded internal-error and must fail the gate
    # (rc alone would wave it through)
    summary = {"ok": all(s["status"] == "ok" for s in stages),
               "stages": stages}
    summary_path = args.summary
    if summary_path is None and single_stage:
        # the committed default summary records the FULL gate; a partial
        # --lint-only/--tests-only record posing as it would claim stages
        # that never ran
        print("(single-stage run: default summary not written; pass "
              "--summary <path> to record it)")
    else:
        summary_path = summary_path or os.path.join("results",
                                                    "check_summary.json")
        write_summary(os.path.join(REPO, summary_path), summary)
        print(f"summary -> {summary_path}")
    print("== check summary ".ljust(72, "="))
    for s in stages:
        note = "ok" if s["status"] == "ok" else \
            f"{s['status'].upper()} (rc={s['rc']})"
        extra = f", {s['findings']} finding(s)" \
            if s.get("findings") else ""
        print(f"{s['name']:<16}: {note}  [{s['wall_seconds']:.1f}s{extra}]")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
