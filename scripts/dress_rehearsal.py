"""MNIST-scale dress rehearsal (VERDICT r4 #7): the production run at
production scale, wall-clock measured.

Every staged run through round 4 used <= 1.5k images or CI-sized fixtures;
this script measures the one thing those cannot: the full
``northstar-iwae-2l-k50`` preset — 8 Burda stages, 3280 passes over a
50,000 x 784 train set, full 10k-image eval suite (k=5000 streaming NLL,
activity, pruned NLL) per stage — end to end on one chip, including the
real-file-sized data loading.

The data is synthetic (this image has no network egress and no real MNIST
files — RESULTS.md §1), but written AT THE REAL SIZES in the reference's
on-disk formats so the whole pipeline is exercised exactly as a real
replication would: `binarized_mnist_{train,test}.amat` (Larochelle text
format, ~78 MB / ~16 MB) plus raw `train-images-idx3-ubyte.gz` so the
decoder bias follows the reference's raw-means policy
(flexible_IWAE.py:150-155). NLLs are NOT comparable to the 84.77 north star;
the wall-clock and per-stage timing table are the deliverables.

Run:  python scripts/dress_rehearsal.py [--checkpoint-every-passes N]
Output: per-stage table + one JSON summary line (written to
results/dress_rehearsal.json ONLY when this process measured all stages
fresh — a resumed/partial run prints its table but leaves the committed
measurement alone); fixture files land in data/rehearsal/ (gitignored,
~95 MB, reused across runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA_DIR = os.path.join(REPO, "data", "rehearsal")
OUT_JSON = os.path.join(REPO, "results", "dress_rehearsal.json")

N_TRAIN, N_TEST = 50_000, 10_000


def make_fixture_files(data_dir: str = DATA_DIR) -> float:
    """Write the real-size reference-format files (idempotent); returns the
    generation seconds (0.0 when already present)."""
    from iwae_replication_project_tpu.data.loaders import _synthetic
    from tests.fixture_io import write_idx_gz

    train_p = os.path.join(data_dir, "binarized_mnist_train.amat")
    test_p = os.path.join(data_dir, "binarized_mnist_test.amat")
    raw_tr_p = os.path.join(data_dir, "train-images-idx3-ubyte.gz")
    raw_te_p = os.path.join(data_dir, "t10k-images-idx3-ubyte.gz")
    paths = (train_p, test_p, raw_tr_p, raw_te_p)
    if all(os.path.exists(p) for p in paths):
        return 0.0
    t0 = time.perf_counter()
    os.makedirs(data_dir, exist_ok=True)
    x_train, x_test = _synthetic("binarized_mnist", N_TRAIN, N_TEST, seed=0)
    # Larochelle .amat: one "%d %d ... %d" line per image
    np.savetxt(train_p, x_train, fmt="%d")
    np.savetxt(test_p, x_test, fmt="%d")
    # raw grayscale (the probabilities scaled to [0,255]) for the raw-means
    # bias policy — the loader requires the train/t10k idx PAIR
    gray_tr, gray_te = _synthetic("binarized_mnist", N_TRAIN, N_TEST, seed=0,
                                  binary=False)
    write_idx_gz(raw_tr_p, (gray_tr * 255).astype(np.uint8).reshape(-1, 28, 28))
    write_idx_gz(raw_te_p, (gray_te * 255).astype(np.uint8).reshape(-1, 28, 28))
    return time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint-every-passes", type=int, default=200)
    ap.add_argument("--data-dir", default=DATA_DIR)
    ap.add_argument("--fresh", action="store_true",
                    help="ignore existing checkpoints (default resumes)")
    args = ap.parse_args(argv)

    gen_s = make_fixture_files(args.data_dir)
    print(f"fixture files: {args.data_dir} (generation {gen_s:.1f}s)")

    from iwae_replication_project_tpu import zoo
    from iwae_replication_project_tpu.experiment import run_experiment

    cfg = zoo.get("northstar-iwae-2l-k50")
    cfg.data_dir = args.data_dir
    cfg.allow_synthetic = False  # the files MUST be found — that is the test
    cfg.log_dir = os.path.join(REPO, "runs", "dress_rehearsal")
    cfg.checkpoint_dir = os.path.join(REPO, "checkpoints", "dress_rehearsal")
    cfg.checkpoint_every_passes = args.checkpoint_every_passes
    cfg.save_figures = False
    cfg.resume = not args.fresh

    # a pre-existing checkpoint means this process will resume (and its first
    # stage's timings would cover only the remaining passes): still run, but
    # mark the measurement partial and keep the committed JSON intact
    from iwae_replication_project_tpu.utils.checkpoint import latest_step
    resumed = cfg.resume and latest_step(
        os.path.join(cfg.checkpoint_dir, cfg.run_name())) is not None

    t0 = time.perf_counter()
    state, history = run_experiment(cfg)
    total_s = time.perf_counter() - t0

    rows = []
    print(f"\n{'stage':>5} {'passes':>6} {'train s':>9} {'eval s':>8} "
          f"{'steps/s':>9} {'NLL':>9}")
    from iwae_replication_project_tpu.training import burda_stages
    lengths = {s: n for s, _, n in burda_stages(cfg.n_stages, cfg.passes_scale)}
    for res, _ in history:
        st = int(res["stage"])
        passes = lengths[st]
        steps = passes * (N_TRAIN // cfg.batch_size)
        tr = res.get("stage_train_seconds", float("nan"))
        ev = res.get("stage_eval_seconds", float("nan"))
        rows.append({"stage": st, "passes": passes,
                     "train_seconds": tr, "eval_seconds": ev,
                     "steps_per_sec": round(steps / tr, 1) if tr else None,
                     "NLL": round(res["NLL"], 3)})
        print(f"{st:>5} {passes:>6} {tr:>9.1f} {ev:>8.1f} "
              f"{steps / tr:>9.1f} {res['NLL']:>9.3f}")

    summary = {
        "metric": "northstar-iwae-2l-k50 dress rehearsal "
                  "(synthetic data at real MNIST file sizes)",
        "n_train": N_TRAIN, "n_test": N_TEST,
        "total_seconds": round(total_s, 1),
        "fixture_generation_seconds": round(gen_s, 1),
        "checkpoint_every_passes": args.checkpoint_every_passes,
        "stages": rows,
    }
    print(json.dumps(summary))
    complete = not resumed and len(rows) == cfg.n_stages
    if complete:
        try:
            with open(OUT_JSON, "w") as f:
                json.dump(summary, f, indent=1)
            print(f"wrote {OUT_JSON}")
        except OSError:
            pass
    else:
        print(f"partial/resumed run ({len(rows)}/{cfg.n_stages} stages "
              f"measured{', resumed' if resumed else ''}): NOT overwriting "
              f"{OUT_JSON}; rerun with --fresh for a full measurement")


if __name__ == "__main__":
    main()
