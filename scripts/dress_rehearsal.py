"""MNIST-scale dress rehearsal (VERDICT r4 #7): the production run at
production scale, wall-clock measured.

Every staged run through round 4 used <= 1.5k images or CI-sized fixtures;
this script measures the one thing those cannot: the full
``northstar-iwae-2l-k50`` preset — 8 Burda stages, 3280 passes over a
50,000 x 784 train set, full 10k-image eval suite (k=5000 streaming NLL,
activity, pruned NLL) per stage — end to end on one chip, including the
real-file-sized data loading.

The data is synthetic (this image has no network egress and no real MNIST
files — RESULTS.md §1), but written AT THE REAL SIZES in the reference's
on-disk formats so the whole pipeline is exercised exactly as a real
replication would: `binarized_mnist_{train,test}.amat` (Larochelle text
format, ~78 MB / ~16 MB) plus raw `train-images-idx3-ubyte.gz` so the
decoder bias follows the reference's raw-means policy
(flexible_IWAE.py:150-155). NLLs are NOT comparable to the 84.77 north star;
the wall-clock and per-stage timing table are the deliverables.

Round 5 extension: ``--dataset {binarized_mnist,omniglot,fashion_mnist}``
rehearses every reference data pipeline at its real scale — Omniglot via a
Burda-split-sized ``chardata.mat`` (24,345/8,070, per-epoch stochastic
binarization on device) and Fashion-MNIST via the 60k/10k idx pair — same
2L IWAE k=50 flagship, same full protocol.

Run:  python scripts/dress_rehearsal.py [--dataset D] [--checkpoint-every-passes N]
Output: per-stage table + one JSON summary line (written to
results/dress_rehearsal[_<dataset>].json ONLY when this process measured
all stages fresh — a resumed/partial run prints its table but leaves the
committed measurement alone); fixture files land in data/rehearsal/
(gitignored, reused across runs).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA_DIR = os.path.join(REPO, "data", "rehearsal")

#: real dataset sizes (train, test): MNIST per the Larochelle split, Omniglot
#: per the Burda chardata.mat split, Fashion-MNIST per its idx files
SIZES = {"binarized_mnist": (50_000, 10_000),
         "omniglot": (24_345, 8_070),
         "fashion_mnist": (60_000, 10_000)}


def out_json(dataset: str) -> str:
    suffix = "" if dataset == "binarized_mnist" else f"_{dataset}"
    return os.path.join(REPO, "results", f"dress_rehearsal{suffix}.json")


def make_fixture_files(dataset: str, data_dir: str = DATA_DIR) -> float:
    """Write the real-size reference-format files for `dataset` (idempotent);
    returns the generation seconds (0.0 when already present)."""
    from iwae_replication_project_tpu.data.loaders import _synthetic
    from tests.fixture_io import write_idx_gz

    n_train, n_test = SIZES[dataset]
    t0 = time.perf_counter()
    if dataset == "binarized_mnist":
        train_p = os.path.join(data_dir, "binarized_mnist_train.amat")
        test_p = os.path.join(data_dir, "binarized_mnist_test.amat")
        raw_tr_p = os.path.join(data_dir, "train-images-idx3-ubyte.gz")
        raw_te_p = os.path.join(data_dir, "t10k-images-idx3-ubyte.gz")
        if all(os.path.exists(p) for p in (train_p, test_p, raw_tr_p,
                                           raw_te_p)):
            return 0.0
        os.makedirs(data_dir, exist_ok=True)
        x_train, x_test = _synthetic(dataset, n_train, n_test, seed=0)
        # Larochelle .amat: one "%d %d ... %d" line per image
        np.savetxt(train_p, x_train, fmt="%d")
        np.savetxt(test_p, x_test, fmt="%d")
        # raw grayscale (the probabilities scaled to [0,255]) for the
        # raw-means bias policy — the loader requires the train/t10k PAIR
        gray_tr, gray_te = _synthetic(dataset, n_train, n_test, seed=0,
                                      binary=False)
        write_idx_gz(raw_tr_p,
                     (gray_tr * 255).astype(np.uint8).reshape(-1, 28, 28))
        write_idx_gz(raw_te_p,
                     (gray_te * 255).astype(np.uint8).reshape(-1, 28, 28))
    elif dataset == "omniglot":
        # the Burda-split chardata.mat the reference downloads
        # (flexible_IWAE.py:164-165): "data"/"testdata" as [784, N]
        # grayscale in [0,1]; the protocol re-binarizes per epoch on device
        p = os.path.join(data_dir, "chardata.mat")
        if os.path.exists(p):
            return 0.0
        os.makedirs(data_dir, exist_ok=True)
        import scipy.io as sio
        gray_tr, gray_te = _synthetic(dataset, n_train, n_test, seed=0,
                                      binary=False)
        sio.savemat(p, {"data": gray_tr.T.astype(np.float32),
                        "testdata": gray_te.T.astype(np.float32)})
    elif dataset == "fashion_mnist":
        sub = os.path.join(data_dir, "fashion_mnist")
        tr = os.path.join(sub, "train-images-idx3-ubyte.gz")
        te = os.path.join(sub, "t10k-images-idx3-ubyte.gz")
        if os.path.exists(tr) and os.path.exists(te):
            return 0.0
        os.makedirs(sub, exist_ok=True)
        gray_tr, gray_te = _synthetic(dataset, n_train, n_test, seed=0,
                                      binary=False)
        write_idx_gz(tr, (gray_tr * 255).astype(np.uint8).reshape(-1, 28, 28))
        write_idx_gz(te, (gray_te * 255).astype(np.uint8).reshape(-1, 28, 28))
    else:
        raise ValueError(f"no rehearsal fixtures for dataset {dataset!r}")
    return time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default="binarized_mnist",
                    choices=sorted(SIZES),
                    help="which reference data pipeline to rehearse at real "
                         "file sizes (binarized_mnist = the .amat + raw-idx "
                         "north-star path; omniglot = chardata.mat with "
                         "per-epoch stochastic binarization; fashion_mnist "
                         "= the idx pair, also stochastic)")
    ap.add_argument("--checkpoint-every-passes", type=int, default=200)
    ap.add_argument("--data-dir", default=DATA_DIR)
    ap.add_argument("--fresh", action="store_true",
                    help="ignore existing checkpoints (default resumes)")
    args = ap.parse_args(argv)
    n_train, n_test = SIZES[args.dataset]

    gen_s = make_fixture_files(args.dataset, args.data_dir)
    print(f"fixture files: {args.data_dir} (generation {gen_s:.1f}s)")

    from iwae_replication_project_tpu import zoo
    from iwae_replication_project_tpu.experiment import run_experiment
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats,
        setup_persistent_cache,
        stats_delta,
    )

    cfg = zoo.get("northstar-iwae-2l-k50")  # the 2L flagship, IWAE k=50
    cfg.dataset = args.dataset
    cfg.data_dir = args.data_dir
    cfg.allow_synthetic = False  # the files MUST be found — that is the test
    cfg.log_dir = os.path.join(REPO, "runs", "dress_rehearsal")
    cfg.checkpoint_dir = os.path.join(REPO, "checkpoints", "dress_rehearsal")
    cfg.checkpoint_every_passes = args.checkpoint_every_passes
    cfg.save_figures = False
    cfg.resume = not args.fresh

    # a pre-existing checkpoint means this process will resume (and its first
    # stage's timings would cover only the remaining passes): still run, but
    # mark the measurement partial and keep the committed JSON intact
    from iwae_replication_project_tpu.utils.checkpoint import latest_step
    resumed = cfg.resume and latest_step(
        os.path.join(cfg.checkpoint_dir, cfg.run_name())) is not None

    # warm-path: the persistent compilation cache lives under the rehearsal
    # checkpoint root (run_experiment would set the same default — doing it
    # here too keeps the entry point self-describing and lint-guarded), so
    # the SECOND rehearsal run — or a preemption-resume — pays zero
    # recompiles. cache_stats deltas below separate compile from execute.
    setup_persistent_cache(cfg.compile_cache_dir, base_dir=cfg.checkpoint_dir)
    stats0 = cache_stats()

    t0 = time.perf_counter()
    state, history = run_experiment(cfg)
    total_s = time.perf_counter() - t0
    cache_delta = stats_delta(stats0)

    rows = []
    print(f"\n{'stage':>5} {'passes':>6} {'train s':>9} {'eval s':>8} "
          f"{'steps/s':>9} {'NLL':>9}")
    from iwae_replication_project_tpu.training import burda_stages
    lengths = {s: n for s, _, n in burda_stages(cfg.n_stages, cfg.passes_scale)}
    for res, _ in history:
        st = int(res["stage"])
        passes = lengths[st]
        # after a mid-stage resume the timer only covered the remaining
        # passes — use the stamped count, not the full stage length
        timed = int(res.get("stage_passes_timed", passes))
        steps = timed * (n_train // cfg.batch_size)
        tr = res.get("stage_train_seconds", float("nan"))
        ev = res.get("stage_eval_seconds", float("nan"))
        rows.append({"stage": st, "passes": passes,
                     "passes_timed": timed,
                     "train_seconds": tr, "eval_seconds": ev,
                     "checkpoint_seconds": res.get("stage_checkpoint_seconds"),
                     "compile_seconds": res.get("compile_seconds"),
                     "recompiles": res.get("compile_cache_misses"),
                     "steps_per_sec": round(steps / tr, 1) if tr else None,
                     "NLL": round(res["NLL"], 3)})
        print(f"{st:>5} {passes:>6} {tr:>9.1f} {ev:>8.1f} "
              f"{steps / tr:>9.1f} {res['NLL']:>9.3f}")

    dest = out_json(args.dataset)
    summary = {
        "metric": f"2L IWAE k=50 dress rehearsal on {args.dataset} "
                  f"(synthetic data at real file sizes)",
        "n_train": n_train, "n_test": n_test,
        "binarization": "fixed" if args.dataset == "binarized_mnist"
        else "stochastic (per-epoch, on device)",
        "total_seconds": round(total_s, 1),
        "fixture_generation_seconds": round(gen_s, 1),
        "checkpoint_every_passes": args.checkpoint_every_passes,
        # warm-path accounting over the whole run: recompiles
        # (persistent_cache_misses) is 0 when the compile cache is warm
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in cache_delta.items()},
        "stages": rows,
    }
    print(json.dumps(summary))
    complete = not resumed and len(rows) == cfg.n_stages
    if complete:
        try:
            with open(dest, "w") as f:
                json.dump(summary, f, indent=1)
            print(f"wrote {dest}")
        except OSError:
            pass
    else:
        print(f"partial/resumed run ({len(rows)}/{cfg.n_stages} stages "
              f"measured{', resumed' if resumed else ''}): NOT overwriting "
              f"{dest}; rerun with --fresh for a full measurement")


if __name__ == "__main__":
    main()
