"""Commit the two missing estimator convergence runs (VERDICT r5 weak #2).

STL and PIWAE — the two most algorithmically intricate gradient estimators in
the repo (objectives/gradients.py: score-stopped graphs, split encoder/decoder
objectives) — had oracles and mesh tests but zero committed *training* runs:
per-leaf gradient parity at one point does not show the dynamics are healthy.
This script trains both to convergence on real data (digits, the offline
replication protocol of RESULTS.md §2) under the scaled Burda schedule and
writes the trajectories to ``results/convergence_stl.json`` /
``results/convergence_piwae.json``. The slow-marked tests in
tests/test_convergence.py (TestExtendedEstimatorConvergence) re-run a short
proxy and cross-check these artifacts.

Usage: ``python scripts/estimator_convergence.py [--short]`` (short = the
3-stage CI proxy instead of the full 8-stage scaled schedule).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESULTS_DIR = os.path.join(REPO, "results")

#: the two runs: name -> (ExperimentConfig overrides, output file)
RUNS = {
    "STL": (dict(loss_function="STL", k=50),
            "convergence_stl.json"),
    # PIWAE k1 x k2 = 10 x 5 (the zoo's piwae-10x5 split, digits-scaled)
    "PIWAE": (dict(loss_function="PIWAE", k=50, k2=5),
              "convergence_piwae.json"),
}


def run_config(workdir: str, short: bool, **over):
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    d = dict(
        dataset="digits", allow_synthetic=False,
        n_hidden_encoder=(64,), n_hidden_decoder=(64,),
        n_latent_encoder=(16,), n_latent_decoder=(784,),
        batch_size=100, eval_k=5, nll_k=128, nll_chunk=64,
        eval_batch_size=99, activity_samples=64, save_figures=False,
        resume=False, seed=0,
        log_dir=os.path.join(workdir, "runs"),
        checkpoint_dir=os.path.join(workdir, "ckpt"),
    )
    # full protocol: the 8-stage Burda schedule scaled to the 1.5k-image
    # dataset (passes_scale=0.2, the digits-scaled zoo presets); short: the
    # 3-stage proxy the CI convergence tests use
    d.update(dict(n_stages=3) if short
             else dict(n_stages=8, passes_scale=0.2))
    d.update(over)
    return ExperimentConfig(**d)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--short", action="store_true",
                    help="3-stage CI proxy instead of the full scaled schedule")
    args = ap.parse_args(argv)

    from iwae_replication_project_tpu.experiment import run_experiment

    os.makedirs(RESULTS_DIR, exist_ok=True)
    for name, (over, fname) in RUNS.items():
        with tempfile.TemporaryDirectory() as workdir:
            cfg = run_config(workdir, args.short, **over)
            print(f"=== {name}: {cfg.n_stages} stages, k={cfg.k}"
                  + (f" k2={cfg.k2}" if name == "PIWAE" else ""))
            _, history = run_experiment(cfg)
        stages = [{"stage": res["stage"], "NLL": res["NLL"],
                   "IWAE": res["IWAE"], "VAE": res["VAE"],
                   "active_units": res2["number_of_active_units"],
                   "stage_train_seconds": res["stage_train_seconds"]}
                  for res, res2 in history]
        nlls = [s["NLL"] for s in stages]
        out = {
            "estimator": name,
            "protocol": ("digits 3-stage CI proxy" if args.short else
                         "digits scaled Burda schedule (8 stages, "
                         "passes_scale=0.2), RESULTS.md §2 protocol"),
            "config": {"k": cfg.k, "k2": cfg.k2, "seed": cfg.seed,
                       "n_stages": cfg.n_stages,
                       "passes_scale": cfg.passes_scale,
                       "arch": "1L h64 z16", "dataset": "digits",
                       "synthetic_data": bool(history[0][0]["synthetic_data"])},
            "stages": stages,
            "final_NLL": nlls[-1],
            "best_NLL": min(nlls),
        }
        path = os.path.join(RESULTS_DIR, fname)
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        print(f"{name}: final NLL {nlls[-1]:.2f}, best {min(nlls):.2f} "
              f"-> {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
