"""Hot-loop smoke stage for scripts/check.py: kernel parity + probe cache.

Exercises, in one short CPU process (``JAX_PLATFORMS=cpu``):

1. interpret-mode parity of the blocked (k, batch) Pallas kernel against the
   reference composition — forward and custom-VJP grads — on an odd shape
   (non-multiple-of-8 k, partial 128-batch tile, ragged pixel dim);
2. bitwise equality of the blocked-scan fallback's forward;
3. the model-level dispatch: ``log_weights`` under every forced
   ``IWAE_HOT_LOOP_PATH`` agrees bitwise with the unfused config, and the
   selection lands on the ``kernel_path`` telemetry gauge/counters;
4. the probe cache: a second ``kernel_usable_block`` query for the same
   shape must NOT re-probe (one compile probe per shape per budget — the
   lever that keeps trace-time selection free of repeated XLA work).

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs of the
    # jitted parity programs below should hit the persistent cache
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import jax.numpy as jnp
    import numpy as np

    from iwae_replication_project_tpu.models import (
        ModelConfig, init_params, log_weights)
    from iwae_replication_project_tpu.ops import hot_loop as hl

    rs = np.random.RandomState(0)
    k, b, h1d, hid, d = 10, 150, 8, 16, 130
    args = (jnp.asarray(rs.randn(k, b, h1d).astype(np.float32)),
            jnp.asarray(rs.randn(h1d, hid).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(hid, hid).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(hid, d).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
            jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32)))

    # 1) interpret-mode kernel parity (fwd + grads), partial batch tile
    want = hl._reference_impl(*args)
    got = hl._fwd_pallas(*args, tk=8, tb=128, interpret=True)
    assert np.allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                       atol=1e-4), "pallas fwd parity"
    x = args[-1]

    def loss_f(*ps):
        return jnp.sum(hl._fused_block_ll(*ps, x, 8, 128, True, None) ** 2)

    def loss_r(*ps):
        return jnp.sum(hl._reference_impl(*ps, x) ** 2)

    g_f = jax.grad(loss_f, argnums=(0, 1, 6))(*args[:-1])
    g_r = jax.grad(loss_r, argnums=(0, 1, 6))(*args[:-1])
    for a, w in zip(g_f, g_r):
        assert np.allclose(np.asarray(a), np.asarray(w), rtol=1e-4,
                           atol=1e-4), "pallas bwd parity"

    # 2) blocked-scan fallback: bitwise forward
    got_bs = hl._blocked_scan_impl(*args, block_k=4)
    assert np.array_equal(np.asarray(got_bs), np.asarray(want)), \
        "blocked-scan bitwise parity"

    # 3) model-level dispatch parity + telemetry
    cfg_f = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                        n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                        likelihood="logits", fused_likelihood=True)
    cfg_p = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                        n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                        likelihood="logits")
    params = init_params(jax.random.PRNGKey(0), cfg_p)
    xb = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5
          ).astype(jnp.float32)
    key = jax.random.PRNGKey(2)
    lw_ref = log_weights(params, cfg_p, key, xb, k=4)
    for path in ("reference", "blocked_scan", "pallas"):
        os.environ["IWAE_HOT_LOOP_PATH"] = path
        lw = log_weights(params, cfg_f, key, xb, k=4)  # iwaelint: disable=key-reuse -- parity check deliberately replays the IDENTICAL key per path; only the dispatch route may differ
        assert np.array_equal(np.asarray(lw), np.asarray(lw_ref)), \
            f"dispatch parity under {path}"
        assert hl.selected_path_code() == float(hl.PATH_CODES[path]), \
            f"kernel_path gauge under {path}"
    os.environ.pop("IWAE_HOT_LOOP_PATH", None)
    counters = hl.path_counters()
    assert counters.get("pallas", 0) >= 1 and \
        counters.get("blocked_scan", 0) >= 1, counters

    # 4) probe-cache hit: the second identical query must not re-probe
    probes = []
    real_probe = hl._probe_compiles
    hl._probe_compiles = lambda *a, **kw: probes.append(a) or True
    try:
        hl._probe_cache.clear()
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is not None
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is not None
        assert len(probes) == 1, f"probe cache missed: {len(probes)} probes"
    finally:
        hl._probe_compiles = real_probe
        hl._probe_cache.clear()

    print("hot-loop smoke: ok")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"hot-loop smoke FAILED: {e}")
        sys.exit(1)
