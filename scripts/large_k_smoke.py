"""Large-k scoring smoke stage for scripts/check.py.

One short CPU process that proves the sharded score path's three hard
invariants on a warm mesh-backed engine:

1. **paper-grade k serves online** — a k=5000 ``score`` request goes
   through the full engine lifecycle (coalesce -> bucket pad -> sharded
   AOT dispatch -> slice) and returns finite values;
2. **bitwise offline/online parity** — the engine's answer equals
   ``parallel/eval.sharded_score_offline`` at the same
   (mesh, k_chunk, seed) bit for bit: serving IS the paper's evaluation
   computation, not an approximation of it;
3. **zero recompiles across a ragged (batch, k) stream** — k is a dynamic
   scalar, so after :meth:`ShardedScoreEngine.warmup` every k in
   ``[1, k_max]`` at every bucket is an AOT-registry hit.

Tiny architecture by design: the smoke checks the dispatch/parity
plumbing, not throughput — ``bench.py --large-k`` owns the numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the sharded program instead of recompiling it
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.parallel import make_mesh
    from iwae_replication_project_tpu.parallel.eval import (
        sharded_score_offline)
    from iwae_replication_project_tpu.serving import ShardedScoreEngine
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh()     # whatever this host has (CPU CI: 1x1)
    eng = ShardedScoreEngine(params=params, model_config=cfg, mesh=mesh,
                             k_chunk=250, k_max=5000, k=50, max_batch=4,
                             timeout_s=120.0)
    warm = eng.warmup()
    # score + score_adaptive pre-built per rung
    assert warm["programs"] == 2 * len(eng.ladder.buckets), warm

    rng = np.random.RandomState(0)
    x = (rng.rand(6, D) > 0.5).astype(np.float32)
    s0 = cache_stats()

    # one paper-grade request through the live engine
    got_5000 = eng.score(x[0], k=5000)
    assert np.isfinite(got_5000), got_5000

    # ragged (batch, k) stream: every k and every bucket, zero compiles
    futures, lineup = [], []
    for i, (n, k) in enumerate([(1, 50), (3, 500), (2, 1), (4, 5000),
                                (1, 4999), (2, 250)]):
        for r in x[:n]:
            lineup.append((r, k))
            futures.append(eng.submit("score", r, k=k))
    eng.flush()
    results = [f.result(timeout=0) for f in futures]
    assert np.isfinite(np.asarray(results)).all(), "non-finite scores"
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"ragged (batch, k) stream compiled: {d}"
    assert d["persistent_cache_misses"] == 0, f"XLA recompiled: {d}"

    # bitwise parity with the offline scorer: the k=5000 request was the
    # engine's first submit (seed 0), the stream minted seeds 1..N in order
    off = sharded_score_offline(params, eng.cfg, mesh, eng._base_key,
                                np.array([0], np.int32), x[0][None], 5000,
                                k_chunk=eng.menu.k_chunk)
    assert np.array_equal(np.asarray(got_5000), np.asarray(off)[0]), \
        "engine k=5000 result != offline parallel/eval scorer (bitwise)"
    for seed, ((row, k), res) in enumerate(zip(lineup, results), start=1):
        off = sharded_score_offline(params, eng.cfg, mesh, eng._base_key,
                                    np.array([seed], np.int32), row[None],
                                    k, k_chunk=eng.menu.k_chunk)
        assert np.array_equal(np.asarray(res), np.asarray(off)[0]), \
            f"stream parity failed at seed={seed} k={k}"

    c = eng.metrics.snapshot()["counters"]
    print(f"large-k smoke OK: k=5000 served online, "
          f"{c['dispatches']} dispatches over ragged (batch, k), "
          f"0 recompiles, bitwise offline parity on mesh "
          f"{dict(mesh.shape)}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"large-k smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
