"""Reproduce results/figures/latent_digits_iwae1l.png (RESULTS.md §2).

Trains the 1L IWAE k=8 on the real sklearn digits data (fixed binarization,
raw-means bias policy — data/loaders.py) with a short three-step LR decay,
then writes the posterior-mean PCA scatter of the 50-d stochastic layer over
the digits test set, colored by class (utils/viz.latent_scatter — the
reference report's qualitative latent view, PDF pp.16-17).

Runtime: ~2 minutes on one TPU v5e chip.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from iwae_replication_project_tpu.api import FlexibleModel
from iwae_replication_project_tpu.data import digits_labels, load_dataset
from iwae_replication_project_tpu.utils.viz import latent_scatter

OUT = "results/figures/latent_digits_iwae1l.png"


def main(out: str = OUT) -> None:
    ds = load_dataset("digits")
    _, y_test = digits_labels()
    m = FlexibleModel([200], [200], [50], [784], dataset_bias=None, pixel_means=ds.bias_means,
                      loss_function="IWAE", k=8, backend="jax",
                      seed=0).compile()
    for lr, epochs in ((1e-3, 150), (5e-4, 100), (2e-4, 80)):
        m.set_learning_rate(lr)
        h = m.fit(ds.x_train, epochs=epochs, batch_size=100)
        print(f"lr={lr}: train bound {h['loss'][0]:.2f} -> {h['loss'][-1]:.2f}")
    proj = latent_scatter(m.params, m.cfg, jax.random.key(7), ds.x_test, out,
                          labels=y_test)
    print(f"wrote {out} ({proj.shape[0]} points)")


if __name__ == "__main__":
    main()
