"""Multi-model serving smoke stage for scripts/check.py (ISSUE 13).

One short CPU process that proves the multi-tenant executable store's two
hard invariants with REAL engines, a REAL socket client, and a two-model
zoo behind one tier:

1. **bitwise-correct under churn** — a burst alternating between two
   model-labeled replicas, with the store budget squeezed to fit roughly
   ONE model's executables, so every model switch forces LRU
   eviction/readmission mid-burst: every request is still answered ``ok``
   and every result bitwise-matches a dedicated single-model engine run
   of the same (payload, seed, k) — eviction is invisible to results;

2. **0 fresh compiles once warm** — after :meth:`ServingTier.warmup`
   populated the warm store AND the persistent XLA cache (the cold tier),
   the whole churning burst performs ZERO fresh XLA compiles
   (``persistent_cache_misses`` stays flat): an evicted program re-enters
   by deserialization (``store_readmits`` > 0), never by compilation.

Uses the same deliberately tiny architectures as serving_smoke.py (two
DIFFERENT shapes, so the tenants are genuinely distinct programs): this
checks store/fleet plumbing, not throughput — ``bench.py --multi-model``
owns the numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point — AND the smoke's cold
    # tier: demoted executables readmit from this cache
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.utils import compile_cache as cc

    D = 24
    cfgs = {
        # two genuinely different architectures: distinct programs, so the
        # store holds distinct per-tenant entries (a shared-arch zoo would
        # still key per model — this makes the byte accounting visible)
        "zoo-a": model.ModelConfig(x_dim=D, n_hidden_enc=(16,),
                                   n_latent_enc=(6,), n_hidden_dec=(16,),
                                   n_latent_dec=(D,)),
        "zoo-b": model.ModelConfig(x_dim=D, n_hidden_enc=(12, 8),
                                   n_latent_enc=(8, 4),
                                   n_hidden_dec=(8, 12),
                                   n_latent_dec=(8, D)),
    }
    params = {name: model.init_params(jax.random.PRNGKey(i), cfg)
              for i, (name, cfg) in enumerate(cfgs.items())}

    def engine(name, label):
        return ServingEngine(params=params[name], model_config=cfgs[name],
                             k=4, max_batch=4, max_inflight=2,
                             timeout_s=30.0, model=label)

    rng = np.random.RandomState(0)
    n_requests = 24
    rows = (rng.rand(n_requests, D) > 0.5).astype(np.float32)
    models = [("zoo-a" if i % 2 == 0 else "zoo-b")
              for i in range(n_requests)]

    # ---- reference: dedicated single-model engines, same (row, seed, k)
    # (results are a pure function of (weights, payload, seed, k), so the
    # dedicated engines are the oracle the churning tier must bit-match)
    ref = {}
    with cc.isolated_aot_registry():
        direct = {name: engine(name, label=None) for name in cfgs}
        futs = [direct[models[i]].submit("score", rows[i], seed=i)
                for i in range(n_requests)]
        for e in direct.values():
            e.flush()
        ref = {i: float(f.result()) for i, f in enumerate(futs)}

    # ---- the two-model tier behind one socket
    tier = ServingTier([engine("zoo-a", "zoo-a"), engine("zoo-b", "zoo-b")],
                       port=0)
    warm = tier.warmup(ops=("score",))
    assert warm["programs"] > 0, warm

    # squeeze the budget to ~one model's worth so every tenant switch in
    # the burst churns the store (evict + readmit)
    st = cc.store_stats()
    per_model = {m: d["resident_bytes"]
                 for m, d in st["per_model"].items() if d["entries"] > 0}
    assert set(per_model) >= {"zoo-a", "zoo-b"}, per_model
    budget = max(per_model["zoo-a"], per_model["zoo-b"]) + 1
    cc.set_store_budget(budget)

    tier.start()
    s0 = cc.cache_stats()

    # alternating single-row burst (explicit seeds: the parity hook) over
    # a real socket; pipelined so both engines hold work concurrently
    with TierClient("127.0.0.1", tier.port) as cli:
        ids = [cli.submit("score", rows[i].tolist(), seed=i,
                          model=models[i])
               for i in range(n_requests)]
        responses = cli.drain(ids)
        stats = cli.stats()

    d = cc.stats_delta(s0)
    cc.set_store_budget(None)       # restore before any assert can bail
    tier.stop(timeout_s=30)

    # every request answered ok, every result bitwise == dedicated engine
    bad = [responses[rid] for rid in ids if not responses[rid]["ok"]]
    assert not bad, f"requests failed under store churn: {bad[:2]}"
    for i, rid in enumerate(ids):
        got = float(responses[rid]["result"][0])
        assert got == ref[i], \
            (f"row {i} ({models[i]}) differs from the dedicated engine "
             f"under churn: {got!r} != {ref[i]!r}")

    # the churn really happened: the budget forced evictions and the
    # evicted programs came back as readmits (demotion -> cold tier)
    assert d["store_evictions"] > 0, f"no eviction churn: {d}"
    assert d["store_readmits"] > 0, f"no readmissions: {d}"
    assert d["store_demotions"] > 0, f"no demotions: {d}"

    # ...and NONE of it compiled anything fresh: the whole burst, churn
    # included, is served from the warm store + the persistent cold tier
    assert d["persistent_cache_misses"] == 0, \
        f"store churn caused fresh XLA compiles: {d}"

    # the wire stats doc carries the same store accounting
    ws = stats["store"]
    assert ws["budget_bytes"] == budget, ws
    assert set(ws["per_model"]) >= {"zoo-a", "zoo-b"}, ws

    print(f"multi-model smoke OK: {n_requests} requests over TCP across "
          f"2 models under a {budget}-byte budget — "
          f"{d['store_evictions']} evictions / {d['store_readmits']} "
          f"readmits mid-burst, 0 fresh compiles, bitwise == dedicated "
          f"single-model engines")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"multi-model smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
