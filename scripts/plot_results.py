"""Render results/nll_trajectories.png from the committed run artifacts.

Design notes (deliberate, not cosmetic): two panels (1L / 2L) with one y-axis
each; hue encodes the objective family (VAE blue, IWAE orange — validated
categorical slots), linestyle encodes k (dashed low, solid high) so identity
is never color-alone; series are direct-labeled at the line ends plus a
legend; grid/axes stay recessive; the best (stage-6) point is dot-marked.
"""

from __future__ import annotations

import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

# paths are relative to the repo root: run as `python scripts/plot_results.py`
# from /root/repo (reads results/summary.json + results/runs/*/metrics.jsonl)

SURFACE = "#fcfcfb"
INK = "#0b0b0b"
MUTED = "#898781"
GRID = "#e1e0d9"
BASELINE = "#c3c2b7"
BLUE = "#2a78d6"    # categorical slot 1 -> VAE
ORANGE = "#eb6834"  # categorical slot 2 -> IWAE

SERIES = [  # (loss, k, color, linestyle)
    ("VAE", 1, BLUE, (0, (4, 2))),
    ("VAE", 50, BLUE, "solid"),
    ("IWAE", 5, ORANGE, (0, (4, 2))),
    ("IWAE", 50, ORANGE, "solid"),
]


def trajectory(run_name: str):
    """NLL by stage, LAST record per stage winning — resumed/extended runs
    (and the replication driver's flake-retry) may append a duplicate stage
    row; the newest reflects the state that was actually checkpointed."""
    path = os.path.join("results/runs", run_name, "metrics.jsonl")
    by_stage = {}
    for line in open(path):
        rec = json.loads(line)
        by_stage[rec["stage"]] = rec["NLL"]
    return [by_stage[s] for s in sorted(by_stage)]


def main():
    # key by run NAME, not (layers, loss, k): the objective-switching run also
    # reports loss="VAE", k=50 and would shadow the plain VAE row
    rows = {r["name"]: r for r in json.load(open("results/summary.json"))}
    fig, axes = plt.subplots(1, 2, figsize=(9.6, 3.8), sharey=True,
                             facecolor=SURFACE)
    for ax, layers in zip(axes, (1, 2)):
        ax.set_facecolor(SURFACE)
        ends = []
        for loss, k, color, ls in SERIES:
            r = rows[f"digits-{layers}L-{loss}-k{k}"]
            nll = trajectory(r["run_name"])
            stages = range(1, len(nll) + 1)
            ax.plot(stages, nll, color=color, linestyle=ls, linewidth=2)
            best = min(range(len(nll)), key=lambda i: nll[i])
            ax.plot(best + 1, nll[best], "o", color=color, markersize=5,
                    markeredgecolor=SURFACE, markeredgewidth=1.2)
            ends.append((nll[-1], len(nll), f"{loss} k={k}"))
        # direct labels at the line ends, nudged apart so they never collide
        ends.sort()
        label_y = []
        for y, _, _ in ends:
            if label_y and y - label_y[-1] < 9.0:
                y = label_y[-1] + 9.0
            label_y.append(y)
        for (y_end, x_end, text), y_lab in zip(ends, label_y):
            ax.annotate(text, (x_end, y_end), xytext=(x_end + 0.15, y_lab),
                        fontsize=8, color=INK, va="center")
        ax.set_title(f"{layers} stochastic layer{'s' if layers > 1 else ''}",
                     fontsize=10, color=INK)
        ax.set_xlabel("Burda stage", fontsize=9, color=MUTED)
        ax.set_xlim(0.8, 9.6)
        ax.grid(True, color=GRID, linewidth=0.6)
        ax.tick_params(colors=MUTED, labelsize=8)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        for side in ("left", "bottom"):
            ax.spines[side].set_color(BASELINE)
    axes[0].set_ylabel("test NLL  (−log p̂, k=5000)", fontsize=9, color=MUTED)
    handles = [plt.Line2D([], [], color=c, linestyle=ls, linewidth=2,
                          label=f"{loss} k={k}")
               for loss, k, c, ls in SERIES]
    fig.legend(handles=handles, loc="upper center", ncol=4, frameon=False,
               fontsize=8, bbox_to_anchor=(0.5, 1.02))
    fig.suptitle("digits (real data): NLL by stage — dot marks the best stage"
                 " (overfitting begins at stage 7 of the 3280-pass schedule)",
                 fontsize=9, color=MUTED, y=1.1)
    fig.tight_layout()
    out = "results/nll_trajectories.png"
    fig.savefig(out, dpi=160, bbox_inches="tight", facecolor=SURFACE)
    print("wrote", out)


if __name__ == "__main__":
    main()
