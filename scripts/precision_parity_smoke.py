"""Low-precision serving parity smoke stage for scripts/check.py (ISSUE 16).

One short CPU process that proves the precision-policy stack end to end
with REAL engines, a REAL socket tier, and the one shared acceptance gate
(telemetry/parity.py):

1. **statistical parity at the model level** — the same rows / seeds / k
   scored through the fp32 oracle, the bf16 program, and the
   weight-only-int8 program produce ``[k, B]`` log-weights that PASS
   :func:`statistical_parity` under their policy tolerances, while a
   deliberately corrupted leg is REJECTED (the gate gates);

2. **fp32 policy is bitwise** — a ``precision="fp32"`` tenant answers
   bit-identically to the no-policy oracle engine (the explicit-fp32
   policy is pinning, not a new program);

3. **one fleet, two precisions, 0 fresh compiles** — one ServingTier
   serving the SAME weights as an fp32 tenant and a bf16 tenant
   side by side: every burst request ok, fp32 rows bitwise, bf16 rows
   inside the row tolerance, both ``@precision``-suffixed store labels
   resident, and the whole warm burst performs ZERO fresh XLA compiles;

4. **int8 admission honesty** — with ``IWAE_SERVING_INT8=force`` the
   quantized path really serves (stamped ``path int8``) and stays inside
   the int8 row tolerance; in ``auto`` mode with no measured win the
   engine records the rejection reason and serves the exact fp32 program
   bitwise.

Uses the same deliberately tiny architecture as serving_smoke.py: this
checks the precision contract, not throughput — ``bench.py --precision``
owns the numbers. Exit 0 on success, 1 with a message on the first failed
check.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import dataclasses

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.ops.hot_loop import quantize_out_block
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.telemetry.parity import (
        BF16_TOLERANCES, INT8_TOLERANCES, statistical_parity)
    from iwae_replication_project_tpu.utils import compile_cache as cc

    D, K, B = 24, 8, 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16,), n_latent_enc=(6,),
                            n_hidden_dec=(16,), n_latent_dec=(D,))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    xb = (rng.rand(B, D) > 0.5).astype(np.float32)

    # ---- 1. statistical parity of the three programs over one batch.
    # Every leg draws from a freshly constructed IDENTICAL key: shared
    # randomness is the parity contract (the legs must differ only in
    # arithmetic), not key reuse across independent draws.
    cfg_bf16 = dataclasses.replace(cfg, compute_dtype="bfloat16")
    params_q = {name: val for name, val in params.items() if name != "out"}
    params_q["out_q"] = quantize_out_block(params["out"])
    legs = {"fp32": (params, cfg), "bf16": (params, cfg_bf16),
            "int8": (params_q, cfg)}
    log_w = {leg: np.asarray(model.log_weights(
                 p, c, jax.random.PRNGKey(7), xb, K))
             for leg, (p, c) in legs.items()}
    for leg, tol in (("bf16", BF16_TOLERANCES), ("int8", INT8_TOLERANCES)):
        v = statistical_parity(log_w["fp32"], log_w[leg], tol)
        assert v["accepted"], \
            f"{leg} leg failed statistical parity: {v['failures']}"
    # the gate must also REJECT: a uniform +1 nat bias is a wrong program
    v = statistical_parity(log_w["fp32"], log_w["fp32"] + 1.0,
                           INT8_TOLERANCES)
    assert not v["accepted"], "parity gate accepted a +1-nat-biased leg"

    def engine(precision, label):
        return ServingEngine(params=params, model_config=cfg, k=K,
                             max_batch=4, max_inflight=2, timeout_s=30.0,
                             model=label, precision=precision)

    n_requests = 16
    rows = (rng.rand(n_requests, D) > 0.5).astype(np.float32)

    # ---- oracle: the no-policy engine (results are a pure function of
    # (weights, payload, seed, k), so it is the bitwise reference for
    # every fp32-program leg below)
    with cc.isolated_aot_registry():
        oracle = engine(None, None)
        futs = [oracle.submit("score", rows[i], seed=i)
                for i in range(n_requests)]
        oracle.flush()
        ref = [float(f.result()) for f in futs]

    # ---- 2 + 3. one fleet, two precisions of the SAME model
    tier = ServingTier([engine("fp32", "tenant-fp32"),
                        engine("bf16", "tenant-bf16")], port=0)
    warm = tier.warmup(ops=("score",))
    assert warm["programs"] > 0, warm
    tier.start()
    s0 = cc.cache_stats()
    tenants = [("tenant-fp32" if i % 2 == 0 else "tenant-bf16")
               for i in range(n_requests)]
    with TierClient("127.0.0.1", tier.port) as cli:
        ids = [cli.submit("score", rows[i].tolist(), seed=i,
                          model=tenants[i])
               for i in range(n_requests)]
        responses = cli.drain(ids)
        stats = cli.stats()
    d = cc.stats_delta(s0)
    tier.stop(timeout_s=30)

    bad = [responses[rid] for rid in ids if not responses[rid]["ok"]]
    assert not bad, f"mixed-precision burst had failures: {bad[:2]}"
    # per-row allowance at this shape, from the same relative row bound
    # the statistical gate enforces (|log p̂| ~ 17 nats at D=24)
    scale = max(1.0, abs(float(np.mean(ref))))
    for i, rid in enumerate(ids):
        got = float(responses[rid]["result"][0])
        if tenants[i] == "tenant-fp32":
            assert got == ref[i], \
                (f"row {i}: explicit fp32 policy diverged from the "
                 f"no-policy oracle: {got!r} != {ref[i]!r}")
        else:
            delta = abs(got - ref[i])
            assert delta <= BF16_TOLERANCES.max_row_rel_delta * scale, \
                f"row {i}: bf16 tenant off by {delta} nats"
    assert d["persistent_cache_misses"] == 0, \
        f"warm mixed-precision burst caused fresh XLA compiles: {d}"
    per_model = stats["store"]["per_model"]
    assert {"tenant-fp32@fp32", "tenant-bf16@bf16"} <= set(per_model), \
        f"precision-suffixed store labels missing: {sorted(per_model)}"

    # ---- 4. int8 admission honesty (forced on, then honest auto)
    saved = os.environ.get("IWAE_SERVING_INT8")
    try:
        os.environ["IWAE_SERVING_INT8"] = "force"
        with cc.isolated_aot_registry():
            e8 = engine("int8", "tenant-int8")
            futs = [e8.submit("score", rows[i], seed=i)
                    for i in range(n_requests)]
            e8.flush()
            forced = [float(f.result()) for f in futs]
            snap8 = e8.metrics.snapshot()
    finally:
        if saved is None:
            os.environ.pop("IWAE_SERVING_INT8", None)
        else:
            os.environ["IWAE_SERVING_INT8"] = saved
    worst = max(abs(a - b) for a, b in zip(forced, ref))
    assert worst <= INT8_TOLERANCES.max_row_rel_delta * scale, \
        f"forced int8 engine off by {worst} nats"
    int8_stamps = [key for key, rec in snap8["kernel"].items()
                   if rec.get("path") == "int8"]
    assert int8_stamps, \
        f"forced int8 engine never stamped the int8 path: {snap8['kernel']}"

    with cc.isolated_aot_registry():
        e_auto = engine("int8", "tenant-int8-auto")
        futs = [e_auto.submit("score", rows[i], seed=i)
                for i in range(n_requests)]
        e_auto.flush()
        auto = [float(f.result()) for f in futs]
        reasons = dict(e_auto.int8_admission)
    assert reasons, "auto int8 engine recorded no admission decisions"
    admitted = any(rec.get("path") == "int8" for rec in
                   e_auto.metrics.snapshot()["kernel"].values())
    if admitted:
        # a measured win (TPU): the quantized program serves, gated
        worst = max(abs(a - b) for a, b in zip(auto, ref))
        assert worst <= INT8_TOLERANCES.max_row_rel_delta * scale, \
            f"admitted int8 off by {worst} nats"
    else:
        # no measured win (CPU CI): the EXACT fp32 program serves
        assert auto == ref, \
            "unadmitted int8 policy did not serve the exact fp32 program"

    print(f"precision parity smoke OK: bf16/int8 legs pass statistical "
          f"parity (gate rejects a biased leg); fp32 policy bitwise; one "
          f"fleet served tenant-fp32@fp32 + tenant-bf16@bf16 over "
          f"{n_requests} TCP requests with 0 fresh compiles; forced int8 "
          f"stamped path=int8 within tolerance; auto admission honest "
          f"({next(iter(reasons.values()))!r})")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"precision parity smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
