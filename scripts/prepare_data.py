#!/usr/bin/env python
"""Dataset preparation / verification for the IWAE-TPU framework.

The reference downloads everything at runtime (tfds / keras.datasets /
chardata.mat — experiment_example.py:25-31, flexible_IWAE.py:147-175). This
build runs in an offline environment, so datasets resolve from local files.
This script reports what the loaders expect, what is present, and can
materialize the bundled real `digits` dataset for inspection.

Expected files under --data-dir (any one layout per dataset suffices):

  binarized_mnist   binarized_mnist_train.amat + binarized_mnist_test.amat
                    (Larochelle fixed binarization — the reference's source,
                    http://www.cs.toronto.edu/~larocheh/public/datasets/
                    binarized_mnist/), or binarized_mnist.npz with
                    x_train/x_test keys. Optionally mnist idx/npz alongside:
                    the output-bias init then uses RAW mnist means, matching
                    flexible_IWAE.py:150-155.
  mnist             mnist/train-images-idx3-ubyte(.gz) + t10k-... (classic
                    LeCun idx), same names at the root, or mnist.npz.
  fashion_mnist     fashion_mnist/train-images-idx3-ubyte(.gz) + t10k-...
                    (Zalando), or fashion_mnist.npz.
  omniglot          chardata.mat (the Burda split, as used by the reference
                    at flexible_IWAE.py:164-165), or omniglot.npz.
  digits            nothing to download — bundled with scikit-learn (UCI
                    optdigits; REAL handwritten digits, available offline).

With no real files present the loaders substitute deterministic synthetic
blobs and print an unmissable warning (results then compare to nothing).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from iwae_replication_project_tpu.data import load_dataset  # noqa: E402
from iwae_replication_project_tpu.data.loaders import DATASETS  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--data-dir", default="data")
    ap.add_argument("--export-digits", metavar="PATH", default=None,
                    help="write the prepared digits dataset to PATH (.npz)")
    ns = ap.parse_args(argv)

    print(f"checking datasets under {ns.data_dir!r}:")
    for name in DATASETS:
        try:
            ds = load_dataset(name, data_dir=ns.data_dir, allow_synthetic=False)
            print(f"  {name:16s} REAL   train={ds.x_train.shape} "
                  f"test={ds.x_test.shape} binarization={ds.binarization}")
        except FileNotFoundError:
            print(f"  {name:16s} MISSING (loaders would fall back to synthetic "
                  f"blobs; see module docstring for expected files)")
        except ImportError as e:
            # "digits" imports scikit-learn at load time; a missing dependency
            # should mark one dataset unavailable, not crash the whole report
            print(f"  {name:16s} UNAVAILABLE (import failed: {e})")

    if ns.export_digits:
        import numpy as np
        ds = load_dataset("digits", allow_synthetic=False)
        np.savez(ns.export_digits, x_train=ds.x_train, x_test=ds.x_test,
                 bias_means=ds.bias_means)
        print(f"wrote {ns.export_digits}")


if __name__ == "__main__":
    main()
