"""Continuous-profiling smoke stage for scripts/check.py.

One short CPU process proving the profiling plane's contracts on a REAL
warm engine (telemetry/profiling.py + the engine's completion-stage
hook), with the device interval pinned by an injected fetch-stage delay
so the statistics are deterministic on shared CI hosts:

1. **off-mode is invisible** — the identical request burst through a
   ``profiling=False`` twin engine returns bitwise-identical results
   (profiling is completion-thread metadata only; it never touches
   seeds, payloads, or program shapes — ``bench.py --profiling`` owns
   the overhead numbers);
2. **a clean run does not drift** — a steady stream of identical
   dispatches establishes the EWMA baseline and emits ZERO ``prof/drift``
   findings, while the measured-MFU gauge goes live (explicit
   ``ProfilingConfig`` peaks: CPU CI has no chip table entry — detection
   stays honest, the smoke supplies the roofline);
3. **a 2x slowdown trips the detector** — swapping the engine's
   injectable clock for a 2x-scaled one (still monotonic; every
   profiling timestamp reads the same clock) doubles every measured
   interval: the very next dispatches cross the z-threshold and emit
   typed ``prof/drift`` findings naming the program, with ratio ~2;
4. **the HTTP surface serves it** — ``/metrics`` (correct Content-Type)
   carries the ``iwae_prof_*`` MFU + drift families, ``/prof`` returns
   the profiler snapshot JSON, and ``/healthz`` answers 200/ok.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: fetch-stage injected device interval: large enough to dominate host
#: jitter (the z-test's sigma floor then rules), small enough that the
#: whole smoke stays ~1s of injected sleeps
DELAY_S = 0.05


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving programs instead of recompiling them
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine, faults
    from iwae_replication_project_tpu.telemetry import (
        ProfilingConfig, get_registry, start_metrics_server)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    x = (rng.rand(16, D) > 0.5).astype(np.float32)

    prof_cfg = ProfilingConfig(
        # explicit roofline peaks: arbitrary but fixed — the MFU gauge's
        # liveness is what the smoke pins, not a real chip's number
        peak_flops=1e12, peak_hbm_bytes=1e11,
        warmup_samples=6, z_threshold=6.0, min_sigma_frac=0.1)

    def engine(profiling):
        # max_batch=1: every request is its own dispatch, so the profiled
        # stream is N identical (program, bucket, k) intervals
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=1, max_inflight=0, timeout_s=60.0,
                             profiling=profiling)

    # -- 1. off-mode parity: profiling is invisible in the bits -------------
    eng_par = engine(prof_cfg)
    assert eng_par.profiler is not None, "profiling did not default on"
    eng_off = engine(False)
    assert eng_off.profiler is None, "profiling=False still built a profiler"
    eng_par.warmup(ops=("score",))
    eng_off.warmup(ops=("score",))
    out_on = eng_par.score(x)   # inline flush path: deterministic, no threads
    out_off = eng_off.score(x)
    assert out_on.tobytes() == out_off.tobytes(), \
        "profiling on/off results are not bitwise identical"
    eng_par.stop()
    eng_off.stop()

    # -- 2. clean run: baseline forms, MFU goes live, NO drift --------------
    # pin the device interval with an injected fetch delay (inside the
    # profiled [t_dispatch, fetched] window) BEFORE the drift engine's
    # first dispatch, so every profiled interval — warmup included —
    # shares the same ~50ms shape: sleep jitter is ~ms against that,
    # far under the 10% sigma floor
    faults.install(faults.FaultSchedule([faults.FaultRule(
        site=faults.SITE_ENGINE_FETCH, times=10 ** 6, name="pin_device_s",
        action=faults.delay(DELAY_S))]))
    try:
        eng = engine(prof_cfg)
        eng.warmup(ops=("score",))
        for i in range(12):
            eng.score(x[i % len(x)])
        snap = eng.profiler.snapshot()
        assert snap["keys"], "clean run attributed no dispatches"
        (key, st), = snap["keys"].items()
        assert "serve_score" in key and st["count"] >= 12, (key, st)
        assert st["last_mfu"] is not None and st["last_mfu"] > 0, \
            f"measured MFU never published: {st}"
        assert abs(st["ewma_s"] - DELAY_S) < DELAY_S, \
            f"EWMA baseline implausible vs the injected interval: {st}"
        assert not eng.profiler.findings(), \
            f"clean run tripped drift: {eng.profiler.findings()[:2]}"

        # -- 3. 2x-slowdown fake clock trips the drift detector -------------
        # still monotonic (2*t now > t before), and every profiling
        # timestamp reads the engine clock, so each measured interval
        # exactly doubles: z = ewma / max(sigma, 0.1*ewma) >= 10 > 6
        eng._clock = lambda: time.monotonic() * 2.0
        for i in range(4):
            eng.score(x[i])
        findings = eng.profiler.findings()
        assert findings, "2x-slowdown clock tripped no prof/drift finding"
        f = findings[0]
        assert f["kind"] == "prof/drift" and f["program"] == "serve_score", f
        assert 1.5 < f["ratio"] < 2.6, \
            f"drift ratio should be ~2x, got {f['ratio']:.2f}: {f}"
        assert f["z"] > prof_cfg.z_threshold, f
    finally:
        faults.clear()

    # -- 4. the HTTP surface: /metrics, /prof, /healthz ---------------------
    srv = start_metrics_server(
        (get_registry(), eng.metrics.registry), port=0,
        profilers=(eng.profiler,),
        health=lambda: {"ok": True, "engine": "running"})
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers.get("Content-Type") == \
                "text/plain; version=0.0.4; charset=utf-8", \
                r.headers.get("Content-Type")
            page = r.read().decode()
        for needle in ("iwae_prof_mfu_", "iwae_prof_drift_total",
                       "iwae_prof_dispatches_total", "iwae_prof_device_s_"):
            assert needle in page, f"/metrics missing {needle}"
        with urllib.request.urlopen(f"{base}/prof", timeout=10) as r:
            assert r.status == 200
            assert r.headers.get("Content-Type") == \
                "application/json; charset=utf-8"
            doc = json.loads(r.read().decode())
        prof = doc["profilers"][0]
        assert prof["keys"] and prof["findings"], prof
        assert prof["findings"][0]["kind"] == "prof/drift"
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert r.status == 200
            health = json.loads(r.read().decode())
        assert health["ok"] is True and health["engine"] == "running", health
    finally:
        srv.shutdown()
        eng.stop()

    print(f"prof smoke OK: profiling on/off bitwise identical, "
          f"{st['count']} clean dispatches -> MFU "
          f"{st['last_mfu']:.3g} live + zero drift, 2x fake clock -> "
          f"{len(findings)} prof/drift finding(s) on serve_score "
          f"(ratio {f['ratio']:.2f}, z {f['z']:.1f}), "
          f"/metrics + /prof + /healthz serving")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"prof smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
