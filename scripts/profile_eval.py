"""Profile the k=5000 streaming-NLL eval path on the live accelerator.

Round-2 verdict: eval ran at ~0.3% of peak (186 img/s) while training hit
13.2% MFU.  This script times `streaming_log_px` across the candidate knobs
(chunk size, compute dtype, fused-likelihood kernel, batch size) and the
jitted whole-testset driver, so the fix is driven by measurement rather than
guesswork.  Run: python scripts/profile_eval.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.evaluation.metrics import streaming_log_px
from iwae_replication_project_tpu.training import create_train_state

K = 5000


def time_fn(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    print(f"devices: {jax.devices()}  on_tpu={on_tpu}")
    rng = np.random.RandomState(0)
    key = jax.random.PRNGKey(1)

    for B in (100, 500):
        x = jnp.asarray((rng.rand(B, 784) > 0.5).astype(np.float32))
        for dtype in (None, "bfloat16"):
            for fused in ((False, True) if on_tpu else (False,)):
                cfg = ModelConfig.two_layer(
                    likelihood="logits", fused_likelihood=fused,
                    compute_dtype=dtype)
                params = create_train_state(jax.random.PRNGKey(0), cfg).params
                for chunk in (100, 250, 500, 1000):
                    if K % chunk:
                        continue
                    try:
                        dt = time_fn(lambda: streaming_log_px(
                            params, cfg, key, x, k=K, chunk=chunk))
                    except Exception as e:  # OOM etc.
                        print(f"B={B} dtype={dtype} fused={fused} chunk={chunk}: FAIL {type(e).__name__}")
                        continue
                    ips = B / dt
                    print(f"B={B:4d} dtype={str(dtype):8s} fused={int(fused)} "
                          f"chunk={chunk:5d}: {dt*1e3:8.1f} ms  {ips:8.1f} img/s")

    # isolate the scan body cost: RNG vs matmul, one chunk only
    cfg = ModelConfig.two_layer(likelihood="logits", fused_likelihood=False)
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    x = jnp.asarray((rng.rand(100, 784) > 0.5).astype(np.float32))

    lw = jax.jit(lambda p, k, xx: model.log_weights(p, cfg, k, xx, 100))
    print("one log_weights chunk=100 B=100:", time_fn(lw, params, key, x) * 1e3, "ms")

    def rng_only(k):
        keys = jax.random.split(k, 2)
        a = jax.random.normal(keys[0], (100, 100, 100))
        b = jax.random.normal(keys[1], (100, 100, 50))
        return a.sum() + b.sum()
    print("rng-only equivalent:", time_fn(jax.jit(rng_only), key) * 1e3, "ms")  # iwaelint: disable=key-reuse -- profiling harness: same key re-used so every timed variant sees identical random draws


if __name__ == "__main__":
    main()
