"""Capture + summarize TPU traces for the two production hot paths.

Produces the trace evidence VERDICT r2/r3 asked for (`utils/profiling.trace`
pointed at real work, with a committable per-op breakdown):

1. one scanned train epoch (training/epoch.make_epoch_fn) at the flagship
   IWAE k=50 2L shape — the program bench.py's `value` measures;
2. one fused whole-testset eval dispatch (evaluation/metrics.dataset_scalars)
   at the production nll_k=5000 / chunk=250 config.

For each, a `jax.profiler` trace is written under --out (xplane.pb +
trace.json.gz, regenerable, NOT meant for commit), and a compact per-category
op table is extracted with xprof's converter into
``results/profile/{train,eval}_op_profile.json`` — the committable artifact.

Usage:  python scripts/profile_trace.py [--out /tmp/iwae_trace]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_TRAIN = 5000
BATCH = 100
K = 50
EVAL_N = 10000
EVAL_K = 5000
EVAL_CHUNK = 250
EVAL_BATCH = 500  # the production eval_batch_size default (utils/config.py)


def _capture(tag: str, out_root: str, fn) -> str:
    """Run `fn` (already warmed) under a profiler trace; return the trace dir."""
    from iwae_replication_project_tpu.utils.profiling import trace

    logdir = os.path.join(out_root, tag)
    with trace(logdir):
        fn()
    return logdir


def _summarize(logdir: str):
    """xplane.pb -> nested {program -> category -> top ops} dict with raw
    times (ps) and FLOP-utilization fractions, via xprof's converter."""
    from xprof.convert import raw_to_tool_data as rtd

    xs = glob.glob(os.path.join(logdir, "plugins/profile/*/*.xplane.pb"))
    if not xs:
        raise RuntimeError(f"no xplane.pb under {logdir}")
    data, _ = rtd.xspace_to_tool_data(xs, "op_profile", {})
    d = json.loads(data if isinstance(data, str) else data.decode())
    root = d["byProgramExcludeIdle"]
    programs = []
    for prog in sorted(root.get("children", []),
                       key=lambda c: -c["metrics"].get("rawTime", 0))[:3]:
        pm = prog["metrics"]
        cats = []
        for cat in sorted(prog.get("children", []),
                          key=lambda c: -c["metrics"].get("rawTime", 0)):
            cm = cat["metrics"]
            if cm.get("rawTime", 0) == 0:
                continue
            cats.append({
                "category": cat["name"],
                "time_ms": round(cm["rawTime"] / 1e9, 4),
                "pct_of_program": round(100 * cm["rawTime"] / pm["rawTime"], 1),
                "flop_util_pct_of_peak": round(cm.get("flops", 0) * 100, 2),
                "top_ops": [
                    {"name": op["name"][:60],
                     "time_ms": round(op["metrics"]["rawTime"] / 1e9, 4),
                     "flop_util_pct": round(op["metrics"].get("flops", 0) * 100, 2)}
                    for op in sorted(cat.get("children", []),
                                     key=lambda c: -c["metrics"].get("rawTime", 0))[:3]
                ],
            })
        programs.append({
            "program": prog["name"],
            "device_time_ms": round(pm["rawTime"] / 1e9, 3),
            "flop_util_pct_of_peak": round(pm.get("flops", 0) * 100, 2),
            "counted_gflops": round(pm.get("bf16Flops", 0) / 1e9, 1),
            "note": ("FLOPs inside custom-call (Pallas) ops are invisible to "
                     "XLA's counter, so program-level util understates true "
                     "utilization"),
            "categories": cats,
        })
    return {"device_type": d.get("deviceType"), "programs": programs}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/iwae_trace",
                    help="trace output root (xplane/trace.json, regenerable)")
    ap.add_argument("--summary-dir", default="results/profile",
                    help="where the committable op-table JSONs land")
    ns = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from iwae_replication_project_tpu.evaluation.metrics import dataset_scalars
    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    # bfloat16 = the production default since round 5 (utils/config.py)
    cfg = ModelConfig.two_layer(likelihood="logits", fused_likelihood=on_tpu,
                                compute_dtype="bfloat16")
    state = create_train_state(jax.random.PRNGKey(0), cfg)
    spec = ObjectiveSpec("IWAE", k=K)
    epoch = make_epoch_fn(spec, cfg, N_TRAIN, BATCH, donate=False)
    x = jnp.asarray((np.random.RandomState(0).rand(N_TRAIN, 784) > 0.5)
                    .astype(np.float32))
    state, losses = epoch(state, x)
    np.asarray(losses)  # warm/compile outside the trace

    def train_once():
        s, l2 = epoch(state, x)
        np.asarray(l2)

    xe = jnp.asarray((np.random.RandomState(1).rand(EVAL_N, 784) > 0.5)
                     .astype(np.float32)).reshape(EVAL_N // EVAL_BATCH,
                                                  EVAL_BATCH, 784)
    ekey = jax.random.PRNGKey(1)
    np.asarray(dataset_scalars(state.params, cfg, ekey, xe, K, EVAL_K,
                               EVAL_CHUNK))  # warm

    def eval_once():
        np.asarray(dataset_scalars(state.params, cfg, ekey, xe, K, EVAL_K,
                                   EVAL_CHUNK))

    os.makedirs(ns.summary_dir, exist_ok=True)
    for tag, fn in (("train", train_once), ("eval", eval_once)):
        logdir = _capture(tag, ns.out, fn)
        summary = _summarize(logdir)
        path = os.path.join(ns.summary_dir, f"{tag}_op_profile.json")
        with open(path, "w") as f:
            json.dump(summary, f, indent=1)
        prog = summary["programs"][0] if summary["programs"] else {}
        print(f"{tag}: device {prog.get('device_time_ms')} ms, "
              f"xla-visible flop-util {prog.get('flop_util_pct_of_peak')}% "
              f"-> {path}")


if __name__ == "__main__":
    main()
