"""Race-detector smoke stage for scripts/check.py.

The ``analysis/race`` instrumented-sync layer over the REAL serving stack
— tier + router + engines over a TCP socket — under seeded perturbation
schedules (``PerturbFuzzer``: the utils/faults.py seeded-schedule idiom),
with a replica killed mid-burst in every round. Four contracts:

1. **race-clean** — across >= 50 fuzzed schedules the lockset +
   happens-before detector records ZERO races on the serving classes'
   instance-attribute traffic (engine, batcher, inflight window, router,
   replicas, tier, connections, quotas). Any report carries the seed that
   reproduces its schedule;
2. **leak-clean at runtime** — after every round's drain: zero open spans
   in the flight recorder, zero pinned executable-store entries, zero
   outstanding router futures (the static leak pass proves the release
   SHAPES exist; this proves they fire under fuzzed schedules);
3. **bitwise parity** — every round's responses, kills and reroutes
   included, are bitwise identical to an uninstrumented direct-engine
   run of the same rows (instrumentation observes, never perturbs
   results);
4. **clean uninstall** — after the sweep, every patched module global and
   class hook is the exact original object (the instrumentation-off tier
   byte-matches the reference too): zero overhead when off.

Scope note: the attribute tracer sees instance-attribute slots (binds,
rebinds, augmented counters) — the torn-flag/lost-counter race class.
Container *content* mutations (``self._pending[id] = f``) go through the
container object, not ``__setattr__``, and are covered by the lockset on
the reads/writes around them plus the queue/future HB edges.

Exit 0 on success, 1 with the reproducing seed on the first failure.
"""

from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: >= 50 seeded schedules (the acceptance floor)
N_SCHEDULES = 50
#: rows per fuzzed burst (small on purpose: the fuzz sweep buys coverage
#: from schedule diversity, not burst size; the tier smoke owns load)
SIZES = (1, 3, 2, 4)
D = 32


class KillableReplica:
    """Engine proxy with an induced-death switch (the serving_tier_smoke
    fault injector): ``kill()`` errors in-flight futures and refuses new
    submits — the router must mark it unhealthy and reroute."""

    def __init__(self, engine):
        self.engine = engine
        self.row_dims = engine.row_dims
        self.k = engine.k
        self._lock = threading.Lock()
        self._live = []
        self.killed = False
        self.submitted = 0

    def submit(self, op, row, k=None, *, seed=None):
        with self._lock:
            if self.killed:
                raise RuntimeError("replica killed (smoke fault injection)")
        f = self.engine.submit(op, row, k=k, seed=seed)
        with self._lock:
            self._live.append(f)
            self.submitted += 1
        return f

    def kill(self):
        with self._lock:
            self.killed = True
            live, self._live = self._live, []
        for f in live:
            try:
                f.set_exception(
                    RuntimeError("replica killed (smoke fault injection)"))
            except Exception:
                pass        # already completed: nothing in flight to lose

    def start(self):
        self.engine.start()

    def stop(self, timeout_s=60.0):
        self.engine.stop()

    def warmup(self, ops=(), ks=None):
        return self.engine.warmup(ops=tuple(ops), ks=ks)


def _burst(tier_port, x, sizes, victim, recorder):
    """One ragged burst through a real socket with a mid-burst kill;
    returns the responses keyed by request id, in submit order."""
    from iwae_replication_project_tpu.serving.frontend import TierClient

    with TierClient("127.0.0.1", tier_port, trace=True,
                    recorder=recorder) as cli:
        ids, off = [], 0
        for i, n in enumerate(sizes):
            ids.append(cli.submit("score", x[off:off + n].tolist()))
            off += n
            if i == len(sizes) // 2 and victim is not None:
                deadline = time.monotonic() + 10.0
                while victim.submitted == 0:
                    assert time.monotonic() < deadline, \
                        "victim replica never received work"
                    time.sleep(0.002)
                victim.kill()
        responses = cli.drain(ids)
    return [responses[rid] for rid in ids]


def _snapshot_patchables(modules):
    """(module, name, value) for every global the instrumentation can swap
    — compared identically after uninstall (contract 4)."""
    import queue as real_queue
    import threading as real_threading
    from concurrent.futures import Future as real_future

    snap = []
    for mod in modules:
        for name, val in vars(mod).items():
            if val is real_threading or val is real_queue \
                    or val is real_future:
                snap.append((mod, name, val))
    return snap


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.analysis.race import (
        Instrumentation,
        PerturbFuzzer,
        RaceDetector,
    )
    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving import batcher as mod_batcher
    from iwae_replication_project_tpu.serving import engine as mod_engine
    from iwae_replication_project_tpu.serving.frontend import ServingTier
    from iwae_replication_project_tpu.serving.frontend import (
        client as mod_client)
    from iwae_replication_project_tpu.serving.frontend import (
        quotas as mod_quotas)
    from iwae_replication_project_tpu.serving.frontend import (
        router as mod_router)
    from iwae_replication_project_tpu.serving.frontend import (
        server as mod_server)
    from iwae_replication_project_tpu.telemetry.tracing import FlightRecorder
    from iwae_replication_project_tpu.utils.compile_cache import (
        executable_store)

    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                            n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                            n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, max_inflight=2, timeout_s=30.0)

    rng = np.random.RandomState(0)
    x = (rng.rand(sum(SIZES), D) > 0.5).astype(np.float32)

    modules = (mod_engine, mod_batcher, mod_router, mod_server, mod_quotas,
               mod_client)
    tracked = (ServingEngine, mod_batcher.MicroBatcher,
               mod_batcher.InflightWindow, mod_router.ReplicaRouter,
               mod_router._Replica, mod_router._Tracked,
               mod_server.ServingTier, mod_server._Connection,
               mod_server._Pending, mod_quotas.ClientQuotas)
    pre_snap = _snapshot_patchables(modules)

    # -- the parity reference: ONE direct engine, uninstrumented ------------
    direct = engine()
    direct.warmup(ops=("score",))
    ref = direct.score(x)
    direct.stop()

    def run_round(seed, instrumented):
        """One tier burst (2 replicas, victim killed mid-burst). Returns
        (results ndarray, detector report or None, leak verdict dict)."""
        rec = FlightRecorder(capacity=64, sample_every=1)
        ins = None
        if instrumented:
            det = RaceDetector(stack_depth=4)
            fuzz = PerturbFuzzer(seed, rate=0.25, max_sleep_s=0.002)
            ins = Instrumentation(detector=det, fuzz=fuzz)
            ins.install(modules=modules, classes=tracked)
        try:
            victim = KillableReplica(engine())
            tier = ServingTier([victim, engine()], port=0,
                               monitor_interval_s=0.05, recorder=rec)
            tier.warmup(ops=("score",))
            tier.start()
            responses = _burst(tier.port, x, SIZES, victim, rec)
            tier.stop(timeout_s=30)
            outstanding = tier.router.outstanding
        finally:
            if ins is not None:
                ins.uninstall()
        bad = [r for r in responses if not r["ok"]]
        assert not bad, \
            f"seed {seed}: requests failed despite a healthy peer: {bad[:2]}"
        out = np.concatenate([np.asarray(r["result"], ref.dtype)
                              for r in responses])
        # spans finalize as futures complete; give stragglers a moment
        deadline = time.monotonic() + 10.0
        while rec.stats()["open"] and time.monotonic() < deadline:
            time.sleep(0.01)
        leaks = {
            "open_spans": rec.stats()["open"],
            "pinned_entries": sum(1 for e in executable_store().entries()
                                  if e["pinned"]),
            "outstanding_futures": outstanding,
        }
        report = ins.det.report() if ins is not None else None
        return out, report, leaks

    # -- contract 3 baseline: an uninstrumented tier burst ------------------
    out0, _, leaks0 = run_round(seed=-1, instrumented=False)
    assert np.array_equal(out0, ref), \
        "uninstrumented tier burst differs from the direct engine"
    assert not any(leaks0.values()), f"uninstrumented run leaked: {leaks0}"

    # -- contracts 1+2+3 under >= 50 fuzzed schedules -----------------------
    for seed in range(N_SCHEDULES):
        out, report, leaks = run_round(seed, instrumented=True)
        assert np.array_equal(out, ref), \
            f"seed {seed}: instrumented results differ from the direct " \
            f"engine (instrumentation must observe, never perturb)"
        assert report["total"] == 0, \
            f"seed {seed} REPRODUCES {report['total']} race(s): " \
            f"{report['races'][:2]}"
        assert not any(leaks.values()), \
            f"seed {seed}: runtime leak after drain: {leaks}"

    # -- contract 4: clean uninstall ----------------------------------------
    from concurrent.futures import Future as _RealFuture
    post_snap = _snapshot_patchables(modules)
    assert post_snap == pre_snap, \
        "uninstall left patched module globals behind"
    req_factory = mod_batcher.Request.__dataclass_fields__[
        "future"].default_factory
    assert req_factory is _RealFuture, \
        "uninstall left a traced default_factory on Request.future"
    for cell in mod_batcher.Request.__init__.__closure__ or ():
        v = cell.cell_contents
        assert not (isinstance(v, type) and issubclass(v, _RealFuture)
                    and v is not _RealFuture), \
            "uninstall left a traced factory in Request.__init__'s closure"
    for cls in tracked:
        for hook in ("__setattr__", "__getattribute__"):
            fn = vars(cls).get(hook)
            assert fn is None or \
                "_patch_class" not in getattr(fn, "__qualname__", ""), \
                f"uninstall left {hook} hook on {cls.__name__}"

    print(f"race smoke OK: {N_SCHEDULES} fuzzed schedules x "
          f"{len(SIZES)} requests with mid-burst replica kill — 0 races, "
          f"0 leaks (spans/pins/futures), bitwise == direct engine, "
          f"clean uninstall")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"race smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
