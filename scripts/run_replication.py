"""Replication evidence runs (VERDICT r2 #1): the full 8-stage Burda schedule
(experiment_example.py:75-77 intent; PDF §3.4) on every configuration that can
produce committed numbers in this zero-egress environment:

* REAL data — the `digits` dataset (sklearn-bundled UCI optdigits, prepared to
  mirror the fixed-binarization MNIST protocol, data/loaders.py): 1L and 2L
  architectures, VAE vs IWAE k=50 (the qualitative structure of PDF Table 1).
* the north-star architecture (2L flagship, experiment_example.py:48-51) with
  VAE / IWAE k=50 on the synthetic MNIST-shaped fallback — pipeline-complete
  evidence at the exact Table-1 headline config; its NLLs are NOT comparable
  to 84.77 (real binarized MNIST is unobtainable offline; see RESULTS.md).

Artifacts land in results/runs/<run_name>/ — a directory that IS committed,
unlike the scratch `runs/` dir. Committed per run: metrics.jsonl (the
numbers) and results.pkl for the flagship. Per-stage PNGs and tfevents files
are REGENERABLE binaries and are NOT committed (advisor r3: they accreted
~360 files / 12 MB by round 4; pruned in round 5 keeping one representative
figure set, the flagship IWAE-2L-k50-digits run). To regenerate any run's
figures/tfevents, rerun this script — runs are deterministic per seed:

    python scripts/run_replication.py [--quick]

Total wall time on one TPU v5e chip is a few minutes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from iwae_replication_project_tpu.experiment import run_experiment  # noqa: E402
from iwae_replication_project_tpu.utils.config import ExperimentConfig  # noqa: E402

RESULTS_DIR = "results/runs"

ARCH_1L = dict(n_hidden_encoder=(200,), n_hidden_decoder=(200,),
               n_latent_encoder=(50,), n_latent_decoder=(784,))
ARCH_2L = dict(n_hidden_encoder=(200, 100), n_hidden_decoder=(100, 200),
               n_latent_encoder=(100, 50), n_latent_decoder=(100, 784))


#: the committed evidence suite stays pinned to f32: its artifacts
#: (results/runs/*, summary.json, the RESULTS.md tables) were produced under
#: the pre-r5 default, and a rerun must regenerate THOSE numbers — not
#: append bf16 rows under the same run names. bf16 evidence has its own
#: artifact (--bf16-study -> summary_seeds_scaled_bf16.json).
_SUITE_DTYPE = "float32"


def replication_suite(n_stages: int = 8):
    """The run list. Names key the summary table in RESULTS.md."""
    runs = []
    for arch_name, arch in (("1L", ARCH_1L), ("2L", ARCH_2L)):
        for loss, k in (("VAE", 1), ("VAE", 50), ("IWAE", 5), ("IWAE", 50)):
            runs.append((f"digits-{arch_name}-{loss}-k{k}", ExperimentConfig(
                dataset="digits", allow_synthetic=False, loss_function=loss,
                k=k, n_stages=n_stages, eval_batch_size=99,
                log_dir=RESULTS_DIR, checkpoint_dir="checkpoints",
                **arch)))
    # alternative objectives (PDF Tables 5-9: one representative point per
    # table) on real data, 1L k=50 like the reference's protocol
    for name, kw in (
            ("digits-1L-Lalpha0.5-k50", dict(loss_function="L_alpha",
                                             alpha=0.5)),
            ("digits-1L-Lmedian-k50", dict(loss_function="L_median")),
            ("digits-1L-Lpower3-k50", dict(loss_function="L_power_p", p=3.0)),
            ("digits-1L-CIWAE-b0.25-k50", dict(loss_function="CIWAE",
                                               beta=0.25)),
            # k is the TOTAL sample count; k1 = k // k2, so Table 9's
            # (k1, k2) = (10, 5) point is k=50, k2=5
            ("digits-1L-MIWAE-10x5", dict(loss_function="MIWAE", k2=5)),
    ):
        runs.append((name, ExperimentConfig(
            dataset="digits", allow_synthetic=False, n_stages=n_stages,
            eval_batch_size=99, log_dir=RESULTS_DIR,
            checkpoint_dir="checkpoints",
            **{"k": 50, **ARCH_1L, **kw})))
    # extension family on real data: DReG (Tucker et al., the modified-
    # gradient estimator absent from the reference code) and the two-stage
    # objective switching of PDF Table 10 (VAE stages 1-4, IWAE from 5)
    runs.append(("digits-1L-DReG-k50", ExperimentConfig(
        dataset="digits", allow_synthetic=False, loss_function="DReG",
        k=50, n_stages=n_stages, eval_batch_size=99,
        log_dir=RESULTS_DIR, checkpoint_dir="checkpoints", **ARCH_1L)))
    runs.append(("digits-1L-VAEtoIWAE-k50", ExperimentConfig(
        dataset="digits", allow_synthetic=False, loss_function="VAE",
        switch_stage=5, switch_loss="IWAE", k=50, n_stages=n_stages,
        eval_batch_size=99, log_dir=RESULTS_DIR,
        checkpoint_dir="checkpoints", **ARCH_1L)))
    # north-star config on the synthetic MNIST-shaped fallback
    for loss, k in (("VAE", 50), ("IWAE", 50)):
        runs.append((f"synthetic-2L-{loss}-k{k}", ExperimentConfig(
            dataset="binarized_mnist", allow_synthetic=True,
            loss_function=loss, k=k, n_stages=n_stages,
            log_dir=RESULTS_DIR, checkpoint_dir="checkpoints", **ARCH_2L)))
    # stochastic-binarization protocol (PDF Table 2: per-epoch on-device
    # re-binarization — dataset "mnist" uses grayscale + stochastic policy)
    runs.append(("synthetic-stochbin-2L-IWAE-k50", ExperimentConfig(
        dataset="mnist", allow_synthetic=True, loss_function="IWAE",
        k=50, n_stages=n_stages, log_dir=RESULTS_DIR,
        checkpoint_dir="checkpoints", **ARCH_2L)))
    # ... and the same protocol on REAL data (round 4): digits_gray keeps the
    # optdigits grayscale intensities and re-binarizes per epoch on device,
    # so Table 2's fixed-vs-stochastic comparison has a real-data row pair
    # against digits-1L-{VAE-k1,IWAE-k50} above (no figures/tfevents bloat,
    # ADVICE r3)
    for loss, k in (("VAE", 1), ("IWAE", 50)):
        runs.append((f"digitsgray-1L-{loss}-k{k}", ExperimentConfig(
            dataset="digits_gray", allow_synthetic=False, loss_function=loss,
            k=k, n_stages=n_stages, eval_batch_size=99, save_figures=False,
            log_dir=RESULTS_DIR, checkpoint_dir="checkpoints", **ARCH_1L)))
    for _, cfg in runs:
        cfg.compute_dtype = _SUITE_DTYPE
        cfg.__post_init__()  # normalizes "float32" -> None (= the committed
        # artifacts' stored value, so resume identity and the dtype-drift
        # note behave exactly as before the r5 default flip)
    return runs


def seed_study(seeds=(1, 2), n_stages: int = 8, passes_scale: float = 1.0,
               compute_dtype=None):
    """Replicate the headline ordering comparison (VAE k=1 vs IWAE k=50, both
    depths) across extra seeds, for the error bars in RESULTS.md §2 (seed 0
    is covered by the main suite at passes_scale=1.0).

    With ``passes_scale<1`` (the --scaled mode) the Burda schedule shrinks
    proportionally to the 1.5k-image dataset, which removes the overfitting
    that forced best-stage selection in round 3 — the principled protocol
    whose final-stage and best-stage NLLs coincide (RESULTS.md §2).

    With ``compute_dtype="bfloat16"`` (the --bf16-study mode, VERDICT r4 #4)
    the exact same protocol runs with bf16 matmul operands, writing to
    separate scratch run/checkpoint dirs — compute_dtype is an execution
    knob, not a science field, so the run names would otherwise collide with
    the committed f32 runs and resume would skip the training."""
    runs = []
    tag = "" if passes_scale == 1.0 else f"-ps{passes_scale}"
    log_dir, ckpt_dir = RESULTS_DIR, "checkpoints"
    if compute_dtype:
        tag += f"-{compute_dtype}"
        log_dir, ckpt_dir = "runs/dtype_study", "checkpoints/dtype_study"
    for seed in seeds:
        for arch_name, arch in (("1L", ARCH_1L), ("2L", ARCH_2L)):
            for loss, k in (("VAE", 1), ("IWAE", 50)):
                runs.append((f"digits-{arch_name}-{loss}-k{k}-s{seed}{tag}",
                             ExperimentConfig(
                                 dataset="digits", allow_synthetic=False,
                                 loss_function=loss, k=k, seed=seed,
                                 n_stages=n_stages, eval_batch_size=99,
                                 passes_scale=passes_scale,
                                 # None = the committed f32 protocol, which
                                 # must keep regenerating its own numbers
                                 # after the r5 bf16 default flip
                                 compute_dtype=compute_dtype or _SUITE_DTYPE,
                                 save_figures=False, log_dir=log_dir,
                                 checkpoint_dir=ckpt_dir, **arch)))
    return runs


def torch_cross_check(n_stages: int = 5, loss: str = "IWAE",
                      eager_backend: str = "torch"):
    """Train the same digits config on an independent eager backend and on
    the JAX path; report both NLL trajectories (cross-backend scientific
    validation on REAL data; summary in results/torch_cross_check.json —
    ``loss="DReG"`` additionally validates the modified-gradient estimators
    end-to-end, writing results/torch_cross_check_dreg.json;
    ``eager_backend="tf2"`` runs the reference's own TF2 execution style,
    writing results/tf2_cross_check.json)."""
    # own log/ckpt dirs: nll_k/eval knobs are not science fields, so this
    # config's run_name collides with the main suite's digits-1L-IWAE-k5 run —
    # logging into RESULTS_DIR would append to that committed artifact
    base = dict(dataset="digits", allow_synthetic=False, loss_function=loss,
                k=5, n_stages=n_stages, eval_batch_size=99, nll_k=500,
                save_figures=False, resume=False,
                compute_dtype=_SUITE_DTYPE,  # committed artifacts are f32
                log_dir="results/cross_check",
                checkpoint_dir="checkpoints/cross_check", **ARCH_1L)
    out = {}
    for backend in ("jax", eager_backend):
        cfg = ExperimentConfig(backend=backend, **base)
        t0 = time.perf_counter()
        _, history = run_experiment(cfg)
        out[backend] = {
            "NLL_by_stage": [round(r["NLL"], 3) for r, _ in history],
            "IWAE_by_stage": [round(r["IWAE"], 3) for r, _ in history],
            "active_units": history[-1][1]["number_of_active_units"],
            "wall_seconds": round(time.perf_counter() - t0, 1),
        }
        print(f"{backend}: NLL {out[backend]['NLL_by_stage']} "
              f"in {out[backend]['wall_seconds']}s")
    out["final_nll_gap"] = round(out["jax"]["NLL_by_stage"][-1]
                                 - out[eager_backend]["NLL_by_stage"][-1], 3)
    os.makedirs("results", exist_ok=True)
    fname = (f"results/{eager_backend}_cross_check.json" if loss == "IWAE"
             else f"results/{eager_backend}_cross_check_{loss.lower()}.json")
    with open(fname, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {fname}; final NLL gap {out['final_nll_gap']} nats")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="3 stages instead of 8 (smoke)")
    ap.add_argument("--only", default=None,
                    help="substring filter on run names")
    ap.add_argument("--seed-study", action="store_true",
                    help="run the extra-seed ordering study instead of the "
                         "main suite (summary lands in "
                         "results/summary_seeds.json)")
    ap.add_argument("--scaled", action="store_true",
                    help="with --seed-study: use the principled scaled "
                         "schedule (passes_scale=0.2, seeds incl. 0; summary "
                         "lands in results/summary_seeds_scaled.json)")
    from iwae_replication_project_tpu.utils.config import _int_list
    ap.add_argument("--seeds", default=None, type=_int_list,
                    help="comma-separated seed list for --seed-study / "
                         "--bf16-study (default 0,1,2 scaled / 1,2 unscaled)")
    ap.add_argument("--bf16-study", action="store_true",
                    help="the scaled seed study under compute_dtype=bfloat16 "
                         "(VERDICT r4 #4: convergence evidence for the bf16 "
                         "default decision; summary lands in "
                         "results/summary_seeds_scaled_bf16.json)")
    ap.add_argument("--torch-check", action="store_true",
                    help="run the torch-oracle cross-backend check on digits")
    ap.add_argument("--check-loss", default=None,
                    help="objective for --torch-check / --tf2-check (e.g. "
                         "DReG validates the modified-gradient estimators "
                         "end-to-end); default IWAE")
    ap.add_argument("--tf2-check", action="store_true",
                    help="run the cross-backend check against the TF2 "
                         "backend (the reference's own execution style)")
    ns = ap.parse_args(argv)
    if ns.scaled and not ns.seed_study:
        ap.error("--scaled only applies to --seed-study (the main suite is "
                 "the unscaled r3 protocol)")
    if ns.torch_check and ns.tf2_check:
        ap.error("--torch-check and --tf2-check are separate runs; pass one "
                 "at a time")
    if ns.check_loss and not (ns.torch_check or ns.tf2_check):
        ap.error("--check-loss only applies to --torch-check / --tf2-check")
    if ns.seeds is not None and not (ns.seed_study or ns.bf16_study):
        ap.error("--seeds only applies to --seed-study / --bf16-study")
    if ns.torch_check or ns.tf2_check:
        torch_cross_check(loss=ns.check_loss or "IWAE",
                          eager_backend="tf2" if ns.tf2_check else "torch")
        return

    n_stages = 3 if ns.quick else 8
    seeds = ns.seeds
    if ns.bf16_study:
        suite = seed_study(seeds=seeds or (0, 1, 2), n_stages=n_stages,
                           passes_scale=0.2, compute_dtype="bfloat16")
    elif ns.seed_study and ns.scaled:
        suite = seed_study(seeds=seeds or (0, 1, 2), n_stages=n_stages,
                           passes_scale=0.2)
    elif ns.seed_study:
        # seed 0 at passes_scale=1.0 IS the main suite's science identity
        # (same run names/dirs) — the unscaled study must not collide with
        # the committed runs, which the old hardcoded (1,2) guaranteed
        seeds = tuple(s for s in (seeds or (1, 2)) if s != 0)
        if not seeds:
            ap.error("unscaled --seed-study cannot run seed 0 (it is the "
                     "main suite's identity); pass --scaled or other seeds")
        suite = seed_study(seeds=seeds, n_stages=n_stages)
    else:
        suite = replication_suite(n_stages)
    summary = []
    for name, cfg in suite:
        if ns.only and ns.only not in name:
            continue
        print(f"\n=== {name} ({n_stages} stages, run {cfg.run_name()}) ===")
        t0 = time.perf_counter()
        try:
            _, history = run_experiment(cfg)
        except jax.errors.JaxRuntimeError:
            # the remote-device transport occasionally drops a compile RPC
            # (INTERNAL: remote_compile read body). Retry once, resuming from
            # the last stage checkpoint. Narrow catch: deterministic errors
            # (shape/NaN/config) must fail loudly, not re-run for minutes.
            # A flake landing between a stage's logger.log and its
            # save_checkpoint (e.g. during the figure dispatches) makes the
            # retry resume from the PREVIOUS stage and re-log that stage —
            # duplicate metrics.jsonl rows are possible; all downstream
            # readers dedup by stage (last row wins).
            traceback.print_exc()
            print(f"retrying {name} once after JaxRuntimeError")
            _, history = run_experiment(cfg)
        dt = time.perf_counter() - t0
        if not history:
            print(f"--- {name}: already complete (resumed past final stage); "
                  f"keeping existing summary row")
            continue
        res, res2 = history[-1]
        nlls = [r["NLL"] for r, _ in history]
        best = min(range(len(nlls)), key=lambda i: nlls[i])
        best_stage = int(history[best][0]["stage"])  # not best+1: a resumed
        # run's history may start past stage 1
        summary.append({
            "name": name, "run_name": cfg.run_name(),
            "dataset": cfg.dataset, "loss": cfg.loss_function, "k": cfg.k,
            "seed": cfg.seed,
            "layers": len(cfg.n_hidden_encoder), "stages": n_stages,
            "passes_scale": cfg.passes_scale,
            "compute_dtype": cfg.compute_dtype or "float32",
            "synthetic_data": res["synthetic_data"],
            "NLL": round(res["NLL"], 3),
            "best_NLL": round(nlls[best], 3),
            "best_stage": best_stage,
            "IWAE_bound": round(res["IWAE"], 3),
            "VAE_bound": round(res["VAE"], 3),
            "active_units": res2["number_of_active_units"],
            "pca_active_units": res2["number_of_PCA_active_units"],
            "wall_seconds": round(dt, 1),
        })
        print(f"--- {name}: NLL={res['NLL']:.3f} "
              f"active={res2['number_of_active_units']} in {dt:.0f}s")

    os.makedirs("results", exist_ok=True)
    if ns.quick:
        # smoke runs must never replace committed 8-stage rows in place
        out = os.path.join("results", "summary_quick.json")
    elif ns.bf16_study:
        out = os.path.join("results", "summary_seeds_scaled_bf16.json")
    elif ns.seed_study:
        out = os.path.join("results", "summary_seeds_scaled.json"
                           if ns.scaled else "summary_seeds.json")
    else:
        out = os.path.join("results", "summary.json")
    if os.path.exists(out):
        # merge by run name so a filtered (--only) rerun refreshes its rows
        # without discarding the rest of the committed summary
        old = {r["name"]: r for r in json.load(open(out))}
        old.update({r["name"]: r for r in summary})
        summary = list(old.values())
    with open(out, "w") as f:
        json.dump(summary, f, indent=2)
    print(f"\nwrote {out}")
    for row in summary:
        print(row)


if __name__ == "__main__":
    main()
