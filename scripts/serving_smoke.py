"""Pipelined-serving smoke stage for scripts/check.py.

One short CPU process that proves the two-stage serving pipeline's two
hard invariants on a warm engine under a ragged burst:

1. **zero recompiles** — after :meth:`ServingEngine.warmup` the whole
   ragged stream must be AOT-registry hits (no ``aot_misses``, no
   persistent-cache misses);
2. **zero lost futures** — every submitted request completes (result, not
   timeout/error), the in-flight window drains to zero, and a mid-burst
   ``stop()`` loses nothing.

Uses a deliberately tiny architecture: the smoke checks pipeline plumbing
(dispatcher/completion hand-off, window accounting, drain), not model
throughput — ``bench.py --serving`` owns the numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving programs instead of recompiling them
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(params=params, model_config=cfg, k=4, max_batch=8,
                        max_inflight=2, timeout_s=30.0)
    warm = eng.warmup(ops=("score",))
    assert warm["programs"] == 4, warm    # ladder 1, 2, 4, 8

    # ragged burst through the live pipeline (dispatcher + completion)
    rng = np.random.RandomState(0)
    x = (rng.rand(17, D) > 0.5).astype(np.float32)
    s0 = cache_stats()
    eng.start()
    futures = []
    for n in (1, 3, 7, 2, 8, 5, 1, 4, 6, 2):
        futures.extend(eng.submit("score", r) for r in x[:n])
    # stop mid-burst on purpose: the drain contract must complete every
    # future that was accepted, with work queued AND in flight
    eng.stop()

    # zero lost futures
    assert all(f.done() for f in futures), "stop() lost futures"
    out = np.stack([f.result(timeout=0) for f in futures])
    assert np.isfinite(out).all(), "non-finite serving results"
    c = eng.metrics.snapshot()["counters"]
    assert c["completed"] == len(futures) == c["submitted"], c
    assert c["errors"] == 0 and c["timeouts"] == 0, c
    assert eng.metrics.inflight == 0, "in-flight window did not drain"

    # zero recompiles across the post-warmup stream
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"ragged burst compiled: {d}"
    assert d["persistent_cache_misses"] == 0, f"XLA recompiled: {d}"
    assert c["aot_hits"] == c["dispatches"] > 0, c

    # the latency split reached the registry (queue/device wait histograms)
    snap = eng.metrics.snapshot()
    assert any(s["count"] > 0 for s in snap["queue_wait"].values()), snap
    assert any(s["count"] > 0 for s in snap["device_wait"].values()), snap

    print(f"serving smoke OK: {c['dispatches']} dispatches, "
          f"{c['completed']} rows, 0 recompiles, window drained")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"serving smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
