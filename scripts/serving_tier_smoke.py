"""Serving-tier smoke stage for scripts/check.py.

One short CPU process that proves the network tier's two hard fleet
invariants with REAL engines, a REAL socket client, and a replica killed
mid-burst:

1. **zero lost responses** — a ragged burst through the TCP front end
   (serving/frontend/) with one of the two replicas killed while its work
   is in flight: every accepted request still gets an ``ok`` response (the
   router reroutes the dead replica's work with the ORIGINAL seeds) and the
   rerouted results are bitwise identical to a direct single-engine run of
   the same rows;
2. **zero recompiles** — after :meth:`ServingTier.warmup` the whole ragged
   stream, reroutes included, is AOT-registry hits (no ``aot_misses``, no
   persistent-cache misses): routing and failure handling never perturb
   program shapes.

The replica kill is injected through a thin proxy that errors the replica's
in-flight futures and refuses new submits — exactly the signal surface the
router sees when an engine dies for real (the engine's own tolerant future
completion makes the late real results harmless). Uses the same deliberately
tiny architecture as serving_smoke.py: this checks fleet plumbing, not
throughput — ``bench.py --serving`` owns the numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class KillableReplica:
    """Engine proxy with an induced-death switch (the smoke's fault
    injector): ``kill()`` errors every in-flight future and makes further
    submits raise — the router must mark it unhealthy and reroute."""

    def __init__(self, engine):
        self.engine = engine
        self.row_dims = engine.row_dims
        self.k = engine.k
        self._lock = threading.Lock()
        self._live = []
        self.killed = False
        self.submitted = 0

    def submit(self, op, row, k=None, *, seed=None):
        with self._lock:
            if self.killed:
                raise RuntimeError("replica killed (smoke fault injection)")
        f = self.engine.submit(op, row, k=k, seed=seed)
        with self._lock:
            self._live.append(f)
            self.submitted += 1
        return f

    def kill(self):
        with self._lock:
            self.killed = True
            live, self._live = self._live, []
        for f in live:
            try:
                f.set_exception(
                    RuntimeError("replica killed (smoke fault injection)"))
            except Exception:
                pass        # already completed: nothing in flight to lose

    def start(self):
        self.engine.start()

    def stop(self, timeout_s=60.0):
        self.engine.stop()

    def warmup(self, ops=(), ks=None):
        return self.engine.warmup(ops=tuple(ops), ks=ks)


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving programs instead of recompiling them
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, max_inflight=2, timeout_s=30.0)

    rng = np.random.RandomState(0)
    sizes = (1, 3, 7, 2, 8, 5, 1, 4, 6, 2)
    x = (rng.rand(sum(sizes), D) > 0.5).astype(np.float32)

    # the parity reference: ONE direct engine, same rows in the same order
    # (seed minting is arrival-order on both sides)
    direct = engine()
    direct.warmup(ops=("score",))
    ref = direct.score(x)
    direct.stop()

    # the tier: two replicas (one killable) on an ephemeral port
    victim = KillableReplica(engine())
    tier = ServingTier([victim, engine()], port=0, monitor_interval_s=0.05)
    warm = tier.warmup(ops=("score",))
    assert warm["programs"] > 0, warm
    tier.start()
    s0 = cache_stats()

    # ragged burst from a real socket client; kill replica 0 mid-burst
    # (half the stream written, and the victim confirmed holding work —
    # the server reads the socket asynchronously, so without the wait the
    # kill could land before any row reached the victim)
    import time as _time
    with TierClient("127.0.0.1", tier.port) as cli:
        ids, off = [], 0
        for i, n in enumerate(sizes):
            ids.append((cli.submit("score", x[off:off + n].tolist()), n, off))
            off += n
            if i == len(sizes) // 2:
                deadline = _time.monotonic() + 10.0
                while victim.submitted == 0:
                    assert _time.monotonic() < deadline, \
                        "victim replica never received work"
                    _time.sleep(0.002)
                victim.kill()
        responses = cli.drain([rid for rid, _, _ in ids])
        stats = cli.stats()

    # zero lost responses: every accepted request answered, all ok (the
    # killed replica's work rerouted, not errored — the fleet had a healthy
    # peer), and rerouted results bitwise-match the direct run
    assert len(responses) == len(ids), "burst responses lost"
    bad = [responses[rid] for rid, _, _ in ids if not responses[rid]["ok"]]
    assert not bad, f"requests failed despite a healthy peer: {bad[:2]}"
    out = np.concatenate([np.asarray(responses[rid]["result"], ref.dtype)
                          for rid, _, _ in ids])
    assert np.array_equal(out, ref), \
        "fleet results (with mid-burst kill) differ from the direct engine"

    # the router saw the death and rerouted
    r = stats["router"]
    assert r["router/replica_failures"] == 1, r
    assert r["router/reroutes"] >= 1, r
    assert [rep["healthy"] for rep in stats["replicas"]].count(False) == 1, \
        stats["replicas"]

    # zero recompiles across the whole post-warmup stream, reroutes included
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"tier burst compiled: {d}"
    assert d["persistent_cache_misses"] == 0, f"XLA recompiled: {d}"

    # graceful drain: stop answers everything and leaves nothing in flight
    tier.stop(timeout_s=30)
    assert tier.router.outstanding == 0, "drain left requests outstanding"

    print(f"serving tier smoke OK: {len(ids)} requests / {len(x)} rows over "
          f"TCP, replica killed mid-burst, {r['router/reroutes']} reroutes, "
          f"0 lost, 0 recompiles, bitwise == direct engine")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"serving tier smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
