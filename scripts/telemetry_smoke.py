"""Telemetry smoke stage for scripts/check.py: registry export + span nesting.

Exercises, in one short CPU process (``JAX_PLATFORMS=cpu``):

1. registry instruments (counter/gauge/histogram) and their snapshot/rows;
2. nested spans — the full path must appear as a ``span/...`` histogram;
3. a jitted on-device diagnostic (ESS of synthetic log-weights) — both the
   uniform-weights and the one-sample-dominates identities;
4. the three exporters: JSONL + TensorBoard via MetricsLogger (flush_every
   policy + registry export), Prometheus text, and the /metrics HTTP
   endpoint.

Exit 0 on success, 1 with a message on the first failed check. Kept
assert-light on timing (CI hosts are noisy); structure is what's checked.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm path discipline, like every entry point: the jitted ESS probe
    # below should not recompile on repeated CI runs
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.telemetry import (
        MetricRegistry, prometheus_text, span, start_metrics_server)
    from iwae_replication_project_tpu.telemetry.diagnostics import ess
    from iwae_replication_project_tpu.utils.logging import MetricsLogger

    reg = MetricRegistry()

    # 1) instruments
    reg.counter("requests").inc(3)
    reg.gauge("depth").set(2.0)
    for v in (0.001, 0.002, 0.004):
        reg.histogram("lat").record(v)
    snap = reg.snapshot()
    assert snap["counters"]["requests"] == 3, snap
    assert snap["gauges"]["depth"] == 2.0, snap
    assert snap["histograms"]["lat"]["count"] == 3, snap

    # 2) nested spans -> one histogram per full path
    with span("smoke/outer", registry=reg):
        with span("inner", registry=reg):
            pass
    rows = reg.rows()
    assert "span/smoke/outer/count" in rows, sorted(rows)
    assert "span/smoke/outer/inner/count" in rows, sorted(rows)

    # 3) jitted ESS identities: uniform weights -> k; degenerate -> ~1
    k = 8
    uniform = jax.numpy.zeros((k, 4))
    degenerate = jax.numpy.concatenate(
        [jax.numpy.full((1, 4), 50.0), jax.numpy.zeros((k - 1, 4))])
    e_u, e_d = jax.jit(lambda a, b: (ess(a), ess(b)))(uniform, degenerate)
    assert np.allclose(np.asarray(e_u), k), e_u
    assert np.allclose(np.asarray(e_d), 1.0, atol=1e-3), e_d

    # 4) exporters
    with tempfile.TemporaryDirectory() as tmp:
        logger = MetricsLogger(tmp, run_name="smoke", flush_every=100)
        logger.log({"a": 1.0}, step=1)
        logger.log_registry(reg, step=2)
        logger.close()  # flush-on-close must drain the buffered rows
        lines = open(os.path.join(tmp, "smoke", "metrics.jsonl")).read() \
            .strip().splitlines()
        assert len(lines) == 2, lines
        assert json.loads(lines[1])["span/smoke/outer/count"] == 1.0
        assert any(f.startswith("events.out.tfevents.")
                   for f in os.listdir(os.path.join(tmp, "smoke")))

    page = prometheus_text(reg)
    assert "iwae_requests_total 3" in page, page
    assert 'iwae_span_smoke_outer_inner{quantile="0.5"}' in page, page

    srv = start_metrics_server(reg, port=0)
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "iwae_depth 2.0" in body, body
    finally:
        srv.shutdown()

    print("telemetry smoke: ok")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"telemetry smoke FAILED: {e}")
        sys.exit(1)
