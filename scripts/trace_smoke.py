"""End-to-end tracing smoke stage for scripts/check.py.

One short CPU process proving the observability tentpole's two hard
contracts with REAL engines, a REAL socket, and real injected faults:

1. **one coherent trace tree per request** — a ragged burst through the
   TCP front end with (a) a replica killed mid-burst holding work (router
   reroute: the victim's attempt span closes errored, attempt-2 serves)
   and (b) a tail-latency hedge (a chaos-stalled replica beaten by the
   client's second connection): every request's retained trace has exactly
   one root, every parent id resolves inside the tree, and the tree spans
   client -> tier -> router attempt(s) -> engine pipeline stages;
2. **bitwise parity vs tracing-off** — the identical burst through a
   tracing-off tier returns bit-identical results: tracing is host-side
   metadata only, it never touches seeds, payloads, or program shapes
   (the kill is applied only on the traced run — reroutes re-serve with
   original seeds, so even the fault is invisible in the bits).

Also exercises the wire surface (the ``traces`` control op in raw and
Chrome formats) and pins the SLO burn-rate gauges on the tier's
Prometheus page.  Same deliberately tiny architecture as the other
serving smokes: this checks observability plumbing, not throughput —
``bench.py --tracing`` owns the overhead numbers.

Exit 0 on success, 1 with a message on the first failed check.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class KillableReplica:
    """Engine proxy with an induced-death switch (the reroute fault
    injector, as in serving_tier_smoke.py) — trace-capable, so the router
    forwards contexts and the engine's stage spans land in the tree."""

    traces = True

    def __init__(self, engine):
        self.engine = engine
        self.row_dims = engine.row_dims
        self.k = engine.k
        self._lock = threading.Lock()
        self._live = []
        self.killed = False
        self.submitted = 0

    def submit(self, op, row, k=None, *, seed=None, trace=None):
        with self._lock:
            if self.killed:
                raise RuntimeError("replica killed (smoke fault injection)")
        f = self.engine.submit(op, row, k=k, seed=seed, trace=trace)
        with self._lock:
            self._live.append(f)
            self.submitted += 1
        return f

    def kill(self):
        with self._lock:
            self.killed = True
            live, self._live = self._live, []
        for f in live:
            try:
                f.set_exception(
                    RuntimeError("replica killed (smoke fault injection)"))
            except Exception:
                pass        # already completed: nothing in flight to lose

    def revive(self):
        """Clear the death switch: the router's warm probe re-admits."""
        with self._lock:
            self.killed = False

    def start(self):
        self.engine.start()

    def stop(self, timeout_s=60.0):
        self.engine.stop()

    def warmup(self, ops=(), ks=None):
        return self.engine.warmup(ops=tuple(ops), ks=ks)


def _tree_check(doc, label):
    """One coherent tree: a single root, every parent resolves locally."""
    ids = {s["span_id"] for s in doc["spans"]}
    roots = [s for s in doc["spans"]
             if s["parent_id"] is None or s["parent_id"] not in ids]
    assert len(roots) == 1, \
        f"{label}: trace {doc['trace_id']} has {len(roots)} roots " \
        f"({[r['name'] for r in roots]})"
    return roots[0], {s["name"] for s in doc["spans"]}


def _wait_for_traces(recorder, trace_ids, deadline_s=20.0):
    deadline = time.monotonic() + deadline_s
    want = set(trace_ids)
    while time.monotonic() < deadline:
        have = {d["trace_id"] for d in recorder.traces()}
        if want <= have:
            return {d["trace_id"]: d for d in recorder.traces()
                    if d["trace_id"] in want}
        time.sleep(0.02)
    have = {d["trace_id"] for d in recorder.traces()}
    raise AssertionError(
        f"traces never finalized: missing {sorted(want - have)[:3]} "
        f"(recorder stats: {recorder.stats()})")


def main() -> int:
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    # warm-path discipline, like every entry point: repeated CI runs
    # deserialize the serving programs instead of recompiling them
    setup_persistent_cache(base_dir=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))

    import jax
    import numpy as np

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.serving import faults
    from iwae_replication_project_tpu.serving.frontend import (
        RetryPolicy, ServingTier, TierClient)
    from iwae_replication_project_tpu.telemetry import prometheus_text
    from iwae_replication_project_tpu.telemetry.tracing import (
        FlightRecorder, start_span)

    D = 32
    cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                            n_hidden_dec=(8, 16), n_latent_dec=(8, D))
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, max_inflight=2, timeout_s=30.0)

    rng = np.random.RandomState(0)
    sizes = (1, 3, 7, 2, 8, 5, 1, 4, 6, 2)
    x = (rng.rand(sum(sizes), D) > 0.5).astype(np.float32)

    # -- reference: the SAME burst through a tracing-OFF tier ---------------
    ref_tier = ServingTier([engine(), engine()], port=0, tracing=False)
    ref_tier.warmup(ops=("score",))
    ref_tier.start()
    with TierClient("127.0.0.1", ref_tier.port) as cli:
        ids, off = [], 0
        for n in sizes:
            ids.append(cli.submit("score", x[off:off + n].tolist()))
            off += n
        ref_resp = cli.drain(ids)
        ref = [ref_resp[rid]["result"] for rid in ids]
        assert all(ref_resp[rid]["ok"] for rid in ids), "reference burst failed"
    ref_tier.stop(timeout_s=30)

    # -- traced run: keep EVERY trace (sample_every=1), kill mid-burst ------
    rec = FlightRecorder(capacity=512, sample_every=1)
    victim = KillableReplica(engine())
    # affinity_slack=0: the hedge below must land on the OTHER replica
    # (strict least-inflight), not ride bucket affinity onto the stalled one
    tier = ServingTier([victim, engine()], port=0, monitor_interval_s=0.05,
                       affinity_slack=0, recorder=rec)
    assert tier.recorder is rec and tier.slo is not None
    tier.warmup(ops=("score",))
    tier.start()

    burst_tids = []
    with TierClient("127.0.0.1", tier.port, trace=True, recorder=rec) as cli:
        spans, ids, off = [], [], 0
        for i, n in enumerate(sizes):
            # explicit per-request root spans so the smoke knows each
            # request's trace id (the auto-mint path is equivalent)
            sp = start_span("client/request", recorder=rec,
                            attrs={"op": "score", "req": i})
            spans.append(sp)
            burst_tids.append(sp.trace_id)
            ids.append(cli.submit("score", x[off:off + n].tolist(),
                                  trace=sp.ctx()))
            off += n
            if i == len(sizes) // 2:
                deadline = time.monotonic() + 10.0
                while victim.submitted == 0:
                    assert time.monotonic() < deadline, \
                        "victim replica never received work"
                    time.sleep(0.002)
                victim.kill()
        responses = cli.drain(ids)
        for sp, rid in zip(spans, ids):
            sp.finish(error=None if responses[rid]["ok"]
                      else responses[rid].get("error"))
        assert all(responses[rid]["ok"] for rid in ids), \
            f"traced burst failed: " \
            f"{[responses[rid] for rid in ids if not responses[rid]['ok']][:2]}"
        out = [responses[rid]["result"] for rid in ids]

        # bitwise parity: tracing (and the kill it wrapped) is invisible
        assert out == ref, \
            "traced-run results differ from the tracing-off reference"

        # -- every burst request: one coherent tree, all layers present ----
        docs = _wait_for_traces(rec, burst_tids)
        rerouted = 0
        for tid in burst_tids:
            root, names = _tree_check(docs[tid], "burst")
            assert root["name"] == "client/request", root
            for need in ("tier/request", "tier/admit", "router/attempt-1",
                         "engine/queue", "engine/pad", "engine/dispatch",
                         "engine/fetch"):
                assert need in names, \
                    f"trace {tid} missing {need}: {sorted(names)}"
            if "router/attempt-2" in names:
                errored = [s for s in docs[tid]["spans"]
                           if s["name"] == "router/attempt-1"
                           and s["error"] is not None]
                assert errored, \
                    f"trace {tid} rerouted without an errored attempt-1"
                rerouted += 1
        assert rerouted >= 1, \
            "the mid-burst kill produced no rerouted trace " \
            "(no trace carries router/attempt-2)"

        # -- hedged request: revive the victim, stall it, hedge beats it ---
        deadline = time.monotonic() + 10.0
        victim.revive()
        while not all(r["healthy"] for r in tier.router.replica_states()):
            assert time.monotonic() < deadline, "victim never re-admitted"
            time.sleep(0.02)
        # stall whichever replica's dispatcher takes the NEXT launch (the
        # hedged request's primary leg, wherever the router places it);
        # the hedge then races from the un-stalled peer
        faults.install(faults.FaultSchedule([faults.FaultRule(
            site=faults.SITE_ENGINE_LAUNCH, times=1, name="stall_primary",
            action=faults.delay(1.2))]))
        try:
            hcli = TierClient(
                "127.0.0.1", tier.port, trace=True, recorder=rec,
                retry=RetryPolicy(max_attempts=2, hedge_after_s=0.15,
                                  deadline_s=20.0, seed=3))
            t0 = time.monotonic()
            hedged = hcli.score(x[0].tolist(), seed=11)
            hedge_wall = time.monotonic() - t0
            assert len(hedged) == 1, hedged
            assert hcli.retry_stats["hedges"] == 1, hcli.retry_stats
            assert hedge_wall < 1.0, \
                f"hedge did not beat the 1.2s stall ({hedge_wall:.2f}s)"
            hcli.close()
        finally:
            faults.clear()
        # find the hedge trace: the one containing a client/hedge span
        deadline = time.monotonic() + 20.0
        hdoc = None
        while hdoc is None and time.monotonic() < deadline:
            for d in rec.traces():
                if any(s["name"] == "client/hedge" for s in d["spans"]):
                    hdoc = d
                    break
            time.sleep(0.02)
        assert hdoc is not None, "hedged request produced no hedge trace"
        root, names = _tree_check(hdoc, "hedge")
        assert root["name"] == "client/request", root
        n_tier = sum(1 for s in hdoc["spans"] if s["name"] == "tier/request")
        assert n_tier == 2, \
            f"hedge trace should hold BOTH legs' tier spans, got {n_tier}"
        assert "client/attempt-1" in names and "client/hedge" in names, names

        # -- wire surface: the traces control op, raw + chrome -------------
        with TierClient("127.0.0.1", tier.port) as wcli:
            raw = wcli.traces(limit=4)
            assert raw["stats"]["retained"] >= len(sizes), raw["stats"]
            assert len(raw["traces"]) == 4
            for doc in raw["traces"]:
                for key in ("trace_id", "root", "duration_s", "error",
                            "kept", "spans"):
                    assert key in doc, key
            chrome = wcli.traces(fmt="chrome")
            json.dumps(chrome)          # valid JSON by construction
            assert chrome["traceEvents"], "chrome export is empty"
            assert any(e.get("ph") == "X" for e in chrome["traceEvents"])

    # -- SLO burn-rate gauges on the tier's Prometheus page -----------------
    page = prometheus_text(tier.registry)
    for needle in ("iwae_slo_score_latency_burn_5m",
                   "iwae_slo_score_availability_burn_1h",
                   "iwae_slo_score_requests_total"):
        assert needle in page, f"SLO schema missing {needle} on /metrics"
    slo_snap = tier.slo.snapshot()
    assert "score" in slo_snap and "5m" in slo_snap["score"]["windows"]

    tier.stop(timeout_s=30)
    assert tier.router.outstanding == 0, "drain left requests outstanding"
    stats = rec.stats()
    print(f"trace smoke OK: {len(sizes)} traced requests + 1 hedge over "
          f"TCP, kill mid-burst -> {rerouted} rerouted trace(s), every "
          f"tree coherent (client->tier->router->engine), bitwise == "
          f"tracing-off, hedge in {hedge_wall:.2f}s vs 1.2s stall, "
          f"{stats['retained']} traces retained, SLO gauges live")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"trace smoke FAILED: {e}", file=sys.stderr)
        sys.exit(1)
