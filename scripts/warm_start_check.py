"""Warm-start proof: two fresh processes, one persistent compile cache.

The warm-path contract (utils/compile_cache.py) is that every production
program is compiled at most once per cache directory — a restarted or
preemption-resumed run pays ZERO XLA recompiles. This script measures that
end to end with the flagship 2-stochastic-layer IWAE k=50 architecture on the
staged experiment driver:

* **cold** — a fresh subprocess with an empty cache dir runs the staged
  experiment; every program is a persistent-cache miss (a real XLA compile).
* **warm** — a second fresh subprocess (new PID, new JAX runtime, fresh
  checkpoint/log dirs — nothing shared but the cache dir) runs the identical
  experiment; the contract is ``persistent_cache_misses == 0``.

By default the run is the CPU fast-path equivalent of the dress rehearsal
(the full 630 s rehearsal is a TPU-host measurement): the same driver, the
same flagship architecture and program structure, with the pass/eval volume
cut down so compile time dominates — which is exactly the quantity under
test. On a TPU host, drop ``--cpu`` off and raise the knobs for a full-size
measurement.

Run:  python scripts/warm_start_check.py [--stages N] [--out PATH]
Output: one JSON summary line; written to results/warm_start_cpu.json by
default (compile-seconds + wall-clock, cold vs warm).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child_main(args) -> None:
    """One measured experiment run; prints a single JSON line on stdout."""
    import jax  # noqa: F401  (initialize before timing anything)

    from iwae_replication_project_tpu.experiment import run_experiment
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats,
        setup_persistent_cache,
    )
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    # the flagship 2L architecture (experiment_example.py:48-51) on synthetic
    # MNIST-shaped data; pass/eval volume cut for the CPU fast path
    cfg = ExperimentConfig(
        dataset="binarized_mnist", data_dir=os.path.join(args.workdir, "data"),
        allow_synthetic=True, n_stages=args.stages,
        nll_k=args.nll_k, nll_chunk=min(50, args.nll_k),
        eval_batch_size=64, activity_samples=64,
        save_figures=False, resume=False,
        log_dir=os.path.join(args.workdir, "runs"),
        checkpoint_dir=os.path.join(args.workdir, "ckpt"),
    )
    # cache dir comes from IWAE_COMPILE_CACHE (set by the parent) — this
    # explicit call is the entry-point contract (lint guard) and a no-op
    # re-resolution of the same directory
    setup_persistent_cache(cfg.compile_cache_dir, base_dir=cfg.checkpoint_dir)

    t0 = time.perf_counter()
    run_experiment(cfg, max_batches_per_pass=args.max_batches,
                   eval_subset=args.eval_subset)
    wall = time.perf_counter() - t0
    out = {"wall_seconds": round(wall, 3)}
    out.update({k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in cache_stats().items()})
    print("WARM_START_CHECK " + json.dumps(out))


def run_child(tag: str, cache_dir: str, args) -> dict:
    with tempfile.TemporaryDirectory(prefix=f"warm_start_{tag}_") as workdir:
        env = dict(os.environ)
        env["IWAE_COMPILE_CACHE"] = cache_dir
        if args.cpu:
            env["JAX_PLATFORMS"] = "cpu"
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--workdir", workdir, "--stages", str(args.stages),
               "--max-batches", str(args.max_batches),
               "--eval-subset", str(args.eval_subset),
               "--nll-k", str(args.nll_k)]
        t0 = time.perf_counter()
        r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                           text=True)
        elapsed = time.perf_counter() - t0
        if r.returncode != 0:
            sys.stderr.write(r.stdout[-4000:] + "\n" + r.stderr[-4000:])
            raise RuntimeError(f"{tag} child failed (rc={r.returncode})")
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith("WARM_START_CHECK ")][-1]
        out = json.loads(line[len("WARM_START_CHECK "):])
        out["process_seconds"] = round(elapsed, 3)
        print(f"{tag}: {json.dumps(out)}")
        return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--workdir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--stages", type=int, default=3)
    ap.add_argument("--max-batches", type=int, default=2,
                    help="batches per pass (fast-path size lever)")
    ap.add_argument("--eval-subset", type=int, default=64)
    ap.add_argument("--nll-k", type=int, default=100)
    ap.add_argument("--cpu", action="store_true", default=True,
                    help="force JAX_PLATFORMS=cpu in the children (default)")
    ap.add_argument("--native", dest="cpu", action="store_false",
                    help="use the host's native accelerator instead")
    ap.add_argument("--out", default=os.path.join(REPO, "results",
                                                  "warm_start_cpu.json"))
    args = ap.parse_args(argv)

    if args.child:
        child_main(args)
        return

    with tempfile.TemporaryDirectory(prefix="warm_start_cache_") as cache_dir:
        cold = run_child("cold", cache_dir, args)
        warm = run_child("warm", cache_dir, args)

    summary = {
        "metric": "flagship staged-driver warm start: two processes, one "
                  "persistent compile cache",
        "platform": "cpu" if args.cpu else "native",
        "config": {"stages": args.stages, "max_batches": args.max_batches,
                   "eval_subset": args.eval_subset, "nll_k": args.nll_k},
        "cold": cold,
        "warm": warm,
        "warm_recompiles": warm["persistent_cache_misses"],
        "wall_speedup": round(cold["wall_seconds"] / warm["wall_seconds"], 2),
        "compile_seconds_saved": round(
            cold["backend_compile_seconds"] - warm["backend_compile_seconds"],
            3),
    }
    print(json.dumps(summary))
    if warm["persistent_cache_misses"] != 0:
        print("WARNING: warm run recompiled "
              f"{warm['persistent_cache_misses']} programs — the warm-start "
              "contract is 0", file=sys.stderr)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"wrote {args.out}")
    return 1 if warm["persistent_cache_misses"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
