"""Test harness: fake 8-device CPU platform (SURVEY.md §4 test plan).

Must set the XLA flags before jax initializes its backends, hence the
environment mutation at module import time, before any jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon TPU plugin overrides JAX_PLATFORMS at import time; force CPU via the
# config API (must happen before the first backend initialization).
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: many driver-level tests compile identical
# tiny programs (same shapes via tiny_config), and this host has one CPU core,
# so compilation dominates suite wall-time. Cold run populates the cache;
# warm runs cut the fast profile roughly in half. The dir is gitignored.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


# ---------------------------------------------------------------------------
# runtime sanitizer layer (ISSUE 2): `pytest --sanitize` arms
# jax.transfer_guard("disallow") + jax.debug_nans around tests carrying the
# `sanitize` marker — the dynamic twin of the static host-sync lint rule.
# The guard turns any IMPLICIT host<->device transfer inside the marked test
# into a hard error (explicit fetches — np.asarray on a jax.Array,
# device_put/device_get — stay legal), and debug_nans re-runs any primitive
# that produced a NaN un-jitted to localize it. Off by default: the guards
# change execution (debug_nans blocks async dispatch), so timing-sensitive
# tests stay honest in the plain profile.
# ---------------------------------------------------------------------------

def pytest_addoption(parser):
    parser.addoption(
        "--sanitize", action="store_true", default=False,
        help="wrap @pytest.mark.sanitize tests in jax.transfer_guard"
             "('disallow') + jax.debug_nans (run `pytest --sanitize -m "
             "sanitize` for the sanitizer profile)")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    # Wrap the CALL phase only: fixtures (setup) legitimately build device
    # inputs from host data — the contract the sanitizer enforces is that the
    # test's own compute path does no implicit transfer (even PRNGKey(0) is
    # an implicit int32 host->device commit, so marked tests take keys from
    # fixtures / fold_in rather than minting them mid-test).
    if item.config.getoption("--sanitize") and \
            item.get_closest_marker("sanitize") is not None:
        with jax.transfer_guard("disallow"), jax.debug_nans(True):
            yield
    else:
        yield


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture
def preempt_after():
    """Shared preemption simulator for the mid-stage kill/resume tests:
    ``with preempt_after(n): run_experiment(...)`` lets the n-th
    experiment.save_checkpoint call COMPLETE, then raises KeyboardInterrupt
    — i.e. the process dies right after a durable save, the contract the
    intra-stage checkpointing feature (checkpoint_every_passes) must
    survive. One definition so the kill-point arithmetic lives in one
    place."""
    import contextlib

    @contextlib.contextmanager
    def _cm(n: int):
        import iwae_replication_project_tpu.experiment as exp
        real = exp.save_checkpoint
        calls = {"n": 0}

        def dying(*a, **kw):
            real(*a, **kw)
            calls["n"] += 1
            if calls["n"] == n:
                raise KeyboardInterrupt("simulated preemption")

        exp.save_checkpoint = dying
        try:
            yield calls
        finally:
            exp.save_checkpoint = real

    return _cm
