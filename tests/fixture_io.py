"""Shared fixture-file writers for the reference's on-disk data formats."""

import gzip
import struct

import numpy as np


def write_idx_gz(path, images_uint8: np.ndarray) -> None:
    """Write MNIST idx3-ubyte .gz: the raw-MNIST format the reference's data
    pipeline downloads (experiment_example.py:25-31). `images_uint8` is
    [N, 28, 28] or [N, 784] uint8."""
    arr = np.ascontiguousarray(images_uint8, dtype=np.uint8)
    n = len(arr)
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28) + arr.tobytes())
