"""Driver worker for tests/test_multihost.py (NOT a test module).

Runs the REAL production entry point (experiment.main → run_experiment) in a
multi-process cluster member. The only test-specific line is forcing the CPU
platform before the first backend use (the axon TPU plugin overrides
JAX_PLATFORMS at import time — same trick as tests/conftest.py); everything
else, including jax.distributed initialization, flows through the driver's
own --multihost path.

Usage: python multihost_driver_worker.py <experiment CLI args...>
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")

from iwae_replication_project_tpu.experiment import main  # noqa: E402

if __name__ == "__main__":
    main(sys.argv[1:])
