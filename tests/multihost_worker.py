"""Worker process for tests/test_multihost.py (NOT a test module).

Each worker owns 4 virtual CPU devices (XLA_FLAGS set by the parent), joins a
two-process jax.distributed cluster, builds the framework's (dp=4, sp=2)
process-spanning mesh, and runs

1. one whole-epoch compiled scan (replicated data path), and
2. one SPMD train step fed through multihost.host_local_batch_to_global with
   ONLY this process's half of the batch (the true multi-host data path),

then prints one JSON line the parent compares across processes and against
its own single-process 8-device run.

Usage: python multihost_worker.py <process_id> <num_processes> <port>
"""

import json
import sys


def main() -> None:
    proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    import jax
    # the axon TPU plugin overrides JAX_PLATFORMS at import time; force CPU
    # via the config API before any backend initialization (same trick as
    # tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    from iwae_replication_project_tpu.parallel import make_mesh, multihost

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nprocs, process_id=proc_id)

    import jax.numpy as jnp
    import numpy as np

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.parallel import (
        make_parallel_epoch_fn, make_parallel_train_step)
    from iwae_replication_project_tpu.parallel.dp import replicate
    from iwae_replication_project_tpu.training import create_train_state

    info = multihost.process_info()
    cfg = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                      n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)
    mesh = make_mesh(dp=4, sp=2)
    spec = ObjectiveSpec("IWAE", k=8)
    state0 = create_train_state(jax.random.PRNGKey(0), cfg)
    x = (jax.random.uniform(jax.random.PRNGKey(42), (32, 12)) > 0.5
         ).astype(jnp.float32)

    # 1. whole-epoch scan, replicated data (every host holds the full set)
    epoch = make_parallel_epoch_fn(spec, cfg, mesh, n_train=32, batch_size=16,
                                   donate=False)
    s1, losses = epoch(replicate(mesh, state0), replicate(mesh, x))
    losses = multihost.fetch(losses)
    leafsum = float(sum(np.abs(l).sum()
                        for l in jax.tree.leaves(multihost.fetch(s1.params))))

    # 2. one SPMD step fed host-locally: this process contributes ONLY its
    # contiguous half of the 16-row batch
    batch = np.asarray(x[:16])
    rows_per_proc = batch.shape[0] // nprocs
    local = batch[proc_id * rows_per_proc:(proc_id + 1) * rows_per_proc]
    x_global = multihost.host_local_batch_to_global(local, mesh)
    step = make_parallel_train_step(spec, cfg, mesh, donate=False,
                                    batch_size=16)
    _, metrics = step(replicate(mesh, state0), x_global)
    step_loss = float(multihost.fetch(metrics["loss"]))

    # 3. the fused sharded evaluation suite (streaming NLL psum, median
    # all_gather, ...) over the process-spanning mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    from iwae_replication_project_tpu.parallel.eval import (
        make_parallel_dataset_scalars)
    from iwae_replication_project_tpu.parallel.mesh import AXES

    scal_fn = make_parallel_dataset_scalars(cfg, mesh, k=8, nll_k=16,
                                            nll_chunk=8)
    batches = jax.device_put(jnp.asarray(np.asarray(x).reshape(2, 16, 12)),
                             NamedSharding(mesh, P(None, AXES.dp)))
    scalars = np.asarray(multihost.fetch(
        scal_fn(s1.params, jax.random.PRNGKey(3), batches)))

    # 4. same suite on a mesh whose sp PAIRS CROSS the process boundary
    # (device order transposed: each sp group holds one device from each
    # process), so the distributed logmeanexp's pmax/psum run over the
    # inter-host link. Results must be placement-independent.
    cross_devs = np.asarray(jax.devices()).reshape(nprocs, -1).T.reshape(-1)
    mesh_x = make_mesh(dp=4, sp=2, devices=list(cross_devs))
    scal_x = make_parallel_dataset_scalars(cfg, mesh_x, k=8, nll_k=16,
                                           nll_chunk=8)
    batches_x = jax.device_put(jnp.asarray(np.asarray(x).reshape(2, 16, 12)),
                               NamedSharding(mesh_x, P(None, AXES.dp)))
    params_x = jax.device_put(s1.params, NamedSharding(mesh_x, P()))
    scalars_x = np.asarray(multihost.fetch(
        scal_x(params_x, jax.random.PRNGKey(3), batches_x)))

    print(json.dumps({"proc": proc_id, "info": info,
                      "epoch_losses": np.asarray(losses).tolist(),
                      "leafsum": round(leafsum, 6),
                      "step_loss": step_loss,
                      "eval_scalars": scalars.tolist(),
                      "eval_scalars_cross_sp": scalars_x.tolist()}),
          flush=True)


if __name__ == "__main__":
    main()
