"""Adaptive-k scoring tests (ISSUE 20).

Layers, bottom up:

* the AUGMENTED online carry (``ops.logsumexp.OnlineLSEVar``) — the
  ``(m, s)`` half must stay bitwise identical to the plain ``OnlineLSE``
  recurrence (the early-stopped-prefix contract rides on it), and the
  ``s2``/ESS/SE statistics folded across ragged chunk boundaries must
  equal the exact flat-batch numbers;
* ``_merge_lse_var_over_sp`` — the cross-device merge of the augmented
  carry, unit-tested under shard_map on the fake-device mesh with the
  same suite shape as the plain ``_merge_lse_over_sp`` tests
  (sequential-merge equality, idle-device identity, all-``-inf`` never
  NaN, ragged chunk states vs flat statistics);
* ``weight_diagnostics(n_samples=)`` — ``diag/ess_frac`` under dynamic k
  normalizes by the ACTUAL count, never the padded leading axis;
* the adaptive engine — bitwise parity with the offline twin, the
  early-stop == fixed-k-prefix pin, replica independence under the
  original seed (the reroute contract), zero recompiles over a ragged
  (batch, target) stream;
* the typed ``bad_request`` for malformed accuracy targets, pinned at
  all three admission depths: engine submit, replica router, and the
  TCP wire — one shared validator, one meaning everywhere;
* the router's estimated-work dispatch: measured ``k_used`` feeds the
  per-(op, target-class) EWMA, and selection balances summed estimated
  work instead of request counts for adaptive traffic.
"""

import threading
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.ops.logsumexp import (
    OnlineLSE,
    OnlineLSEVar,
    lse_var_stats,
    online_logsumexp_init,
    online_logsumexp_update,
    online_lse_var_init,
    online_lse_var_merge,
    online_lse_var_update,
)
from iwae_replication_project_tpu.parallel import make_mesh
from iwae_replication_project_tpu.parallel.eval import (
    _merge_lse_over_sp,
    _merge_lse_var_over_sp,
    sharded_score_adaptive_offline,
)
from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map
from iwae_replication_project_tpu.serving import ShardedScoreEngine
from iwae_replication_project_tpu.serving.buckets import (
    target_class,
    validate_adaptive_target,
)
from iwae_replication_project_tpu.telemetry.diagnostics import (
    weight_diagnostics,
)

D = 12
CFG = model.ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                        n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=D)
CHUNK = 4


@pytest.fixture(scope="module")
def tiny():
    params = model.init_params(jax.random.PRNGKey(0), CFG)
    x = (np.random.RandomState(0).rand(9, D) > 0.5).astype(np.float32)
    return {"params": params, "x": x,
            "base_key": jax.device_put(jax.random.PRNGKey(7))}


def make_sharded(tiny, mesh, **kw):
    kw.setdefault("k_chunk", CHUNK)
    kw.setdefault("k_max", 100)
    kw.setdefault("k", 8)
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_s", 60.0)
    return ShardedScoreEngine(params=tiny["params"], model_config=CFG,
                              mesh=mesh, **kw)


def _flat_stats(log_w):
    """Exact flat-batch reference for (ess, se) of ``[n, B]`` log-weights,
    in float64: the numbers the streamed second-moment carry must match."""
    log_w = np.asarray(log_w, np.float64)
    n = log_w.shape[0]
    m = log_w.max(axis=0)
    w = np.exp(log_w - m)
    s, s2 = w.sum(0), (w * w).sum(0)
    ess = s * s / s2
    var = np.maximum(s2 / (s * s) - 1.0 / n, 0.0) * n / max(n - 1, 1)
    return ess, np.sqrt(var)


# ---------------------------------------------------------------------------
# the augmented carry: chunked streaming == exact flat-batch statistics
# ---------------------------------------------------------------------------

def test_lse_var_update_keeps_m_s_bitwise_equal_to_plain_carry():
    """THE prefix contract's foundation: the (m, s) half of every
    OnlineLSEVar update is expression-identical to OnlineLSE — fold the
    same chunks through both and every intermediate state must match
    BITWISE, so a consumer reading log p̂ off the augmented carry gets the
    plain carry's bits."""
    rng = np.random.RandomState(11)
    plain = online_logsumexp_init((5,))
    aug = online_lse_var_init((5,))
    for n in (3, 1, 4, 2):
        chunk = jnp.asarray(rng.randn(n, 5).astype(np.float32) * 3)
        plain = online_logsumexp_update(plain, chunk, axis=0)
        aug = online_lse_var_update(aug, chunk, axis=0)
        np.testing.assert_array_equal(np.asarray(plain.m), np.asarray(aug.m))
        np.testing.assert_array_equal(np.asarray(plain.s), np.asarray(aug.s))
        assert int(plain.n) == int(aug.n)


def test_lse_var_ragged_chunk_stream_matches_flat_statistics():
    """Ragged chunk boundaries (3+1+4+2 samples) streamed through the
    augmented carry yield the exact flat-batch ESS and SE."""
    rng = np.random.RandomState(13)
    blocks = [rng.randn(n, 6).astype(np.float32) * 2 for n in (3, 1, 4, 2)]
    st = online_lse_var_init((6,))
    for b in blocks:
        st = online_lse_var_update(st, jnp.asarray(b), axis=0)
    ess, se = lse_var_stats(st.s, st.s2, st.n)
    want_ess, want_se = _flat_stats(np.concatenate(blocks, axis=0))
    np.testing.assert_allclose(np.asarray(ess), want_ess, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(se), want_se, rtol=1e-5)
    assert int(st.n) == 10


def test_lse_var_merge_is_associative_and_matches_flat():
    """merge(merge(a, b), c) == merge(a, merge(b, c)), and either order
    reproduces the flat statistics — the property that lets the same carry
    serve a scan over chunks and a psum over devices."""
    rng = np.random.RandomState(17)
    blocks = [rng.randn(n, 4).astype(np.float32) for n in (2, 5, 3)]
    parts = []
    for b in blocks:
        parts.append(online_lse_var_update(online_lse_var_init((4,)),
                                           jnp.asarray(b), axis=0))
    left = online_lse_var_merge(online_lse_var_merge(parts[0], parts[1]),
                                parts[2])
    right = online_lse_var_merge(parts[0],
                                 online_lse_var_merge(parts[1], parts[2]))
    for a, b in zip(left, right):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    ess, se = lse_var_stats(left.s, left.s2, left.n)
    want_ess, want_se = _flat_stats(np.concatenate(blocks, axis=0))
    np.testing.assert_allclose(np.asarray(ess), want_ess, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(se), want_se, rtol=1e-5)


def test_lse_var_stats_all_inf_row_never_nan():
    """An all--inf row (no live sample) must read ess=0, se=+inf —
    defined, never NaN, never falsely converged — both straight from the
    init state and after folding an all--inf chunk."""
    st = online_lse_var_init((3,))
    ess, se = lse_var_stats(st.s, st.s2, st.n)
    assert np.array_equal(np.asarray(ess), np.zeros(3, np.float32))
    assert np.all(np.isposinf(np.asarray(se)))
    st = online_lse_var_update(
        st, jnp.full((4, 3), -jnp.inf, jnp.float32), axis=0)
    ess, se = lse_var_stats(st.s, st.s2, st.n)
    assert not np.any(np.isnan(np.asarray(ess)))
    assert np.array_equal(np.asarray(ess), np.zeros(3, np.float32))
    assert np.all(np.isposinf(np.asarray(se)))


# ---------------------------------------------------------------------------
# _merge_lse_var_over_sp: the cross-device augmented merge, in isolation
# ---------------------------------------------------------------------------

def _run_merge_var(mesh, m, s, s2):
    """Feed per-device augmented partial states ``[sp, B]`` through the
    real merge under shard_map; returns host (m_g, safe, s_g, s2_g)."""
    def local(m_l, s_l, s2_l):
        state = OnlineLSEVar(m=m_l[0], s=s_l[0], s2=s2_l[0],
                             n=jnp.int32(0))
        return _merge_lse_var_over_sp(state)

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(AXES.sp), P(AXES.sp), P(AXES.sp)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False))
    return tuple(np.asarray(v)
                 for v in fn(jnp.asarray(m), jnp.asarray(s),
                             jnp.asarray(s2)))


def _run_merge_plain(mesh, m, s):
    """The plain merge under the same harness (the bitwise (m, s) twin)."""
    def local(m_l, s_l):
        state = OnlineLSE(m=m_l[0], s=s_l[0], n=jnp.int32(0))
        return _merge_lse_over_sp(state)

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(AXES.sp), P(AXES.sp)),
        out_specs=(P(), P(), P()),
        check_vma=False))
    return tuple(np.asarray(v) for v in fn(jnp.asarray(m), jnp.asarray(s)))


@pytest.mark.parametrize("sp", [2, 4])
def test_var_merge_matches_sequential_associative_merge(devices, sp):
    mesh = make_mesh(dp=1, sp=sp)
    rng = np.random.RandomState(3)
    m = rng.randn(sp, 5).astype(np.float32) * 10
    s = rng.rand(sp, 5).astype(np.float32) + 0.1
    s2 = rng.rand(sp, 5).astype(np.float32) + 0.05
    m_g, safe, s_g, s2_g = _run_merge_var(mesh, m, s, s2)
    want = OnlineLSEVar(m=jnp.asarray(m[0]), s=jnp.asarray(s[0]),
                        s2=jnp.asarray(s2[0]), n=jnp.int32(0))
    for i in range(1, sp):
        want = online_lse_var_merge(
            want, OnlineLSEVar(m=jnp.asarray(m[i]), s=jnp.asarray(s[i]),
                               s2=jnp.asarray(s2[i]), n=jnp.int32(0)))
    np.testing.assert_array_equal(m_g, np.asarray(want.m))
    np.testing.assert_allclose(s_g, np.asarray(want.s), rtol=1e-6)
    np.testing.assert_allclose(s2_g, np.asarray(want.s2), rtol=1e-6)
    # the (m, s) half must be BITWISE what the plain sp merge computes —
    # log p̂ finalized off the augmented carry is the fixed-k program's bits
    pm, psafe, ps = _run_merge_plain(mesh, m, s)
    np.testing.assert_array_equal(m_g, pm)
    np.testing.assert_array_equal(safe, psafe)
    np.testing.assert_array_equal(s_g, ps)


def test_var_merge_idle_device_contributes_exact_zero(devices):
    """A device whose blocks were all masked carries (m=-inf, s=0, s2=0)
    — the merge must treat that as an EXACT zero contribution to BOTH
    moments, not a NaN and not a drift."""
    mesh = make_mesh(dp=1, sp=2)
    m = np.stack([np.array([1.0, -2.0], np.float32),
                  np.full((2,), -np.inf, np.float32)])
    s = np.stack([np.array([0.5, 1.5], np.float32),
                  np.zeros((2,), np.float32)])
    s2 = np.stack([np.array([0.25, 0.75], np.float32),
                   np.zeros((2,), np.float32)])
    m_g, safe, s_g, s2_g = _run_merge_var(mesh, m, s, s2)
    np.testing.assert_array_equal(m_g, m[0])
    np.testing.assert_array_equal(safe, m[0])
    np.testing.assert_array_equal(s_g, s[0])    # bitwise: + 0 exactly
    np.testing.assert_array_equal(s2_g, s2[0])  # bitwise: + 0 exactly


def test_var_merge_all_devices_all_inf_rows_never_nan(devices):
    """No live sample anywhere: the merged sums are 0 with a finite safe
    max, and the downstream statistics read ess=0, se=+inf — never NaN
    (the exp(-inf - -inf) trap, squared this time)."""
    mesh = make_mesh(dp=1, sp=2)
    m = np.full((2, 3), -np.inf, np.float32)
    z = np.zeros((2, 3), np.float32)
    m_g, safe, s_g, s2_g = _run_merge_var(mesh, m, z, z)
    assert np.all(np.isneginf(m_g))
    np.testing.assert_array_equal(safe, np.zeros(3, np.float32))
    np.testing.assert_array_equal(s_g, np.zeros(3, np.float32))
    np.testing.assert_array_equal(s2_g, np.zeros(3, np.float32))
    ess, se = lse_var_stats(jnp.asarray(s_g), jnp.asarray(s2_g), 8)
    assert not np.any(np.isnan(np.asarray(ess)))
    assert np.array_equal(np.asarray(ess), np.zeros(3, np.float32))
    assert np.all(np.isposinf(np.asarray(se)))


def test_var_merge_of_ragged_chunk_states_matches_flat_stats(devices):
    """Per-device augmented carries built from RAGGED chunk splits merge
    over sp to the exact flat-batch ESS/SE — chunking and device placement
    must both be invisible to the convergence statistics."""
    mesh = make_mesh(dp=1, sp=2)
    rng = np.random.RandomState(5)
    blocks = [rng.randn(n, 4).astype(np.float32)
              for n in (3, 1, 2, 5)]       # ragged chunks
    halves = [blocks[:2], blocks[2:]]
    m, s, s2, n_tot = [], [], [], 0
    for chunks in halves:
        st = online_lse_var_init((4,))
        for c in chunks:
            st = online_lse_var_update(st, jnp.asarray(c), axis=0)
        m.append(np.asarray(st.m))
        s.append(np.asarray(st.s))
        s2.append(np.asarray(st.s2))
        n_tot += int(st.n)
    m_g, safe, s_g, s2_g = _run_merge_var(
        mesh, np.stack(m), np.stack(s), np.stack(s2))
    ess, se = lse_var_stats(jnp.asarray(s_g), jnp.asarray(s2_g), n_tot)
    want_ess, want_se = _flat_stats(np.concatenate(blocks, axis=0))
    np.testing.assert_allclose(np.asarray(ess), want_ess, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(se), want_se, rtol=1e-5)


# ---------------------------------------------------------------------------
# weight_diagnostics under dynamic k (the diag/ess_frac fix)
# ---------------------------------------------------------------------------

def test_weight_diagnostics_dynamic_n_matches_unpadded():
    """A [16, B] buffer holding 8 live samples + 8 rows of -inf padding
    with n_samples=8 must report the SAME ess / ess_frac / log_weight_var
    as the unpadded [8, B] call — the padded leading axis must never be
    the denominator."""
    rng = np.random.RandomState(23)
    live = rng.randn(8, 5).astype(np.float32)
    padded = np.concatenate(
        [live, np.full((8, 5), -np.inf, np.float32)], axis=0)
    want = weight_diagnostics(jnp.asarray(live))
    got = weight_diagnostics(jnp.asarray(padded), n_samples=8)
    for key in ("diag/ess", "diag/ess_frac", "diag/log_weight_var"):
        np.testing.assert_allclose(float(got[key]), float(want[key]),
                                   rtol=1e-5, err_msg=key)
    # without n_samples the fraction would have silently halved
    assert abs(float(got["diag/ess_frac"])
               - float(want["diag/ess"]) / 8.0) < 1e-6


def test_weight_diagnostics_per_row_counts():
    """Per-row n_samples ([B]): each column normalizes by ITS OWN count —
    the adaptive scorer's rows stop at different k_used."""
    rng = np.random.RandomState(29)
    full = rng.randn(8, 2).astype(np.float32)
    counts = np.array([8, 4], np.float32)
    padded = full.copy()
    padded[4:, 1] = -np.inf
    got = weight_diagnostics(jnp.asarray(padded), n_samples=counts)
    e0 = weight_diagnostics(jnp.asarray(full[:, :1]))
    e1 = weight_diagnostics(jnp.asarray(full[:4, 1:]))
    np.testing.assert_allclose(
        float(got["diag/ess"]),
        (float(e0["diag/ess"]) + float(e1["diag/ess"])) / 2, rtol=1e-5)
    np.testing.assert_allclose(
        float(got["diag/ess_frac"]),
        (float(e0["diag/ess_frac"]) + float(e1["diag/ess_frac"])) / 2,
        rtol=1e-5)


def test_weight_diagnostics_zero_count_never_nan():
    """n_samples=0 (a row that drew nothing yet): every scalar is 0, never
    NaN — a NaN here would read as a health number (and abort under the
    debug_nans sanitize profile)."""
    log_w = jnp.full((4, 3), -jnp.inf, jnp.float32)
    got = weight_diagnostics(log_w, n_samples=0)
    for key, v in got.items():
        v = float(v)
        assert not np.isnan(v), key
        assert v == 0.0, key


# ---------------------------------------------------------------------------
# the adaptive engine: offline parity, prefix contract, replica independence
# ---------------------------------------------------------------------------

def test_adaptive_engine_bitwise_matches_offline_twin(devices, tiny):
    """Engine-served score_adaptive rows == the offline
    parallel/eval.sharded_score_adaptive_offline twin at explicit seeds,
    BITWISE — through coalescing, bucket padding, and slicing."""
    mesh = make_mesh(dp=2, sp=2)
    eng = make_sharded(tiny, mesh)
    n = 5
    seeds = np.arange(40, 40 + n, dtype=np.int32)
    futs = [eng.submit("score_adaptive", r, k=100, seed=int(s),
                       target_se=0.5)
            for s, r in zip(seeds, tiny["x"][:n])]
    eng.flush()
    got = np.stack([np.asarray(f.result(timeout=60)) for f in futs])
    off = np.asarray(sharded_score_adaptive_offline(
        tiny["params"], eng.cfg, mesh, eng._base_key, seeds, tiny["x"][:n],
        k_cap=100, target_se=0.5, k_chunk=CHUNK))
    assert got.shape == (n, 3) and off.shape == (n, 3)
    assert np.array_equal(got, off)
    # the stopping rule actually engaged for at least one row: k_used
    # sits on the sp*k_chunk grid, strictly below the cap somewhere
    k_used = got[:, 2]
    assert np.all(k_used % (2 * CHUNK) == 0) or np.all(k_used <= 100)
    assert np.all(k_used >= 1) and np.all(k_used <= 100)


def test_adaptive_early_stop_equals_fixed_k_prefix(devices, tiny):
    """THE determinism pin: an early-stopped row's log p̂ is BITWISE the
    plain fixed-k score at k = k_used under the same seed — stopping is a
    pure truncation of the same sample stream, never a different one."""
    mesh = make_mesh(dp=1, sp=2)
    eng = make_sharded(tiny, mesh)
    seed = 91
    fut = eng.submit("score_adaptive", tiny["x"][0], k=100, seed=seed,
                     target_se=0.5)
    eng.flush()
    log_px, se, k_used = (float(v) for v in np.asarray(fut.result(60)))
    assert 1 <= k_used <= 100 and np.isfinite(se)
    fixed = eng.submit("score", tiny["x"][0], k=int(k_used), seed=seed)
    eng.flush()
    assert np.float32(log_px) == np.asarray(fixed.result(60)), \
        (log_px, k_used)


def test_adaptive_result_independent_of_replica(devices, tiny):
    """The reroute contract: the SAME (row, seed, cap, target) served by
    two independently constructed replicas returns the bitwise-identical
    triple — results are a pure function of (weights, payload, seed,
    target, cap), never of which engine answered (so a rerouted retry
    with the original seed is invisible)."""
    mesh = make_mesh(dp=1, sp=2)
    out = []
    for _ in range(2):
        eng = make_sharded(tiny, mesh)
        fut = eng.submit("score_adaptive", tiny["x"][1], k=64, seed=17,
                         target_se=0.4, ess_floor=3.0)
        eng.flush()
        out.append(np.asarray(fut.result(60)))
    assert np.array_equal(out[0], out[1])


def test_adaptive_zero_recompiles_over_ragged_target_stream(devices, tiny):
    """THE tentpole pin: (k_cap, target_se, ess_floor) are dynamic
    scalars, so ONE warm executable per bucket serves every (batch, cap,
    target) combination — zero AOT misses, zero XLA recompiles."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    mesh = make_mesh(dp=2, sp=2)
    eng = make_sharded(tiny, mesh)
    eng.warmup()
    s0 = cache_stats()
    futs = []
    stream = ((1, 100, 0.5, None), (3, 7, 0.2, None), (2, 64, None, 4.0),
              (8, 100, 1.0, 2.0), (5, 99, 0.05, None), (1, 8, None, 2.0))
    for n, cap, tse, ef in stream:
        futs.extend(eng.submit("score_adaptive", r, k=cap, target_se=tse,
                               ess_floor=ef) for r in tiny["x"][:n])
    eng.flush()
    for f in futs:
        out = np.asarray(f.result(timeout=60))
        assert out.shape == (3,) and np.isfinite(out).all()
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"ragged (batch, target) stream compiled: {d}"
    c = eng.metrics.snapshot()["counters"]
    assert c["recompiles"] == 0


def test_adaptive_profiler_attributes_k_used(devices, tiny):
    """The SLO/profiling layer can't be gamed by easy rows: the dispatch
    profiler's per-key snapshot carries the measured k_used EWMA, not the
    cap."""
    mesh = make_mesh(dp=1, sp=2)
    eng = make_sharded(tiny, mesh)
    fut = eng.submit("score_adaptive", tiny["x"][0], k=100, seed=3,
                     target_se=0.5)
    eng.flush()
    np.asarray(fut.result(60))
    snap = eng.profiler.snapshot()
    hits = {key: st for key, st in snap["keys"].items()
            if "score_adaptive" in key}
    assert hits, sorted(snap["keys"])
    st = next(iter(hits.values()))
    assert st["ewma_k_used"] is not None
    assert 1 <= st["ewma_k_used"] <= 100


# ---------------------------------------------------------------------------
# the typed bad_request at all three admission depths (ONE shared validator)
# ---------------------------------------------------------------------------

BAD_TARGETS = (
    {"target_se": "x"},                      # non-number
    {"target_se": -1.0},                     # non-positive
    {"target_se": float("nan")},             # non-finite
    {"ess_floor": True},                     # bool masquerading as number
    {"ess_floor": 1e9},                      # can never be met under the cap
    {},                                      # target-less adaptive request
)


def test_validate_adaptive_target_rules():
    for bad in BAD_TARGETS:
        with pytest.raises(ValueError):
            validate_adaptive_target(bad.get("target_se"),
                                     bad.get("ess_floor"), 100, 100)
    # normalization: None -> 0.0 (disabled), k_cap validated as a k
    assert validate_adaptive_target(0.1, None, 50, 100) == (0.1, 0.0, 50)
    assert validate_adaptive_target(None, 8.0, 50, 100) == (0.0, 8.0, 50)
    with pytest.raises(ValueError, match="out of range"):
        validate_adaptive_target(0.1, None, 101, 100)


def test_target_class_decade_labels():
    assert target_class(1e-2, 0.0) == "se:e-2"
    assert target_class(0.05, 0.0) == "se:e-2"
    assert target_class(0.0, 250.0) == "ess:e+2"
    # an accounting key only: distinct exact targets share a decade class
    assert target_class(0.011, 0.0) == target_class(0.099, 0.0)


def test_engine_depth_rejects_malformed_targets(devices, tiny):
    """Depth 1 — engine submit: every malformed target is a synchronous
    ValueError before any queueing or program build; targets on a fixed-k
    op are rejected too."""
    eng = make_sharded(tiny, make_mesh(dp=1, sp=1))
    for bad in BAD_TARGETS:
        with pytest.raises(ValueError):
            eng.submit("score_adaptive", tiny["x"][0], k=50, **bad)
    with pytest.raises(ValueError, match="fixed-k"):
        eng.submit("score", tiny["x"][0], k=5, target_se=0.1)
    assert eng.metrics.snapshot()["counters"]["submitted"] == 0


class FakeAdaptiveEngine:
    """The engine surface plus the adaptive capability bits: serves
    score_adaptive, returns the [log_px, se, k_used] triple with a
    scripted k_used so router EWMA behavior is checkable."""

    def __init__(self, mode="auto", k_used=50.0, dims=4):
        self.mode = mode
        self.k_used = k_used
        self.row_dims = {"score": dims, "score_adaptive": dims}
        self._ADAPTIVE_OPS = ("score_adaptive",)
        self.k = 5
        self.k_max = 1000
        self.lock = threading.Lock()
        self.held = []
        self.served = []

    def submit(self, op, row, k=None, *, seed=None, target_se=None,
               ess_floor=None):
        with self.lock:
            self.served.append((op, k, seed, target_se, ess_floor))
            f = Future()
            if self.mode == "manual":
                self.held.append(f)
            else:
                f.set_result(np.array(
                    [float(seed or 0), 0.01, self.k_used], np.float32))
            return f

    def finish(self):
        with self.lock:
            held, self.held = self.held, []
        for f in held:
            f.set_result(np.array([0.0, 0.01, self.k_used], np.float32))

    def start(self):
        pass

    def stop(self, timeout_s=None):
        self.finish()

    def warmup(self, ops=(), ks=None):
        return {}


def test_router_depth_rejects_malformed_targets():
    """Depth 2 — the replica router: the same shared validator speaks
    synchronously at tier admission; nothing leaks past rejection."""
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    eng = FakeAdaptiveEngine()
    r = ReplicaRouter([eng])
    for bad in BAD_TARGETS:
        with pytest.raises(ValueError):
            r.submit("score_adaptive", [0.0] * 4, k=50, **bad)
    with pytest.raises(ValueError, match="fixed-k"):
        r.submit("score", [0.0] * 4, k=5, target_se=0.1)
    assert r.outstanding == 0 and eng.served == []
    # the cap defaults to the fleet k_max at ADMISSION
    r.submit("score_adaptive", [0.0] * 4, target_se=0.1).result(timeout=5)
    assert eng.served[-1][1] == 1000
    r.drain(timeout_s=5)


def test_wire_depth_rejects_malformed_targets():
    """Depth 3 — the TCP wire: every malformed target is a typed
    ``bad_request`` RESPONSE on a live connection, and the connection
    survives all of them."""
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.serving.frontend.client import (
        TierError)

    eng = FakeAdaptiveEngine()
    tier = ServingTier([eng], monitor_interval_s=60.0).start()
    try:
        cli = TierClient("127.0.0.1", tier.port)
        assert "score_adaptive" in cli.info()["adaptive_ops"]
        for bad in BAD_TARGETS:
            with pytest.raises(TierError) as ei:
                cli.request("score_adaptive", [0.0] * 4, k=50, **bad)
            assert ei.value.code == "bad_request", bad
        with pytest.raises(TierError) as ei:
            cli.request("score", [0.0] * 4, k=5, target_se=0.1)
        assert ei.value.code == "bad_request"
        # the connection survived all seven rejections and still serves
        out = cli.score_adaptive([0.0] * 4, k=50, target_se=0.1)
        assert len(out) == 1 and len(out[0]) == 3
        cli.close()
    finally:
        tier.stop()


# ---------------------------------------------------------------------------
# router estimated-work dispatch (fake engines — no device)
# ---------------------------------------------------------------------------

def test_router_k_used_feeds_work_ewma():
    """A completed adaptive request's measured k_used column becomes its
    (op, target-class) work estimate; the next result folds in at the
    EWMA weight."""
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter
    from iwae_replication_project_tpu.serving.frontend.router import (
        WORK_EWMA_ALPHA)

    eng = FakeAdaptiveEngine(k_used=100.0)
    r = ReplicaRouter([eng])
    r.submit("score_adaptive", [0.0] * 4, k=1000,
             target_se=1e-2).result(timeout=5)
    assert r.work_estimates() == {"score_adaptive/se:e-2": 100.0}
    eng.k_used = 200.0
    r.submit("score_adaptive", [0.0] * 4, k=1000,
             target_se=1e-2).result(timeout=5)
    want = 100.0 + WORK_EWMA_ALPHA * (200.0 - 100.0)
    assert r.work_estimates()["score_adaptive/se:e-2"] == pytest.approx(want)
    # fixed-k traffic never touches the estimator
    r.submit("score", [0.0] * 4, k=5).result(timeout=5)
    assert set(r.work_estimates()) == {"score_adaptive/se:e-2"}
    r.drain(timeout_s=5)


def test_router_balances_adaptive_by_estimated_work_not_inflight():
    """Ten easy rows must not count like ten expensive ones: a replica
    holding MORE requests of a cheap (EWMA-primed) class must still win
    over a peer holding one expensive unprimed request — the opposite of
    what least-inflight would pick."""
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    e0, e1 = FakeAdaptiveEngine(k_used=10.0), FakeAdaptiveEngine()
    r = ReplicaRouter([e0, e1], affinity_slack=0)
    # prime the (score_adaptive, se:e-2) EWMA at 10 via one completed
    # request (auto mode answers immediately; e0 wins the idle tie-break)
    r.submit("score_adaptive", [0.0] * 4, k=1000,
             target_se=1e-2).result(timeout=5)
    assert r.work_estimates()["score_adaptive/se:e-2"] == 10.0
    e0.mode = e1.mode = "manual"
    # an unprimed class (se:e-4) costs its cap: 1000 estimated samples,
    # placed on e0 (idle tie-break to the lowest index)
    r.submit("score_adaptive", [0.0] * 4, k=1000, target_se=1e-4)
    assert len(e0.served) == 2
    # two primed-class requests (10 each) pile onto e1: 0 < 1000, then
    # affinity holds at 10 <= 10
    r.submit("score_adaptive", [0.0] * 4, k=1000, target_se=1e-2)
    r.submit("score_adaptive", [0.0] * 4, k=1000, target_se=1e-2)
    assert len(e1.served) == 2
    # the decisive pick: e0 has 1 outstanding (work 1000), e1 has 2
    # (work 20). Least-inflight would choose e0; estimated work must
    # choose e1.
    r.submit("score_adaptive", [0.0] * 4, k=1000, target_se=1e-3)
    assert len(e1.served) == 3 and len(e0.served) == 2
    e0.finish()
    e1.finish()
    r.drain(timeout_s=5)
