"""Fixture tests for the static-analysis framework (analysis/).

Per ISSUE 2: every rule proves it fires on a known-bad snippet AND stays
silent on a clean one; the suppression grammar (line/file scope, mandatory
justification) is exercised; and the self-lint test runs the exact CI
invocation (the [tool.iwaelint] paths) asserting the shipped tree is clean —
the same contract scripts/check.py gates on.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from iwae_replication_project_tpu.analysis import (
    BARE_SUPPRESSION,
    USELESS_SUPPRESSION,
    LintConfig,
    all_rules,
    lint_paths,
    load_config,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, src, rel="pkg/mod.py", **config_over):
    """Lint one snippet as file `rel` under a scratch root, with hot_paths /
    entry_points etc. resolvable against that root."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    cfg = LintConfig(root=str(tmp_path), **config_over)
    return lint_paths([str(path)], cfg, root=str(tmp_path))


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# rule registry / framework
# ---------------------------------------------------------------------------

class TestFramework:
    def test_at_least_eight_rules_registered(self):
        # the ISSUE's acceptance floor; bare-suppression is a meta-rule on top
        assert len(all_rules()) >= 8

    def test_unknown_rule_in_config_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown rule"):
            lint_src(tmp_path, "x = 1\n", select=["no-such-rule"])

    def test_syntax_error_is_a_finding(self, tmp_path):
        (findings,) = lint_src(tmp_path, "def broken(:\n")
        assert findings.rule == "parse-error"

    def test_pyproject_config_loads(self):
        cfg, src = load_config(REPO)
        assert src == os.path.join(REPO, "pyproject.toml")
        assert "bench.py" in cfg.paths
        assert any(p.endswith("parallel") for p in cfg.hot_paths)


# ---------------------------------------------------------------------------
# rule 1: key-reuse
# ---------------------------------------------------------------------------

BAD_KEY_TWO_CONSUMERS = """
    import jax

    def sample(key, shape):
        a = jax.random.normal(key, shape)
        b = jax.random.uniform(key, shape)   # second consumer, same key
        return a + b
"""

BAD_KEY_LOOP = """
    import jax

    def chain(key, n):
        out = 0.0
        for _ in range(n):
            out = out + jax.random.normal(key, ())  # same key every iteration
        return out
"""

CLEAN_KEY_SPLIT = """
    import jax

    def sample(key, shape):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, shape)
        b = jax.random.uniform(k2, shape)
        return a + b

    def chain(key, n):
        out = 0.0
        for i in range(n):
            out = out + jax.random.normal(jax.random.fold_in(key, i), ())
        return out

    def loop_rebind(key, n):
        out = 0.0
        for _ in range(n):
            key, sub = jax.random.split(key)
            out = out + jax.random.normal(sub, ())
        return out
"""

CLEAN_KEY_BRANCHES = """
    import jax

    def either(key, flag):
        if flag:
            return jax.random.normal(key, ())
        return jax.random.uniform(key, ())   # alternative path, not reuse

    def early_out(spec, key, x):
        if spec == "a":
            return consumer_a(key, x)
        if spec == "b":
            return consumer_b(key, x)        # unreachable after the first
        return consumer_c(key, x)

    def shadowed(table, cfg):
        for key, value in table.items():     # dict key, not a PRNG key
            setattr(cfg, key, value)
            setattr(cfg, key, value)
"""


class TestKeyReuse:
    def test_fires_on_two_consumers(self, tmp_path):
        assert rules_of(lint_src(tmp_path, BAD_KEY_TWO_CONSUMERS)) == \
            ["key-reuse"]

    def test_fires_on_loop_reuse(self, tmp_path):
        assert "key-reuse" in rules_of(lint_src(tmp_path, BAD_KEY_LOOP))

    def test_clean_on_split_fold_and_rebind(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_KEY_SPLIT) == []

    def test_clean_on_branches_and_shadowing(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_KEY_BRANCHES) == []


# ---------------------------------------------------------------------------
# rule 2: donated-after-call
# ---------------------------------------------------------------------------

BAD_DONATED = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def train(state, batches):
        new_state, loss = step(state, batches)
        return new_state, state.params       # state's buffers were donated
"""

CLEAN_DONATED = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def train(state, batches):
        state, loss = step(state, batches)   # re-bound: old buffer dropped
        return state, loss

    def loop(state, xs):
        for x in xs:
            state, _ = step(state, x)
        return state
"""


class TestDonatedAfterCall:
    def test_fires_on_read_after_donation(self, tmp_path):
        assert rules_of(lint_src(tmp_path, BAD_DONATED)) == \
            ["donated-after-call"]

    def test_clean_on_rebinding(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_DONATED) == []


# ---------------------------------------------------------------------------
# rule 3: jit-in-loop
# ---------------------------------------------------------------------------

BAD_JIT_LOOP = """
    import jax

    def sweep(fns, x):
        outs = []
        for fn in fns:
            outs.append(jax.jit(fn)(x))      # re-jits every iteration
        return outs

    def aot_sweep(fn, xs):
        outs = []
        for x in xs:
            exe = fn.lower(x).compile()      # re-compiles every iteration
            outs.append(exe(x))
        return outs
"""

CLEAN_JIT_FACTORY = """
    import jax

    def make_fn(cfg):
        def fn(x):
            return x * cfg.scale
        return jax.jit(fn)                   # factory: one jit per build

    def drive(fn, xs):
        outs = []
        for x in xs:
            outs.append(fn(x))               # dispatching in a loop is fine
        return outs
"""


class TestJitInLoop:
    def test_fires_on_jit_and_aot_compile_in_loop(self, tmp_path):
        assert rules_of(lint_src(tmp_path, BAD_JIT_LOOP)) == \
            ["jit-in-loop", "jit-in-loop"]

    def test_clean_on_factory_and_dispatch_loop(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_JIT_FACTORY) == []


# ---------------------------------------------------------------------------
# rule 4: host-sync (hot paths only)
# ---------------------------------------------------------------------------

BAD_HOST_SYNC = """
    import numpy as np
    import jax.numpy as jnp

    def epoch_body(state, x):
        loss = compute(state, x)
        if np.asarray(loss) > 0:             # implicit fetch per step
            state = clip(state)
        lr = float(jnp.mean(loss))           # scalarized device value
        return state, loss.item()            # and a blocking item()
"""


class TestHostSync:
    def test_fires_in_hot_path(self, tmp_path):
        got = rules_of(lint_src(tmp_path, BAD_HOST_SYNC, rel="hot/epoch.py",
                                hot_paths=["hot"]))
        assert got == ["host-sync"] * 3

    def test_silent_outside_hot_paths(self, tmp_path):
        assert lint_src(tmp_path, BAD_HOST_SYNC, rel="driver/main.py",
                        hot_paths=["hot"]) == []

    def test_clean_hot_path_code(self, tmp_path):
        clean = """
            import jax.numpy as jnp

            def epoch_body(state, x):
                loss = compute(state, x)
                scale = float(x.shape[0])    # python int — not a sync
                return state, loss / scale
        """
        assert lint_src(tmp_path, clean, rel="hot/epoch.py",
                        hot_paths=["hot"]) == []


# ---------------------------------------------------------------------------
# rule 5: nonhashable-static
# ---------------------------------------------------------------------------

BAD_STATIC = """
    import jax

    f = jax.jit(_impl, static_argnums=(1,))
    g = jax.jit(_impl2, static_argnames=("layers",))

    def call(x):
        a = f(x, [16, 16])                   # list at a static position
        b = g(x, layers=[16, 16])            # list for a static name
        return a + b
"""

CLEAN_STATIC = """
    import jax

    f = jax.jit(_impl, static_argnums=(1,))
    g = jax.jit(_impl2, static_argnames=("layers",))

    def call(x):
        a = f(x, (16, 16))                   # tuples hash
        b = g(x, layers=(16, 16))
        return a + b
"""


class TestNonHashableStatic:
    def test_fires_on_list_at_static_position(self, tmp_path):
        assert rules_of(lint_src(tmp_path, BAD_STATIC)) == \
            ["nonhashable-static"] * 2

    def test_clean_on_tuples(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_STATIC) == []


# ---------------------------------------------------------------------------
# rule 6: dtype-promotion
# ---------------------------------------------------------------------------

BAD_DTYPE = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def widen(x):
        jax.config.update("jax_enable_x64", True)
        a = jnp.asarray(x, dtype=jnp.float64)
        b = np.zeros(3, dtype="float64")
        c = jnp.zeros(3, dtype=float)
        return a, b, c
"""

CLEAN_DTYPE = """
    import jax.numpy as jnp
    import numpy as np

    def keep(x):
        a = jnp.asarray(x, dtype=jnp.bfloat16)
        b = np.zeros(3, dtype=np.float32)
        return a, b
"""


class TestDtypePromotion:
    def test_fires_on_f64_and_x64(self, tmp_path):
        got = rules_of(lint_src(tmp_path, BAD_DTYPE))
        assert got == ["dtype-promotion"] * 4

    def test_clean_on_bf16_f32(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_DTYPE) == []


# ---------------------------------------------------------------------------
# rule 7: cache-setup
# ---------------------------------------------------------------------------

BAD_ENTRY = """
    def main():
        run_everything()
"""

GOOD_ENTRY = """
    from iwae_replication_project_tpu.utils.compile_cache import (
        setup_persistent_cache)

    def main():
        setup_persistent_cache(None, base_dir="ckpt")
        run_everything()
"""

BAD_HAND_ROLLED = """
    import jax

    def main():
        jax.config.update("jax_compilation_cache_dir", "/tmp/cache")
"""


class TestCacheSetup:
    def test_fires_on_entry_point_without_setup(self, tmp_path):
        got = lint_src(tmp_path, BAD_ENTRY, rel="run.py",
                       entry_points=["run.py"])
        assert rules_of(got) == ["cache-setup"]

    def test_clean_entry_point(self, tmp_path):
        assert lint_src(tmp_path, GOOD_ENTRY, rel="run.py",
                        entry_points=["run.py"]) == []

    def test_fires_on_hand_rolled_cache_config(self, tmp_path):
        got = lint_src(tmp_path, BAD_HAND_ROLLED, rel="pkg/util.py")
        assert rules_of(got) == ["cache-setup"]

    def test_owner_module_is_exempt(self, tmp_path):
        assert lint_src(tmp_path, BAD_HAND_ROLLED, rel="pkg/owner.py",
                        cache_owners=["pkg/owner.py"]) == []


# ---------------------------------------------------------------------------
# rule 8: fragile-import
# ---------------------------------------------------------------------------

BAD_IMPORTS = """
    from jax import shard_map
    from jax.experimental.shard_map import shard_map as sm
    import jax.experimental.host_callback
"""

CLEAN_IMPORTS = """
    import jax
    from jax.sharding import Mesh, PartitionSpec
    from iwae_replication_project_tpu.parallel.mesh import shard_map
"""


class TestFragileImport:
    def test_fires_on_direct_fragile_imports(self, tmp_path):
        assert rules_of(lint_src(tmp_path, BAD_IMPORTS)) == \
            ["fragile-import"] * 3

    def test_clean_via_shim(self, tmp_path):
        assert lint_src(tmp_path, CLEAN_IMPORTS) == []

    def test_shim_file_is_exempt(self, tmp_path):
        assert lint_src(tmp_path, BAD_IMPORTS, rel="pkg/mesh.py",
                        import_shims=["pkg/mesh.py"]) == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class TestSuppression:
    def test_justified_line_suppression_silences(self, tmp_path):
        src = BAD_KEY_TWO_CONSUMERS.replace(
            "# second consumer, same key",
            "# iwaelint: disable=key-reuse -- antithetic pair by design")
        assert lint_src(tmp_path, src) == []

    def test_bare_suppression_is_its_own_finding(self, tmp_path):
        src = BAD_KEY_TWO_CONSUMERS.replace(
            "# second consumer, same key", "# iwaelint: disable=key-reuse")
        assert rules_of(lint_src(tmp_path, src)) == [BARE_SUPPRESSION]

    def test_file_scope_suppression(self, tmp_path):
        src = ("# iwaelint: disable-file=fragile-import -- compat probe "
               "module\n" + textwrap.dedent(BAD_IMPORTS))
        assert lint_src(tmp_path, src) == []

    def test_suppression_is_per_rule(self, tmp_path):
        # suppressing an unrelated rule must not silence the real finding
        src = BAD_KEY_TWO_CONSUMERS.replace(
            "# second consumer, same key",
            "# iwaelint: disable=jit-in-loop -- wrong rule on purpose")
        assert "key-reuse" in rules_of(lint_src(tmp_path, src))


# ---------------------------------------------------------------------------
# useless-suppression (meta-rule keeping the suppression inventory honest)
# ---------------------------------------------------------------------------

class TestUselessSuppression:
    def test_fires_when_the_rule_does_not_fire(self, tmp_path):
        src = """
            import jax

            def fine(key, shape):
                return jax.random.normal(key, shape)  # iwaelint: disable=key-reuse -- leftover from a refactor
        """
        assert rules_of(lint_src(tmp_path, src)) == [USELESS_SUPPRESSION]

    def test_silent_when_the_suppression_is_live(self, tmp_path):
        src = BAD_KEY_TWO_CONSUMERS.replace(
            "# second consumer, same key",
            "# iwaelint: disable=key-reuse -- antithetic pair by design")
        assert lint_src(tmp_path, src) == []

    def test_mixed_tokens_flag_only_the_dead_one(self, tmp_path):
        src = BAD_KEY_TWO_CONSUMERS.replace(
            "# second consumer, same key",
            "# iwaelint: disable=key-reuse,jit-in-loop -- key pair is "
            "antithetic")
        (f,) = lint_src(tmp_path, src)
        assert f.rule == USELESS_SUPPRESSION
        assert "jit-in-loop" in f.message

    def test_stale_file_scope_suppression_fires(self, tmp_path):
        src = ("# iwaelint: disable-file=fragile-import -- nothing fragile "
               "left here\nx = 1\n")
        got = lint_src(tmp_path, src)
        assert rules_of(got) == [USELESS_SUPPRESSION]
        assert "file" in got[0].message

    def test_not_judged_for_unselected_rules(self, tmp_path):
        # a --select subset must not condemn the other rules' suppressions
        src = """
            import jax

            def fine(key, shape):
                return jax.random.normal(key, shape)  # iwaelint: disable=key-reuse -- judged only when key-reuse runs
        """
        assert lint_src(tmp_path, src, select=["jit-in-loop"]) == []

    def test_unknown_rule_token_fires_even_under_select(self, tmp_path):
        # a misspelled/removed rule name can never become live, so it is
        # reported unconditionally — no run subset can vindicate it
        src = """
            import jax

            def fine(key, shape):
                return jax.random.normal(key, shape)  # iwaelint: disable=key-resue -- typo'd rule name
        """
        got = lint_src(tmp_path, src, select=["jit-in-loop"])
        assert rules_of(got) == [USELESS_SUPPRESSION]
        assert "unknown rule 'key-resue'" in got[0].message

    def test_useless_suppression_is_not_suppressible(self, tmp_path):
        src = """
            import jax

            def fine(key, shape):
                return jax.random.normal(key, shape)  # iwaelint: disable=key-reuse,useless-suppression -- trying to silence the meta-rule
        """
        assert USELESS_SUPPRESSION in rules_of(lint_src(tmp_path, src))


# ---------------------------------------------------------------------------
# CLI + self-lint
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, *args, cwd=REPO):
        return subprocess.run(
            [sys.executable, "-m", "iwae_replication_project_tpu.analysis",
             *args], cwd=cwd, capture_output=True, text=True)

    def test_bad_file_exits_1_with_json(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("from jax.experimental.shard_map import shard_map\n")
        r = self._run("--format", "json", str(bad))
        assert r.returncode == 1, r.stderr
        payload = json.loads(r.stdout)
        assert payload["total"] == 1
        assert payload["counts"] == {"fragile-import": 1}

    def test_unknown_path_exits_2(self):
        r = self._run("definitely/not/a/path.py")
        assert r.returncode == 2
        assert "error" in r.stderr

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in ("key-reuse", "donated-after-call", "jit-in-loop",
                     "host-sync", "nonhashable-static", "dtype-promotion",
                     "cache-setup", "fragile-import"):
            assert rule in r.stdout

    def test_self_lint_clean(self):
        """THE acceptance gate: the CI invocation over the production tree
        exits 0 (scripts/check.py stage 1)."""
        r = self._run("iwae_replication_project_tpu", "scripts", "bench.py",
                      "__graft_entry__.py")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "clean" in r.stdout
