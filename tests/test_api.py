"""API-facade tests: backend dispatch, reference method surface, cross-backend
parity (JAX vs torch eager oracle), weight save/load."""

import jax
import numpy as np
import pytest

from iwae_replication_project_tpu.api import FlexibleModel

ARCH = dict(n_hidden_encoder=[16], n_hidden_decoder=[16],
            n_latent_encoder=[4], n_latent_decoder=[12])


def make_x(n=8, d=12, seed=1):
    return (np.random.RandomState(seed).rand(n, d) > 0.5).astype(np.float32)


def build(backend="jax", **kw):
    args = dict(ARCH)
    args.update(kw)
    bias = args.pop("dataset_bias", None)
    return FlexibleModel(args.pop("n_hidden_encoder"), args.pop("n_hidden_decoder"),
                         args.pop("n_latent_encoder"), args.pop("n_latent_decoder"),
                         dataset_bias=bias, backend=backend, **args)


class TestDispatch:
    def test_jax_backend_class(self):
        from iwae_replication_project_tpu.backends.jax_backend import JaxFlexibleModel
        assert isinstance(build("jax"), JaxFlexibleModel)

    def test_torch_backend_class(self):
        from iwae_replication_project_tpu.backends.torch_ref import TorchFlexibleModel
        assert isinstance(build("torch"), TorchFlexibleModel)

    @pytest.mark.slow
    def test_tf2_backend_dispatch_or_gate(self):
        """With TF importable, backend='tf2' dispatches to the real TF2
        implementation (tests/test_tf2_backend.py covers it); without TF it
        raises the guidance ImportError."""
        try:
            import tensorflow  # noqa: F401
        except ImportError:
            with pytest.raises(ImportError):
                build("tf2")
        else:
            from iwae_replication_project_tpu.backends.tf2_ref import (
                TF2FlexibleModel)
            assert isinstance(build("tf2"), TF2FlexibleModel)

    def test_unknown_backend(self):
        with pytest.raises(ValueError):
            build("mxnet")

    @pytest.mark.parametrize("backend", ["jax", "torch"])
    def test_typo_kwargs_rejected(self, backend):
        with pytest.raises(TypeError):
            build(backend, loss_fuction="IWAE")  # codespell:ignore

    def test_bias_from_pixel_means(self):
        means = np.clip(np.random.RandomState(0).rand(12), 0.05, 0.95).astype(np.float32)
        with pytest.warns(DeprecationWarning, match="pixel_means"):
            m = build("jax", dataset_bias=means).compile()
        got = np.asarray(m.params["out"]["out"]["b"])
        np.testing.assert_allclose(1 / (1 + np.exp(-got)), means, rtol=1e-4)

    def test_explicit_pixel_means_kwarg(self):
        means = np.clip(np.random.RandomState(0).rand(12), 0.05, 0.95).astype(np.float32)
        m = build("jax", pixel_means=means).compile()
        got = np.asarray(m.params["out"]["out"]["b"])
        np.testing.assert_allclose(1 / (1 + np.exp(-got)), means, rtol=1e-4)

    def test_explicit_bias_kwarg_no_transform(self):
        """bias= installs the vector verbatim — including values in [0,1],
        which the deprecated dataset_bias range heuristic would have
        double-transformed through the logit (VERDICT r4 Weak #4)."""
        bias = np.linspace(0.1, 0.9, 12).astype(np.float32)  # inside [0,1]!
        m = build("jax", bias=bias).compile()
        got = np.asarray(m.params["out"]["out"]["b"])
        np.testing.assert_allclose(got, bias, rtol=1e-6)

    def test_bias_kwargs_conflict(self):
        v = np.full(12, 0.5, np.float32)
        with pytest.raises(ValueError, match="not both"):
            build("jax", pixel_means=v, bias=v)
        with pytest.raises(ValueError, match="replace dataset_bias"):
            build("jax", dataset_bias=v, bias=v)


class TestJaxSurface:
    @pytest.fixture(scope="class")
    def model(self):
        return build("jax", loss_function="IWAE", k=8, seed=0).compile()

    def test_requires_compile(self):
        m = build("jax")
        with pytest.raises(RuntimeError):
            m.get_L_k(make_x(), 4)

    def test_reference_method_surface(self, model):
        x = make_x()
        assert model.get_log_weights(x, 4).shape == (4, 8)
        for val in (model.get_L(x, 16), model.get_L_k(x, 8), model.get_L_V1(x, 8),
                    model.get_L_alpha(x, 8, 0.5), model.get_L_power_p(x, 8, 2.0),
                    model.get_L_median(x, 8), model.get_L_CIWAE(x, 8, 0.3),
                    model.get_L_MIWAE(x, 4, 2), model.get_NLL(x, k=20, chunk=10),
                    model.get_E_qhIx_log_pxIh(x, 8), model.get_Dkl_qhIx_ph(x, 8),
                    model.get_reconstruction_loss(x)):
            assert np.isfinite(float(val))

    def test_train_step_and_fit(self, model):
        x = make_x(32)
        r = model.train_step(x[:8])
        assert "IWAE" in r and np.isfinite(r["IWAE"])
        hist = model.fit(x, epochs=2, batch_size=8)
        assert len(hist["loss"]) == 2

    def test_activity_and_stats(self, model):
        x = make_x(20)
        variances, eigvals = model.get_levels_of_units_activity(x, 20)
        masks, n_act, n_pca = model.get_active_units(variances, eigvals)
        assert len(n_act) == 1
        res, res2 = model.get_training_statistics(x, k=4, batch_size=10,
                                                  nll_k=20, nll_chunk=10,
                                                  activity_samples=20)
        assert np.isfinite(res["NLL"])

    def test_generate(self, model):
        gen = model.generate(5)
        assert gen.shape == (5, 12)
        g = np.asarray(gen)
        assert np.all((g > 0) & (g < 1))

    def test_save_load_weights(self, model, tmp_path):
        x = make_x()
        path = str(tmp_path / "w")
        model.save_weights(path)
        before = model.get_log_weights(x, 1)  # noqa: F841 - exercises eval path
        other = build("jax", loss_function="IWAE", k=8, seed=123).compile()
        other.load_weights(path)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     model.params, other.params)

    def test_save_load_weights_cross_backend(self, model, tmp_path):
        """One payload format for every backend: a jax checkpoint loads into
        the torch oracle bit-for-bit and round-trips back."""
        path = str(tmp_path / "w")
        model.save_weights(path)
        tm = build("torch", loss_function="IWAE", k=8, seed=9).compile()
        tm.load_weights(path)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     model.params, tm._weights_pytree())
        back = str(tmp_path / "w2")
        tm.save_weights(back)
        other = build("jax", loss_function="IWAE", k=8, seed=123).compile()
        other.load_weights(back)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     model.params, other.params)

    def test_load_weights_rejects_mismatched_architecture(self, model, tmp_path):
        """A checkpoint from a different architecture must refuse to load,
        naming both architectures — even when the leaf COUNT happens to match
        (same-leaf-count mismatches would otherwise silently load transposed /
        mis-assigned weights; VERDICT r3 Weak #4)."""
        path = str(tmp_path / "w")
        model.save_weights(path)
        # same number of layers/leaves, different widths
        other = build("jax", n_hidden_encoder=[12], n_hidden_decoder=[12],
                      n_latent_encoder=[6], n_latent_decoder=[12],
                      loss_function="IWAE", k=8).compile()
        with pytest.raises(ValueError) as ei:
            other.load_weights(path)
        msg = str(ei.value)
        assert "[16]" in msg and "[12]" in msg  # names both architectures
        # different depth (different treedef) also refuses
        deeper = build("jax", n_hidden_encoder=[16, 8], n_latent_encoder=[4, 2],
                       n_hidden_decoder=[8, 16], n_latent_decoder=[4, 12],
                       loss_function="IWAE", k=8).compile()
        with pytest.raises(ValueError):
            deeper.load_weights(path)

    def test_save_load_weights_pkl_suffix_roundtrip(self, model, tmp_path):
        """Old-API callers passed explicit .pkl paths; the pair
        save_weights('x.pkl') / load_weights('x.pkl') must keep round-tripping
        (save now writes x.npz, load follows)."""
        path = str(tmp_path / "w.pkl")
        # a stale legacy file at the exact path must not shadow the fresh
        # save — but it is preserved as <stem>.pkl.bak with a warning, not
        # silently deleted (it may be the only copy of other weights)
        (tmp_path / "w.pkl").write_bytes(b"stale")
        with pytest.warns(UserWarning, match=r"\.bak"):
            model.save_weights(path)
        assert (tmp_path / "w.npz").exists()
        assert not (tmp_path / "w.pkl").exists()
        assert (tmp_path / "w.pkl.bak").read_bytes() == b"stale"
        # ... and the BARE-path save spelling must clear the stale sibling
        # too: otherwise load_weights('w.pkl') would resurrect it
        (tmp_path / "w.pkl").write_bytes(b"stale2")
        with pytest.warns(UserWarning, match=r"\.bak"):
            model.save_weights(str(tmp_path / "w"))
        assert not (tmp_path / "w.pkl").exists()
        assert (tmp_path / "w.pkl.bak").read_bytes() == b"stale2"
        other = build("jax", loss_function="IWAE", k=8, seed=123).compile()
        other.load_weights(path)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     model.params, other.params)

    def test_load_weights_legacy_pickle(self, model, tmp_path):
        """Rounds ≤4 wrote pickle payloads; they still load (with a warning),
        and a legacy payload from a different architecture still refuses via
        the version-stable arch-dict compare (not str(treedef))."""
        import pickle
        flat, treedef = jax.tree.flatten(model._weights_pytree())
        payload = {"arrays": [np.asarray(a) for a in flat],
                   "treedef": str(treedef), "arch": model._arch_descr()}
        path = str(tmp_path / "legacy.pkl")
        with open(path, "wb") as f:
            pickle.dump(payload, f)
        other = build("jax", loss_function="IWAE", k=8, seed=123).compile()
        with pytest.warns(UserWarning, match="legacy pickle"):
            other.load_weights(path)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), model.params, other.params)
        # wrong arch in the payload -> refuse, even with matching leaf count
        payload["arch"] = {"n_hidden_encoder": [99]}
        with open(path, "wb") as f:
            pickle.dump(payload, f)
        with pytest.raises(ValueError, match="architecture lists differ"), \
                pytest.warns(UserWarning):
            other.load_weights(path)

    def test_tensorboard_log(self, model, tmp_path):
        import glob
        model.tensorboard_log({"VAE": -90.0, "IWAE": -88.0}, epoch_n=5,
                              logdir=str(tmp_path))
        files = glob.glob(str(tmp_path) + "/**/events.out.tfevents.*", recursive=True)
        assert files, "no tensorboard event file written"
        assert glob.glob(str(tmp_path) + "/**/metrics.jsonl", recursive=True)


class TestModifiedGradientOracle:
    """Per-leaf gradient parity for the modified-gradient estimators: the JAX
    hand-rolled VJP cotangents (objectives/gradients.py:64-109) vs the torch
    oracle's autograd-on-surrogate derivation, on tied weights AND the same
    realized latent draws (the torch side replays the JAX samples through its
    own reparameterization, so both backends differentiate the same graph).
    VERDICT r3 Missing #4: these estimators previously had no independent
    cross-implementation check."""

    ARCH2L = dict(n_hidden_encoder=[8, 6], n_latent_encoder=[5, 3],
                  n_hidden_decoder=[6, 8], n_latent_decoder=[5, 12])

    @pytest.mark.parametrize("name,k2", [("STL", 1), ("DReG", 1), ("PIWAE", 3)])
    def test_per_leaf_gradient_parity(self, name, k2):
        from iwae_replication_project_tpu.models import iwae as model
        from iwae_replication_project_tpu.models.iwae import (
            ModelConfig, init_params)
        from iwae_replication_project_tpu.objectives.estimators import (
            ObjectiveSpec)
        from iwae_replication_project_tpu.objectives.gradients import (
            objective_value_and_grad)

        cfg = ModelConfig(n_hidden_enc=(8, 6), n_latent_enc=(5, 3),
                          n_hidden_dec=(6, 8), n_latent_dec=(5, 12), x_dim=12)
        params = init_params(jax.random.PRNGKey(0), cfg)
        x = make_x(4, 12, seed=2)
        k = 6
        spec = ObjectiveSpec(name, k=k, k2=k2)
        dkey = jax.random.PRNGKey(7)
        jbound, jgrads = objective_value_and_grad(spec, params, cfg, dkey,
                                                  jax.numpy.asarray(x))
        # the latents the JAX estimator actually sampled (stop_q_score only
        # changes the gradient graph, not the draws)
        _, aux = model.log_weights_and_aux(params, cfg, dkey,
                                           jax.numpy.asarray(x), k)
        h_fixed = [np.asarray(h) for h in aux["h"]]

        tm = build("torch", **self.ARCH2L).compile()
        tm.load_jax_params(params)
        tbound, ttree = tm.estimator_gradients_as_jax_tree(
            x, name, k, k2=k2, h_fixed=h_fixed)

        np.testing.assert_allclose(float(jbound), tbound, rtol=1e-5, atol=1e-6)
        jleaves, jdef = jax.tree.flatten(jgrads)
        tleaves, tdef = jax.tree.flatten(ttree)
        assert str(jdef) == str(tdef)
        assert any(np.abs(np.asarray(g)).max() > 1e-8 for g in jleaves)
        for jg, tg in zip(jleaves, tleaves):
            np.testing.assert_allclose(np.asarray(jg), tg, rtol=2e-3,
                                       atol=2e-6)

    def test_dreg_encoder_grad_differs_from_stl(self):
        """Sanity on the oracle itself: DReG (w~^2 cotangent) and STL (w~) must
        disagree on encoder grads while agreeing on decoder grads for the same
        replayed draws."""
        tm = build("torch", **self.ARCH2L).compile()
        x = make_x(4, 12, seed=3)
        torch_seed = 13
        import torch
        torch.manual_seed(torch_seed)
        h, _, _ = tm._encode(tm._flatten(torch.from_numpy(x)), 6)
        h_fixed = [hi.detach().numpy() for hi in h]
        _, g_stl = tm.estimator_gradients_as_jax_tree(x, "STL", 6,
                                                      h_fixed=h_fixed)
        _, g_dreg = tm.estimator_gradients_as_jax_tree(x, "DReG", 6,
                                                       h_fixed=h_fixed)
        enc_diff = np.abs(g_stl["enc"][0]["mu"]["w"]
                          - g_dreg["enc"][0]["mu"]["w"]).max()
        dec_diff = np.abs(g_stl["out"]["out"]["w"]
                          - g_dreg["out"]["out"]["w"]).max()
        assert enc_diff > 1e-7
        assert dec_diff < 1e-9

    def test_torch_vae_v1_rejects_multilayer(self):
        """VAE_V1's analytic KL is single-stochastic-layer only — the torch
        oracle must refuse L>=2 like the JAX path (estimators.py) instead of
        silently returning a wrong bound."""
        tm = build("torch", loss_function="IWAE", k=4,
                   n_hidden_encoder=[10, 8], n_latent_encoder=[5, 3],
                   n_hidden_decoder=[8, 10], n_latent_decoder=[5, 12]).compile()
        with pytest.raises(ValueError, match="single-stochastic-layer"):
            tm.get_L_V1(make_x(8), 4)

    def test_torch_tensorboard_log(self, tmp_path):
        """tensorboard_log is part of the method-for-method surface on every
        backend (shared on the base facade)."""
        import glob
        tm = build("torch", loss_function="IWAE", k=4).compile()
        tm.tensorboard_log({"VAE": -90.0, "IWAE": -88.0}, epoch_n=1,
                           logdir=str(tmp_path))
        assert glob.glob(str(tmp_path) + "/**/metrics.jsonl", recursive=True)

    def test_torch_fit_epochs_compose(self):
        """fit(epochs=2) == fit(1); fit(1) on the torch oracle: the shuffle
        stream is driven by a carried per-epoch counter, not the per-batch
        `epoch` counter (VERDICT r3 weak #5)."""
        x = make_x(24, seed=11)
        a = build("torch", loss_function="IWAE", k=4, seed=5).compile()
        ha = a.fit(x, epochs=2, batch_size=8)["loss"]
        b = build("torch", loss_function="IWAE", k=4, seed=5).compile()
        hb = (b.fit(x, epochs=1, batch_size=8)["loss"]
              + b.fit(x, epochs=1, batch_size=8)["loss"])
        np.testing.assert_allclose(ha, hb, rtol=1e-6)

    @pytest.mark.parametrize("name", ["DReG", "STL", "PIWAE"])
    def test_torch_training_with_modified_estimators(self, name):
        """The torch backend can now *train* with these objectives (fresh
        sampled graph, optimizer step) — parity with the JAX train path."""
        tm = build("torch", loss_function=name, k=6, k2=2 if name == "PIWAE"
                   else 1, **self.ARCH2L).compile()
        x = make_x(16, 12, seed=4)
        hist = tm.fit(x, epochs=2, batch_size=8)
        assert len(hist["loss"]) == 2
        assert all(np.isfinite(v) for v in hist["loss"])


class TestCrossBackendParity:
    """The torch oracle and the JAX path must agree on every bound when fed
    the SAME log-weights (estimator parity) and statistically on their own
    samples (model parity)."""

    def test_estimator_parity_on_shared_weights(self):
        import torch
        from iwae_replication_project_tpu.objectives import (
            ObjectiveSpec, bound_from_log_weights)
        lw_np = (np.random.RandomState(0).randn(12, 5) * 3).astype(np.float32)
        tm = build("torch").compile()
        jlw = jax.numpy.asarray(lw_np)
        tlw = torch.from_numpy(lw_np)
        pairs = [
            (bound_from_log_weights(ObjectiveSpec("IWAE", k=12), jlw), tm._iwae(tlw)),
            (bound_from_log_weights(ObjectiveSpec("VAE", k=12), jlw), tlw.mean()),
            (bound_from_log_weights(ObjectiveSpec("L_power_p", k=12, p=2.0), jlw),
             tm._iwae(2.0 * tlw) / 2.0),
            (bound_from_log_weights(ObjectiveSpec("MIWAE", k=12, k2=3), jlw),
             (torch.log(torch.exp(tlw.reshape(3, 4, 5)
                                  - tlw.reshape(3, 4, 5).max(1, keepdim=True).values)
                        .mean(1))
              + tlw.reshape(3, 4, 5).max(1).values).mean()),
        ]
        for jval, tval in pairs:
            np.testing.assert_allclose(float(jval), float(tval), rtol=1e-5)

    @pytest.mark.slow
    def test_model_parity_weight_tied(self):
        """THE load-bearing cross-backend check: copy the JAX params into the
        torch oracle, then both backends' bounds are MC estimates of the SAME
        quantity — assert agreement within a few standard errors of the MC
        noise. A tenths-of-a-nat systematic bias (clamp, floor, log-prob,
        bias-init discrepancy) fails this; independent-init corridors can't
        see it."""
        x = make_x(64, seed=3)
        bias = np.clip(x.mean(0), 0.05, 0.95)
        jm = build("jax", pixel_means=bias, loss_function="VAE", k=8, seed=0).compile()
        jm.fit(x, epochs=10, batch_size=16)
        tm = build("torch", pixel_means=bias, loss_function="VAE", k=8,
                   seed=0).compile()
        tm.load_jax_params(jm.params)

        # VAE bound: n_rep independent k=64 estimates per backend
        jv = np.array([float(jm.get_L(x, 64)) for _ in range(8)])
        tv = np.array([float(tm.get_L(x, 64)) for _ in range(8)])
        se = np.sqrt(jv.var(ddof=1) / len(jv) + tv.var(ddof=1) / len(tv))
        assert abs(jv.mean() - tv.mean()) < max(4 * se, 0.02), (
            jv.mean(), tv.mean(), se)

        # IWAE/NLL at larger k (lower variance): same corridor
        jn = np.array([float(jm.get_NLL(x, k=400, chunk=100)) for _ in range(4)])
        tn = np.array([float(tm.get_NLL(x, k=400, chunk=100)) for _ in range(4)])
        se = np.sqrt(jn.var(ddof=1) / len(jn) + tn.var(ddof=1) / len(tn))
        assert abs(jn.mean() - tn.mean()) < max(4 * se, 0.02), (
            jn.mean(), tn.mean(), se)

    @pytest.mark.slow
    def test_torch_eval_surface_parity_weight_tied(self):
        """The newly-completed torch eval surface (activity, pruned NLL,
        reconstruction, generation, statistics driver) agrees with the JAX
        path on tied weights."""
        x = make_x(32, seed=5)
        bias = np.clip(x.mean(0), 0.05, 0.95)
        jm = build("jax", pixel_means=bias, loss_function="IWAE", k=4,
                   seed=1).compile()
        tm = build("torch", pixel_means=bias, loss_function="IWAE", k=4,
                   seed=1).compile()
        tm.load_jax_params(jm.params)

        jv, je = jm.get_levels_of_units_activity(x, 256)
        tv, te = tm.get_levels_of_units_activity(x, 256)
        for a, b in zip(jv, tv):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=0.05, rtol=0.5)
        _, jn, jp = jm.get_active_units(jv, je)
        _, tn, tp = tm.get_active_units(tv, te)
        assert jn == tn and jp == tp

        jr = float(jm.get_reconstruction_loss(x))
        tr = float(tm.get_reconstruction_loss(x))
        assert abs(jr - tr) / max(abs(jr), 1.0) < 0.1, (jr, tr)

        assert tm.generate(5).shape == (5, x.shape[1])
        assert np.asarray(jm.generate(5)).shape == (5, x.shape[1])

        # statistics driver: repeated MC estimates per backend, SE-scaled
        # corridor (same form as test_model_parity_weight_tied — a
        # tenths-of-a-nat systematic torch/JAX bias in the replication-target
        # metrics must fail, VERDICT r2 weak #6)
        n_rep = 4
        jreps, treps = [], []
        for _ in range(n_rep):
            jres, jres2 = jm.get_training_statistics(x, 4, batch_size=16,
                                                     nll_k=64, nll_chunk=16,
                                                     activity_samples=128)
            tres, tres2 = tm.get_training_statistics(x, 4, batch_size=16,
                                                     nll_k=64, nll_chunk=16,
                                                     activity_samples=128)
            jreps.append(jres)
            treps.append(tres)
        assert set(jres) == set(tres)
        for key in ("VAE", "IWAE", "NLL"):
            jv = np.array([r[key] for r in jreps])
            tv = np.array([r[key] for r in treps])
            se = np.sqrt(jv.var(ddof=1) / n_rep + tv.var(ddof=1) / n_rep)
            assert abs(jv.mean() - tv.mean()) < max(4 * se, 0.02), (
                key, jv.mean(), tv.mean(), se)
        assert (jres2["number_of_active_units"]
                == tres2["number_of_active_units"])
