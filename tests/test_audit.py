"""Fixture tests for the jaxpr-level program auditor (analysis/audit/).

Per ISSUE 7's acceptance bar, every pass is proven LIVE by a fixture program
seeding its hazard — a donation bug, an unmasked-padding reduction, a
hot-path host transfer, a cache-fragmenting signature — plus a lock-order
inversion for the concurrency checker; each hazard's discharged twin is
proven clean; the real program suite audits clean end-to-end (the same
contract scripts/check.py gates on); and the golden jaxpr signatures pin
the serving programs and the train step against silent program drift.
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import pytest
from jax import lax

from iwae_replication_project_tpu.analysis import LintConfig, lint_paths
from iwae_replication_project_tpu.analysis.audit import (
    BARE_WAIVER,
    AuditEnv,
    AuditProgram,
    all_passes,
    build_programs,
    run_audit,
    select_passes,
    signature,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "jaxpr_signatures.json")

#: a fake env that isolates jaxpr-level checks from this host's backend and
#: cache configuration (no registry -> no cross-test registry bleed)
ENV_TPU = AuditEnv(backend="tpu", cache_dir="/tmp/cache")
ENV_CPU_CACHE = AuditEnv(backend="cpu", cache_dir="/tmp/cache")


def prog(name, fn, *args, taints=None, sig_args=None, hot=True, waivers=None):
    return AuditProgram(name=name, jaxpr=jax.make_jaxpr(fn)(*args),
                        taints=taints or {}, sig_args=sig_args, hot=hot,
                        waivers=waivers or {})


def audit(p, pass_name, env=ENV_TPU):
    return run_audit([p], select_passes([pass_name]), env)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# framework
# ---------------------------------------------------------------------------

class TestFramework:
    def test_four_passes_registered(self):
        assert set(all_passes()) >= {"donation-safety", "padding-taint",
                                     "host-transfer", "recompile-cardinality"}

    def test_unknown_pass_raises(self):
        with pytest.raises(ValueError, match="unknown pass"):
            select_passes(["no-such-pass"])

    def test_unknown_program_raises(self):
        with pytest.raises(ValueError, match="unknown program"):
            build_programs(["no-such-program"])

    def test_waiver_silences_with_justification(self):
        x = jnp.zeros((8, 4))
        p = prog("waived", lambda x: jnp.sum(jnp.pad(x, ((0, 3), (0, 0)))),
                 x, waivers={"padding-taint": "zero padding under plain sum "
                                              "adds exact zeros"})
        assert audit(p, "padding-taint") == []

    def test_bare_waiver_is_its_own_finding(self):
        x = jnp.zeros((8, 4))
        p = prog("bare", lambda x: jnp.sum(jnp.pad(x, ((0, 3), (0, 0)))),
                 x, waivers={"padding-taint": ""})
        got = rules_of(audit(p, "padding-taint"))
        assert BARE_WAIVER in got and "padding-taint" in got


# ---------------------------------------------------------------------------
# pass 1: donation-safety
# ---------------------------------------------------------------------------

class TestDonationSafety:
    def test_fires_on_donated_but_unconsumed_input(self):
        f = jax.jit(lambda a, b: b * 2.0, donate_argnums=(0,))
        p = prog("don_unused", f, jnp.zeros((3,)), jnp.zeros((3,)))
        got = audit(p, "donation-safety")
        assert rules_of(got) == ["donation-safety"]
        assert "never consumed" in got[0].message

    def test_clean_when_every_donated_input_is_consumed(self):
        f = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
        p = prog("don_used", f, jnp.zeros((3,)), jnp.zeros((3,)))
        assert audit(p, "donation-safety") == []

    def test_fires_on_donation_with_cpu_persistent_cache(self):
        # the RESULTS.md §5 hazard class, statically: donation + warm cache
        # on the CPU backend corrupts cache-deserialized executables
        f = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
        p = prog("don_cache", f, jnp.zeros((3,)), jnp.zeros((3,)))
        got = audit(p, "donation-safety", env=ENV_CPU_CACHE)
        assert rules_of(got) == ["donation-safety"]
        assert "donation_safe" in got[0].message

    def test_clean_without_donation_even_on_cpu_cache(self):
        p = prog("no_don", jax.jit(lambda a: a * 2), jnp.zeros((3,)))
        assert audit(p, "donation-safety", env=ENV_CPU_CACHE) == []


# ---------------------------------------------------------------------------
# pass 2: padding-taint
# ---------------------------------------------------------------------------

class TestPaddingTaint:
    X = jnp.zeros((8, 4))

    def test_fires_on_unmasked_logsumexp_over_padded_rows(self):
        # THE IWAE hazard: exp(0)=1 from a padded row silently biases the
        # k-sample bound (no NaN, no crash — just a wrong number)
        p = prog("bad_lse",
                 lambda x: jnp.mean(jax.scipy.special.logsumexp(x, axis=0)),
                 self.X, taints={0: {0: 5}})
        got = audit(p, "padding-taint")
        assert got and all(f.rule == "padding-taint" for f in got)

    def test_clean_when_iota_mask_discharges_the_taint(self):
        def masked(x):
            keep = lax.broadcasted_iota(jnp.int32, (8, 1), 0) < 5
            return jnp.mean(jax.scipy.special.logsumexp(
                jnp.where(keep, x, -jnp.inf), axis=0))
        p = prog("good_lse", masked, self.X, taints={0: {0: 5}})
        assert audit(p, "padding-taint") == []

    def test_pad_eqn_seeds_taint_without_declaration(self):
        # kernel-style internal padding needs no input declaration
        p = prog("pad_reduce", lambda x: jnp.sum(
            jax.scipy.special.logsumexp(jnp.pad(x, ((0, 3), (0, 0))),
                                        axis=0)), self.X)
        assert rules_of(audit(p, "padding-taint")) != []

    def test_slice_off_the_padding_discharges(self):
        # the pad -> compute -> out[:k] unpad idiom must prove clean
        p = prog("pad_slice", lambda x: jnp.sum(
            (jnp.pad(x, ((0, 3), (0, 0))) * 2)[:8], axis=0), self.X)
        assert audit(p, "padding-taint") == []

    def test_fires_on_contraction_over_padded_axis(self):
        p = prog("dot_contract", lambda x: x.T @ x, self.X,
                 taints={0: {0: 5}})
        got = audit(p, "padding-taint")
        assert got and "dot_general" in got[0].location

    def test_inverted_iota_mask_does_not_discharge(self):
        # polarity matters: this mask hands the PADDED rows the data
        # operand, so it must not be blessed like the correct idiom
        def inverted(x):
            drop = lax.broadcasted_iota(jnp.int32, (8, 1), 0) >= 5
            return jnp.mean(jax.scipy.special.logsumexp(
                jnp.where(drop, x, -jnp.inf), axis=0))
        p = prog("bad_mask", inverted, self.X, taints={0: {0: 5}})
        assert rules_of(audit(p, "padding-taint")) != []

    def test_uncompared_iota_does_not_discharge(self):
        # a raw iota that never went through a comparison proves nothing
        def bogus(x):
            raw = lax.broadcasted_iota(jnp.int32, (8, 1), 0).astype(bool)
            return jnp.sum(jnp.where(raw, x, 0.0), axis=0)
        p = prog("raw_iota", bogus, self.X, taints={0: {0: 5}})
        assert rules_of(audit(p, "padding-taint")) != []

    def test_wrong_boundary_literal_mask_does_not_discharge(self):
        # correctly polarized, wrong bound: iota < padded_size keeps every
        # padded row, so it must not be blessed like iota < real_extent
        def overwide(x):
            keep = lax.broadcasted_iota(jnp.int32, (8, 1), 0) < 8
            return jnp.mean(jax.scipy.special.logsumexp(
                jnp.where(keep, x, -jnp.inf), axis=0))
        p = prog("wide_mask", overwide, self.X, taints={0: {0: 5}})
        assert rules_of(audit(p, "padding-taint")) != []

    def test_traced_mask_bound_discharges_on_trust(self):
        # a traced bound cannot be compared statically: discharged (the
        # runtime parity pins' jurisdiction) and counted as unverified
        def masked(x, n):
            keep = lax.broadcasted_iota(jnp.int32, (8, 1), 0) < n
            return jnp.mean(jax.scipy.special.logsumexp(
                jnp.where(keep, x, -jnp.inf), axis=0))
        p = prog("traced_mask", masked, self.X, jnp.int32(5),
                 taints={0: {0: 5}})
        assert audit(p, "padding-taint") == []

    def test_reverse_cumsum_poisons_the_real_rows(self):
        # reverse cumulation folds the padded tail into every real row, so
        # the out[:real] unpad slice must NOT discharge afterwards
        p = prog("rev_cum", lambda x: jnp.sum(
            lax.cumsum(x, axis=0, reverse=True)[:5]), self.X,
            taints={0: {0: 5}})
        assert rules_of(audit(p, "padding-taint")) != []

    def test_forward_cumsum_keeps_the_unpad_discharge(self):
        # forward cumulation corrupts only the padded tail itself
        p = prog("fwd_cum", lambda x: jnp.sum(
            lax.cumsum(x, axis=0)[:5]), self.X, taints={0: {0: 5}})
        assert audit(p, "padding-taint") == []

    def test_reduction_along_clean_axis_stays_clean(self):
        # row-taint must ride along reductions over OTHER axes (the serving
        # programs' whole design: reduce over k/pixels, never over rows)
        p = prog("other_axis", lambda x: jnp.sum(x * 2.0, axis=1), self.X,
                 taints={0: {0: 5}})
        assert audit(p, "padding-taint") == []


# ---------------------------------------------------------------------------
# pass 3: host-transfer
# ---------------------------------------------------------------------------

class TestHostTransfer:
    @staticmethod
    def _with_print(x):
        jax.debug.print("loss {}", jnp.mean(x))
        return x * 2

    def test_fires_on_callback_in_hot_program(self):
        p = prog("cb", self._with_print, jnp.zeros((3,)))
        got = audit(p, "host-transfer")
        assert rules_of(got) == ["host-transfer"]

    def test_cold_programs_are_exempt(self):
        p = prog("cb_cold", self._with_print, jnp.zeros((3,)), hot=False)
        assert audit(p, "host-transfer") == []

    def test_clean_pure_program(self):
        p = prog("pure", lambda x: jnp.tanh(x).sum(), jnp.zeros((3,)))
        assert audit(p, "host-transfer") == []


# ---------------------------------------------------------------------------
# pass 4: recompile-cardinality
# ---------------------------------------------------------------------------

class TestRecompileCardinality:
    def test_fires_on_python_scalar_in_signature(self):
        p = prog("scalar_sig", lambda x: x * 2, jnp.zeros((3,)),
                 sig_args=((jnp.zeros((3,)), 0.75), {}))
        got = audit(p, "recompile-cardinality")
        assert rules_of(got) == ["recompile-cardinality"]
        assert "PER VALUE" in got[0].message

    def test_fires_on_weak_typed_program_input(self):
        sds = jax.ShapeDtypeStruct((3,), jnp.float32, weak_type=True)
        p = AuditProgram(name="weak_in",
                         jaxpr=jax.make_jaxpr(lambda x: x * 2)(sds))
        got = audit(p, "recompile-cardinality")
        assert got and "weak-typed" in got[0].message

    def test_clean_on_committed_arrays(self):
        x = jnp.zeros((3,), jnp.float32)
        p = prog("clean_sig", lambda x: x * 2, x, sig_args=((x,), {}))
        assert audit(p, "recompile-cardinality") == []

    def test_registry_entries_are_audited(self):
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, isolated_aot_registry, registry_signatures)
        with isolated_aot_registry():
            # a python float rides the dispatch args -> one executable per
            # value: exactly the fragmentation the pass must flag
            aot_call("frag_prog", jax.jit(lambda x, s: x * s),
                     (jnp.zeros((2,)), 0.5))
            env = AuditEnv(backend="tpu", cache_dir=None,
                           registry=registry_signatures())
            p = prog("any", lambda x: x, jnp.zeros((1,)))
            got = run_audit([p], select_passes(["recompile-cardinality"]),
                            env)
        assert [f.program for f in got] == ["aot:frag_prog"]

    def test_registry_findings_run_once_and_ignore_program_waivers(self):
        # registry auditing is cross-program state: N audited programs must
        # not multiply the findings, and one program's justified waiver must
        # not silence a registry-wide fragmentation hazard
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, isolated_aot_registry, registry_signatures)
        with isolated_aot_registry():
            aot_call("frag_prog", jax.jit(lambda x, s: x * s),
                     (jnp.zeros((2,)), 0.5))
            env = AuditEnv(backend="tpu", cache_dir=None,
                           registry=registry_signatures())
            p1 = prog("waived", lambda x: x, jnp.zeros((1,)),
                      waivers={"recompile-cardinality": "fixture program"})
            p2 = prog("plain", lambda x: x, jnp.zeros((1,)))
            got = run_audit([p1, p2],
                            select_passes(["recompile-cardinality"]), env)
        assert [f.program for f in got] == ["aot:frag_prog"]


# ---------------------------------------------------------------------------
# the concurrency checker (lint rules; ISSUE 7's fifth fixture class)
# ---------------------------------------------------------------------------

BAD_LOCK_ORDER = """
    import threading

    class Pipeline:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.items = []

        def produce(self):
            with self._a:
                with self._b:
                    self.items.append(1)

        def consume(self):
            with self._b:
                with self._a:
                    return self.items.pop()
"""

BAD_INDIRECT_ORDER = """
    import threading

    class Engine:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def outer(self):
            with self._a:
                self.inner()

        def inner(self):
            with self._b:
                pass

        def reverse(self):
            with self._b:
                with self._a:
                    pass
"""

CLEAN_CONDITION_ALIAS = """
    import threading

    class E:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)

        def f(self):
            with self._cv:
                with self._lock:
                    pass
"""

BAD_THREE_CYCLE = """
    import threading

    class Trio:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._c = threading.Lock()

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def bc(self):
            with self._b:
                with self._c:
                    pass

        def ca(self):
            with self._c:
                with self._a:
                    pass
"""

BAD_UNLOCKED_STATE = """
    import threading

    class Window:
        def __init__(self):
            self._cv = threading.Condition()
            self._open = 0

        def acquire(self):
            with self._cv:
                self._open += 1

        def force(self):
            self._open += 1
"""


BAD_SWALLOW = """
    class Conn:
        def write(self, data):
            span = self.tracer.start_span("write")
            try:
                self.sock.sendall(data)
            except OSError:
                self.dead = True          # drops the error while a span is
                                          # live: NOT the exempt teardown
                                          # shape (acquisitions_in > 0)
            span.finish()

        def tick(self):
            try:
                self.poll()
            except Exception:
                pass                      # the classic except-and-drop
"""

EXEMPT_TEARDOWN_SWALLOW = """
    class Conn:
        def close(self):
            try:
                self.sock.shutdown(2)
            except OSError:
                pass                      # teardown drop, acquisition-free:
                                          # the leak pass retires the waiver
            self.sock.close()

        def write(self, data):
            try:
                self.sock.sendall(data)
            except OSError:
                self.dead = True          # constant-flag body, same verdict
"""

CLEAN_SWALLOW = """
    class Conn:
        def write(self, data):
            try:
                self.sock.sendall(data)
            except OSError as e:
                self.complete(exc=e)      # uses the exception: handled

        def read(self):
            try:
                return self.sock.recv(1)
            except OSError:
                return None               # explicit decision

        def serve(self):
            while True:
                try:
                    self.step()
                except OSError:
                    break                 # explicit decision
                except ValueError:
                    continue              # explicit decision

        def probe(self):
            try:
                self.step()
            except Exception:
                raise                     # re-raised
"""

SUPPRESSED_SWALLOW = """
    class Conn:
        def tick(self):
            try:
                self.poll()
            except Exception:  # iwaelint: disable=swallowed-exception -- best-effort poll: the caller's next tick retries, and there is no future/span to complete
                pass
"""


class TestConcurrencyRules:
    def lint(self, tmp_path, src, rel="conc/m.py"):
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        cfg = LintConfig(root=str(tmp_path), concurrency_paths=["conc"])
        return lint_paths([str(path)], cfg, root=str(tmp_path))

    def test_fires_on_lock_order_inversion(self, tmp_path):
        assert rules_of(self.lint(tmp_path, BAD_LOCK_ORDER)) == ["lock-order"]

    def test_fires_on_indirect_inversion_via_method_call(self, tmp_path):
        assert "lock-order" in rules_of(self.lint(tmp_path,
                                                  BAD_INDIRECT_ORDER))

    def test_fires_on_three_lock_cycle(self, tmp_path):
        # no pair inverts directly; the deadlock is the a->b->c->a cycle,
        # which pairwise inversion checks cannot see
        got = self.lint(tmp_path, BAD_THREE_CYCLE)
        assert rules_of(got) == ["lock-order"] * 3
        assert "cyclic lock order" in got[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        src = BAD_LOCK_ORDER.replace(
            "with self._b:\n                with self._a:",
            "with self._a:\n                with self._b:")
        assert self.lint(tmp_path, src) == []

    def test_condition_aliasing_is_not_an_inversion(self, tmp_path):
        assert self.lint(tmp_path, CLEAN_CONDITION_ALIAS) == []

    def test_fires_on_bare_write_of_guarded_attr(self, tmp_path):
        got = self.lint(tmp_path, BAD_UNLOCKED_STATE)
        assert rules_of(got) == ["unlocked-shared-state"]
        assert "force" in got[0].message

    def test_swallowed_exception_fires_on_drops(self, tmp_path):
        got = self.lint(tmp_path, BAD_SWALLOW)
        assert rules_of(got) == ["swallowed-exception"] * 2
        assert "swallows the error" in got[0].message

    def test_swallowed_exception_clean_shapes(self, tmp_path):
        # uses-the-exception, return, break, continue, re-raise all count
        # as handling
        assert self.lint(tmp_path, CLEAN_SWALLOW) == []

    def test_swallowed_exception_justified_suppression(self, tmp_path):
        # a deliberate best-effort drop carries its justification in place
        # (and the suppression is LIVE, so useless-suppression stays quiet)
        assert self.lint(tmp_path, SUPPRESSED_SWALLOW) == []

    def test_swallowed_exception_teardown_exemption(self, tmp_path):
        # except-OSError teardown drops (pass / constant-flag bodies) in
        # functions the leak pass proves acquisition-free need NO waiver —
        # the PR-10 suppression-retirement semantics
        assert self.lint(tmp_path, EXEMPT_TEARDOWN_SWALLOW) == []

    def test_outside_concurrency_paths_is_silent(self, tmp_path):
        assert self.lint(tmp_path, BAD_LOCK_ORDER, rel="other/m.py") == []
        assert self.lint(tmp_path, BAD_SWALLOW, rel="other/m.py") == []

    def test_real_concurrency_files_are_clean(self):
        # the production thread fan passes its own checker (the deliberate
        # best-effort drops carry justified suppressions in place)
        cfg = LintConfig(root=REPO, select=["lock-order",
                                            "unlocked-shared-state",
                                            "swallowed-exception"])
        files = [os.path.join(REPO, p) for p in (
            "iwae_replication_project_tpu/serving/engine.py",
            "iwae_replication_project_tpu/serving/batcher.py",
            "iwae_replication_project_tpu/serving/faults.py",
            "iwae_replication_project_tpu/serving/frontend",
            "iwae_replication_project_tpu/telemetry/registry.py",
            "iwae_replication_project_tpu/utils/faults.py")]
        assert lint_paths(files, cfg, root=REPO) == []


# ---------------------------------------------------------------------------
# the real program suite + golden signatures
# ---------------------------------------------------------------------------

class TestRealProgramSuite:
    def test_tree_audits_clean(self):
        """THE acceptance gate: every pass over every real program, on this
        host's actual backend/cache env (scripts/check.py stage 2)."""
        programs = build_programs()
        findings = run_audit(programs, all_passes(),
                             AuditEnv.current(include_registry=False))
        assert findings == [], "\n".join(f.human() for f in findings)

    def test_serving_programs_declare_their_padding(self):
        by_name = {p.name: p for p in build_programs(
            ["serve_score", "serve_encode", "serve_decode",
             "serve_score_fused", "serve_score_sharded"])}
        for p in by_name.values():
            assert len(p.taints) == 2, \
                f"{p.name} lost its padded-row taint declaration"

    def test_golden_jaxpr_signatures(self):
        """Program-drift tripwire: eqn count + primitive histogram of the
        three serving programs and the train step. An intended change
        regenerates with IWAE_UPDATE_GOLDENS=1 (and shows up in the diff
        instead of as mystery serving recompiles)."""
        with open(GOLDEN_PATH, encoding="utf-8") as f:
            golden = json.load(f)
        progs = build_programs(sorted(golden))
        current = {p.name: signature(p.jaxpr) for p in progs}
        if os.environ.get("IWAE_UPDATE_GOLDENS"):
            with open(GOLDEN_PATH, "w", encoding="utf-8") as f:
                json.dump(current, f, indent=2, sort_keys=True)
                f.write("\n")
            pytest.skip("goldens regenerated")
        assert current == golden, (
            "traced program structure drifted from tests/golden/"
            "jaxpr_signatures.json — if intended, regenerate with "
            "IWAE_UPDATE_GOLDENS=1 pytest tests/test_audit.py")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m",
             "iwae_replication_project_tpu.analysis.audit", *args],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_list_passes(self):
        r = self._run("--list-passes")
        assert r.returncode == 0
        for name in ("donation-safety", "padding-taint", "host-transfer",
                     "recompile-cardinality"):
            assert name in r.stdout

    def test_unknown_select_exits_2(self):
        r = self._run("--select", "nope")
        assert r.returncode == 2
        assert "error" in r.stderr

    def test_self_audit_clean_json(self):
        """The CI invocation: full suite, JSON output, exit 0."""
        r = self._run("--format", "json")
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["total"] == 0
        assert set(payload["programs"]) == {
            "train_step", "eval_scorer_k5000", "serve_score", "serve_encode",
            "serve_decode", "serve_score_fused", "serve_score_sharded",
            "hot_loop_reference", "hot_loop_blocked_scan",
            "hot_loop_pallas"}


# ---------------------------------------------------------------------------
# scripts/check.py integration
# ---------------------------------------------------------------------------

class TestCheckSummary:
    def test_analyzer_rc_classification(self):
        """The satellite bugfix: exit 2 (analyzer crash) must be
        distinguishable from exit 1 (findings) — any nonzero-as-findings
        conflation can mask analyzer crashes."""
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import check
        finally:
            sys.path.pop(0)
        assert check.classify_analyzer_rc(0) == "ok"
        assert check.classify_analyzer_rc(1) == "findings"
        assert check.classify_analyzer_rc(2) == "internal-error"
        assert check.classify_analyzer_rc(139) == "internal-error"

    @pytest.mark.slow
    def test_lint_only_writes_summary(self, tmp_path):
        out = tmp_path / "summary.json"
        r = subprocess.run(
            [sys.executable, os.path.join("scripts", "check.py"),
             "--lint-only", "--summary", str(out)],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(out.read_text())
        assert payload["ok"] is True
        assert [s["name"] for s in payload["stages"]] == \
            ["lint", "race", "audit", "cost"]
        for s in payload["stages"]:
            assert s["status"] == "ok" and s["findings"] == 0
            assert s["wall_seconds"] > 0
