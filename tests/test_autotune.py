"""The measured tile/remat autotuner (ops/autotune.py) — ISSUE 12.

Pins the winner-cache lifecycle the fleet depends on: persistence + reload,
independent invalidation by version / VMEM budget / chip generation, LOUD
fallback to the hand-picked tiles on a corrupt cache, a warm cache making a
second tuning run free (zero probe compiles, zero timed runs), and the
trace-time consultation points in ops/hot_loop.py actually honoring
persisted winners.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from iwae_replication_project_tpu.ops import autotune as at
from iwae_replication_project_tpu.ops import hot_loop as hl

#: one small shape shared by most tests (k, b, h1_dim, hid, n_pixels)
SHAPE = (4, 8, 10, 16, 20)


@pytest.fixture(autouse=True)
def fresh_store():
    """Every test sees an empty in-memory store and leaves none behind."""
    at.reload_store()
    yield
    at.reload_store()


def _counter(name: str) -> float:
    from iwae_replication_project_tpu.telemetry.registry import get_registry
    return get_registry().counter(f"autotune/{name}").value


def _fake_measure(ms_by_call):
    """Deterministic injected measurement: pops the next wall-ms value per
    candidate (cycling), so tests control the winner without timing."""
    calls = []

    def measure(fn, args, reps):
        calls.append(fn)
        return ms_by_call[(len(calls) - 1) % len(ms_by_call)]

    measure.calls = calls
    return measure


# ---------------------------------------------------------------------------
# store lifecycle
# ---------------------------------------------------------------------------

def test_winner_persistence_and_reload(tmp_path):
    p = str(tmp_path / "autotune.json")
    measure = _fake_measure([3.0, 1.0, 2.0])
    rec = at.tune("serving_row", *SHAPE, path=p, measure=measure)
    assert rec["cache"] == "tuned"
    assert rec["path"] in ("pallas", "blocked_scan", "reference")
    assert os.path.exists(p)
    doc = json.load(open(p))
    assert doc["version"] == at.AUTOTUNE_VERSION
    assert len(doc["entries"]) == 1
    # a FRESH process (reload) serves the same winner from disk
    at.reload_store()
    got = at.winner_for("serving_row", *SHAPE, None, path=p)
    assert got is not None and got["path"] == rec["path"]
    # the ranking is measured: min of the injected walls won
    assert got["measured_ms"] == 1.0
    # the full measured field survives persistence (bench provenance)
    assert len(got["all_measured"]) == rec["measured_candidates"]


def test_second_tune_run_is_free(tmp_path):
    """The once-per-fleet contract: a warm cache makes tune() a pure
    lookup — zero probe compiles, zero timed runs (the injected measure
    must never be called)."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, isolated_aot_registry, stats_delta)

    p = str(tmp_path / "autotune.json")
    with isolated_aot_registry():
        rec = at.tune("serving_row", *SHAPE, path=p, reps=1)  # real measure
        assert rec["cache"] == "tuned"
        assert _counter("probe_compiles") >= rec["measured_candidates"]
        # warm: same key, fresh process state
        at.reload_store()
        probes0 = _counter("probe_compiles")
        searches0 = _counter("searches")
        s0 = cache_stats()
        measure = _fake_measure([1.0])
        rec2 = at.tune("serving_row", *SHAPE, path=p, measure=measure)
        assert rec2["cache"] == "hit"
        assert rec2["path"] == rec["path"]
        assert measure.calls == []                      # zero timed runs
        assert _counter("probe_compiles") == probes0    # zero probes
        assert _counter("searches") == searches0        # no search at all
        d = stats_delta(s0)
        assert d["aot_misses"] == 0 and d["persistent_cache_misses"] == 0


def test_version_invalidation(tmp_path):
    p = str(tmp_path / "autotune.json")
    at.tune("serving_row", *SHAPE, path=p, measure=_fake_measure([1.0]))
    # an incompatible version must invalidate wholesale (methodology drift)
    doc = json.load(open(p))
    doc["version"] = at.AUTOTUNE_VERSION + 1
    json.dump(doc, open(p, "w"))
    at.reload_store()
    before = _counter("version_mismatch")
    assert at.winner_for("serving_row", *SHAPE, None, path=p) is None
    assert _counter("version_mismatch") == before + 1


def test_budget_invalidation(tmp_path, monkeypatch):
    p = str(tmp_path / "autotune.json")
    monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "13000000")
    at.tune("serving_row", *SHAPE, path=p, measure=_fake_measure([1.0]))
    assert at.winner_for("serving_row", *SHAPE, None, path=p) is not None
    # a different budget changes which tiles fit -> its key must miss
    monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "9000000")
    assert at.winner_for("serving_row", *SHAPE, None, path=p) is None


def test_chip_key_invalidation(tmp_path):
    p = str(tmp_path / "autotune.json")
    # a winner measured on another chip generation must never rank
    # candidates here: plant an entry under a foreign chip key
    foreign = at.entry_key("serving_row", *SHAPE, None, chip="tpu-v99")
    at._save_store(p, {foreign: {"path": "pallas", "tile": [8, 1]}})
    at.reload_store()
    assert at.winner_for("serving_row", *SHAPE, None, path=p) is None
    assert at.entry_key("serving_row", *SHAPE, None) != foreign


def test_corrupt_cache_loud_fallback(tmp_path):
    p = str(tmp_path / "autotune.json")
    with open(p, "w") as f:
        f.write("{this is not json")
    before = _counter("cache_corrupt")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        got = at.winner_for("serving_row", *SHAPE, None, path=p)
    assert got is None                       # hand-picked tiles stand
    assert _counter("cache_corrupt") == before + 1
    # ... and the selection machinery keeps working on the heuristics
    path, tile = hl.serving_select_path(*SHAPE, on_tpu=False)
    assert path == "reference" and tile is None


def test_corrupt_cache_wrong_schema(tmp_path):
    p = str(tmp_path / "autotune.json")
    json.dump({"version": at.AUTOTUNE_VERSION, "entries": "nope"},
              open(p, "w"))
    with pytest.warns(RuntimeWarning, match="corrupt"):
        assert at.winner_for("serving_row", *SHAPE, None, path=p) is None


def test_entry_key_validates_kind():
    with pytest.raises(ValueError, match="unknown autotune kind"):
        at.entry_key("nope", *SHAPE, None)
    with pytest.raises(ValueError, match="unknown autotune kind"):
        at.candidates_for("nope", *SHAPE)


# ---------------------------------------------------------------------------
# candidate space
# ---------------------------------------------------------------------------

def test_candidates_admissible_and_budgeted():
    k, b = 32, 300
    cands = at.candidates_for("fwd", k, b, 100, 200, 784,
                              include_pallas=True)
    tiles = [c.tile for c in cands if c.path == "pallas"]
    assert tiles, "pallas candidates missing with include_pallas=True"
    for tk, tb in tiles:
        assert hl.tile_admissible(tk, tb, k, b)
        assert hl.fits_vmem_block(tk, tb, 100, 200, 784)
    # the hand-picked choice is IN the space (winner can only meet/beat it)
    assert (8, 128) in tiles or (8, b) in tiles
    # off-TPU the measured space drops pallas but keeps real fallbacks
    cpu = at.candidates_for("fwd", k, b, 100, 200, 784,
                            include_pallas=False)
    assert all(c.path != "pallas" for c in cpu)
    assert any(c.path == "reference" for c in cpu)
    assert any(c.path == "blocked_scan" for c in cpu)


def test_serving_row_candidates_are_row_tiles():
    cands = at.candidates_for("serving_row", 16, 8, 10, 16, 20,
                              include_pallas=True)
    assert all(c.tile[1] == 1 for c in cands if c.path == "pallas")


# ---------------------------------------------------------------------------
# trace-time consultation (ops/hot_loop.py)
# ---------------------------------------------------------------------------

def _plant(tmp_path, monkeypatch, kind, shape, record):
    """Persist one winner record and point the default store at it."""
    p = str(tmp_path / "autotune.json")
    key = at.entry_key(kind, *shape, None)
    at._save_store(p, {key: record})
    monkeypatch.setenv("IWAE_AUTOTUNE_CACHE", p)
    at.reload_store()
    return p


def test_scan_winner_overrides_remat_slab(tmp_path, monkeypatch):
    k, b, h1, hid, pix = 12, 8, 10, 16, 20
    assert hl._scan_block_k(k, b, hid, pix, h1, None) == k  # hand pick
    _plant(tmp_path, monkeypatch, "scan", (k, b, h1, hid, pix),
           {"path": "blocked_scan", "block_k": 3})
    assert hl._scan_block_k(k, b, hid, pix, h1, None) == 3
    # an out-of-range persisted slab is clamped to a divisor, never crashes
    _plant(tmp_path, monkeypatch, "scan", (k, b, h1, hid, pix),
           {"path": "blocked_scan", "block_k": 500})
    assert hl._scan_block_k(k, b, hid, pix, h1, None) == k


def test_fwd_winner_decides_auto_path(tmp_path, monkeypatch):
    k, b, h1, hid, pix = 4, 6, 10, 16, 20
    assert hl.select_path(k, b, h1, hid, pix, on_tpu=False)[0] == "reference"
    _plant(tmp_path, monkeypatch, "fwd", (k, b, h1, hid, pix),
           {"path": "blocked_scan", "block_k": 2})
    assert hl.select_path(k, b, h1, hid, pix,
                          on_tpu=False)[0] == "blocked_scan"
    # explicit force still outranks the winner
    assert hl.select_path(k, b, h1, hid, pix, on_tpu=False,
                          force="reference")[0] == "reference"


def test_serving_winner_decides_gate(tmp_path, monkeypatch):
    k, rows, h1, hid, pix = 4, 8, 10, 16, 20
    assert hl.serving_select_path(k, rows, h1, hid, pix,
                                  on_tpu=False)[0] == "reference"
    _plant(tmp_path, monkeypatch, "serving_row", (k, rows, h1, hid, pix),
           {"path": "blocked_scan", "block_k": 2})
    assert hl.serving_select_path(k, rows, h1, hid, pix,
                                  on_tpu=False)[0] == "blocked_scan"


def test_serving_winner_reaches_engine_gate(tmp_path, monkeypatch):
    """A persisted serving winner changes what the ENGINE dispatches —
    bitwise-identically (the blocked scan's forward is bitwise-equal to
    the reference composition)."""
    import jax

    from iwae_replication_project_tpu.models import ModelConfig
    from iwae_replication_project_tpu.serving import ServingEngine
    from iwae_replication_project_tpu.training import create_train_state

    cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                      n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                      likelihood="logits")
    params = create_train_state(jax.random.PRNGKey(0), cfg).params
    x = (np.random.RandomState(1).rand(4, 12) > 0.5).astype(np.float32)
    pinned = ServingEngine(params=params, model_config=cfg, k=4, max_batch=4,
                           timeout_s=None, kernel_path="reference")
    want = pinned.score(x)

    h1, hid, pix = 4, 16, 12
    _plant(tmp_path, monkeypatch, "serving_row", (4, 4, h1, hid, pix),
           {"path": "blocked_scan", "block_k": 2})
    eng = ServingEngine(params=params, model_config=cfg, k=4, max_batch=4,
                        timeout_s=None)
    got = eng.score(x)
    assert np.array_equal(got, want)
    snap = eng.metrics.snapshot()["kernel"]
    assert snap["score/b4/k4"]["path"] == "blocked_scan"


def test_kernel_usable_block_winner_tile(tmp_path, monkeypatch):
    """A persisted fwd tile overrides the hand-picked one (interpret mode:
    the estimate decides, no probe) — and an inadmissible persisted tile
    falls back to the heuristic instead of compiling garbage."""
    k, b, h1, hid, pix = 32, 130, 10, 16, 20
    assert hl.kernel_usable_block(k, b, h1, hid, pix,
                                  interpret=True) == (8, b)
    _plant(tmp_path, monkeypatch, "fwd", (k, b, h1, hid, pix),
           {"path": "pallas", "tile": [16, 128]})
    assert hl.kernel_usable_block(k, b, h1, hid, pix,
                                  interpret=True) == (16, 128)
    _plant(tmp_path, monkeypatch, "fwd", (k, b, h1, hid, pix),
           {"path": "pallas", "tile": [13, 40]})     # violates Mosaic rules
    assert hl.kernel_usable_block(k, b, h1, hid, pix,
                                  interpret=True) == (8, b)


# ---------------------------------------------------------------------------
# the search itself
# ---------------------------------------------------------------------------

def test_tune_winner_is_measured_min(tmp_path):
    p = str(tmp_path / "autotune.json")
    cands = at.candidates_for("serving_row", *SHAPE, include_pallas=False)
    walls = [5.0 + i for i in range(len(cands))]
    walls[2] = 0.5                            # the planted winner
    rec = at.tune("serving_row", *SHAPE, path=p,
                  measure=_fake_measure(walls))
    assert rec["measured_ms"] == 0.5
    assert rec["measured_candidates"] == len(cands)
    # the committed provenance is sorted fastest-first
    assert rec["all_measured"][0]["measured_ms"] == 0.5


def test_tune_failed_candidates_are_skipped(tmp_path):
    p = str(tmp_path / "autotune.json")
    seen = []

    def measure(fn, args, reps):
        seen.append(fn)
        return None if len(seen) == 1 else float(len(seen))

    rec = at.tune("serving_row", *SHAPE, path=p, measure=measure)
    assert rec["measured_candidates"] == rec["candidates"] - 1

    def all_fail(fn, args, reps):
        return None

    with pytest.raises(RuntimeError, match="every candidate failed"):
        at.tune("scan", *SHAPE, path=p, measure=all_fail, force=True)


def test_tune_ladder_and_cli(tmp_path):
    """tune_ladder covers the (k, bucket) grid; the iwae-autotune CLI runs
    end to end (real measurement at a tiny shape) and persists winners."""
    from iwae_replication_project_tpu.models import ModelConfig

    p = str(tmp_path / "autotune.json")
    cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                      n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                      likelihood="logits")
    rows = at.tune_ladder(cfg, ks=[2], buckets=[1, 2],
                          kinds=("serving_row",), reps=1, path=p)
    assert len(rows) == 2
    assert all(r["cache"] == "tuned" for r in rows)
    at.reload_store()
    rows2 = at.tune_ladder(cfg, ks=[2], buckets=[1, 2],
                           kinds=("serving_row",), reps=1, path=p)
    assert all(r["cache"] == "hit" for r in rows2)


def test_pallas_winner_never_interprets_off_tpu(tmp_path, monkeypatch):
    """A persisted pallas serving winner (another chip's cache copied in,
    or a debug --include-pallas tune) must NOT route off-TPU production
    through interpret-mode pallas: the auto gate falls through to the
    hand-picked order instead (select_path's own on_tpu rule)."""
    k, rows, h1, hid, pix = 4, 8, 10, 16, 20
    _plant(tmp_path, monkeypatch, "serving_row", (k, rows, h1, hid, pix),
           {"path": "pallas", "tile": [4, 1]})
    path, tile = hl.serving_select_path(k, rows, h1, hid, pix,
                                        on_tpu=False)
    assert path == "reference" and tile is None
