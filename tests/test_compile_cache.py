"""Warm-path engine tests (utils/compile_cache.py): persistent-cache reuse
across processes, AOT-registry accounting across stages and runs, buffer
donation bit-parity, and the entry-point lint guard.

All CPU, tier-1 fast: the cross-process test uses a tiny probe program in a
tmpdir cache, not the full driver (scripts/warm_start_check.py is the
full-driver version of the same measurement).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.utils import compile_cache as cc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# directory resolution
# ---------------------------------------------------------------------------

class TestResolution:
    def test_explicit_wins(self, tmp_path):
        assert cc.resolve_cache_dir(str(tmp_path), base_dir="/elsewhere") == \
            str(tmp_path)

    def test_off_spellings_disable(self):
        for off in ("off", "OFF", "none", "0", ""):
            assert cc.resolve_cache_dir(off, base_dir="/elsewhere") is None

    def test_env_fills_in(self, tmp_path, monkeypatch):
        monkeypatch.setenv("IWAE_COMPILE_CACHE", str(tmp_path / "envcache"))
        assert cc.resolve_cache_dir(None, base_dir="/elsewhere") == \
            str(tmp_path / "envcache")
        monkeypatch.setenv("IWAE_COMPILE_CACHE", "off")
        assert cc.resolve_cache_dir(None, base_dir="/elsewhere") is None

    def test_default_under_base_dir(self, monkeypatch):
        monkeypatch.delenv("IWAE_COMPILE_CACHE", raising=False)
        # an already-configured cache (conftest) wins over the base_dir
        # default (first-wins precedence, same answer setup would give)...
        assert cc.resolve_cache_dir(None, base_dir="/ckpt") == \
            jax.config.jax_compilation_cache_dir
        # ...and with nothing configured anywhere, the default lands under
        # base_dir/.jax_compile_cache
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        before = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            assert cc.resolve_cache_dir(None, base_dir="/ckpt") == \
                os.path.join("/ckpt", cc.CACHE_SUBDIR)
            assert cc.resolve_cache_dir(None, base_dir=None) is None
        finally:
            jax.config.update("jax_compilation_cache_dir", before)

    def test_setup_keeps_already_configured_dir(self, tmp_path, monkeypatch):
        """First-wins: without an explicit override, an already-configured
        JAX cache (conftest points it at tests/.jax_cache) is kept — the
        driver must not re-point the cache a wrapper already chose."""
        monkeypatch.delenv("IWAE_COMPILE_CACHE", raising=False)
        before = jax.config.jax_compilation_cache_dir
        assert before  # conftest configured it
        got = cc.setup_persistent_cache(None, base_dir=str(tmp_path))
        assert got == before
        assert jax.config.jax_compilation_cache_dir == before

    def test_setup_explicit_repoints_and_restores(self, tmp_path):
        before = jax.config.jax_compilation_cache_dir
        before_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            got = cc.setup_persistent_cache(str(tmp_path / "c"))
            assert got == str(tmp_path / "c")
            assert os.path.isdir(got)
            assert jax.config.jax_compilation_cache_dir == got
        finally:
            jax.config.update("jax_compilation_cache_dir", before)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              before_min)


# ---------------------------------------------------------------------------
# (a) cross-process persistent-cache reuse: warm start = zero recompiles
# ---------------------------------------------------------------------------

_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from iwae_replication_project_tpu.utils.compile_cache import (
    aot_call, cache_stats, setup_persistent_cache)

setup_persistent_cache(sys.argv[1])

@jax.jit
def probe(x):
    return (jnp.sin(x) @ jnp.cos(x).T).sum()

aot_call("probe", probe, (jnp.ones((32, 32)),)).block_until_ready()
print("STATS " + json.dumps(cache_stats()))
"""


def _run_child(cache_dir: str) -> dict:
    env = dict(os.environ)
    r = subprocess.run([sys.executable, "-c", _CHILD, str(cache_dir)],
                       env=env, cwd=REPO, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("STATS ")][-1]
    return json.loads(line[len("STATS "):])


def test_second_process_reuses_persistent_cache(tmp_path):
    """Cold process: every compile is a persistent-cache miss (a real XLA
    compile). Warm process (same cache dir, fresh runtime): zero misses —
    the compile-event count drops to zero on warm start."""
    cache_dir = tmp_path / "cache"
    cold = _run_child(cache_dir)
    assert cold["persistent_cache_misses"] >= 1
    assert cold["aot_misses"] == 1
    assert len(os.listdir(cache_dir)) > 0  # entries actually persisted
    warm = _run_child(cache_dir)
    assert warm["persistent_cache_misses"] == 0
    assert warm["persistent_cache_hits"] >= 1


# ---------------------------------------------------------------------------
# (b) AOT registry accounting across stages / runs
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path, tag, **over):
    from iwae_replication_project_tpu.utils.config import ExperimentConfig
    d = dict(
        dataset="binarized_mnist", data_dir=str(tmp_path / "data"),
        n_hidden_encoder=(16,), n_hidden_decoder=(16,),
        n_latent_encoder=(4,), n_latent_decoder=(784,),
        loss_function="IWAE", k=4, batch_size=32, n_stages=2,
        eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
        activity_samples=8, save_figures=False,
        # these tests pin the warm-path program COUNTS; diagnostics add one
        # estimator-diagnostics program per eval (its own aot entry), so they
        # run the pre-telemetry profile the counts were pinned under
        diagnostics=False,
        log_dir=str(tmp_path / f"runs_{tag}"),
        checkpoint_dir=str(tmp_path / f"ckpt_{tag}"),
    )
    d.update(over)
    return ExperimentConfig(**d)


def test_aot_registry_accounting_across_stages_and_runs(tmp_path):
    """Two stages with identical shapes: the epoch and eval programs compile
    once (misses) and every further stage dispatch is a registry hit. A
    second run_experiment in the same process — fresh jitted closures, same
    shapes — re-uses the module-level registry with zero new compiles."""
    from iwae_replication_project_tpu.experiment import run_experiment

    s0 = cc.cache_stats()
    run_experiment(_tiny_cfg(tmp_path, "a"), max_batches_per_pass=2,
                   eval_subset=32)
    d1 = cc.stats_delta(s0)
    # stage 1 compiles the epoch + fused-eval programs; stage 2 (same spec,
    # same shapes) must be pure hits: 1 pass then 3 passes -> 3 epoch hits,
    # plus the stage-2 eval hit
    assert d1["aot_misses"] == 2
    assert d1["aot_hits"] == 4

    s1 = cc.cache_stats()
    run_experiment(_tiny_cfg(tmp_path, "b"), max_batches_per_pass=2,
                   eval_subset=32)
    d2 = cc.stats_delta(s1)
    assert d2["aot_misses"] == 0          # nothing recompiled
    assert d2["aot_hits"] == 6            # every dispatch was a registry hit


def test_stage_rows_stamp_cache_stats(tmp_path):
    """The per-stage metrics.jsonl rows carry the warm-path accounting and
    the split-out checkpoint seconds (ADVICE r5: mid-stage save time must
    not deflate the steps/s derived from stage_train_seconds)."""
    from iwae_replication_project_tpu.experiment import run_experiment

    cfg = _tiny_cfg(tmp_path, "rows", checkpoint_every_passes=1)
    run_experiment(cfg, max_batches_per_pass=2, eval_subset=32)
    jsonl = os.path.join(cfg.log_dir, cfg.run_name(), "metrics.jsonl")
    rows = [json.loads(ln) for ln in open(jsonl)]
    assert len(rows) == 2
    for row in rows:
        for field in ("aot_hits", "aot_misses", "aot_compile_seconds",
                      "compile_cache_misses", "compile_cache_hits",
                      "compile_seconds", "stage_checkpoint_seconds",
                      "stage_train_seconds", "checkpoint_every_passes"):
            assert field in row, field
        # the cadence the row was produced under is stamped so derived
        # steps/s is comparable across --checkpoint-every-passes settings
        assert row["checkpoint_every_passes"] == 1.0
    # stage 1 is a single pass: the only boundary is the final one, which the
    # end-of-stage save owns -> zero mid-stage checkpoint seconds. Stage 2
    # (3 passes, cadence 1) saves after passes 1 and 2: the split-out time is
    # nonzero and excluded from the train timer.
    assert rows[0]["stage_checkpoint_seconds"] == 0.0
    assert rows[1]["stage_checkpoint_seconds"] > 0.0
    assert rows[1]["stage_train_seconds"] > 0.0


# ---------------------------------------------------------------------------
# (c) buffer donation: bit-identical results
# ---------------------------------------------------------------------------

def test_donated_epoch_bit_identical_per_leaf(rng):
    """donate=True must be a pure memory optimization: every state leaf and
    every per-batch loss bit-equals the donate=False run.

    The persistent cache is suspended for this test: donation + CACHED
    executables is exactly the jaxlib-0.4.x CPU combination
    `donation_safe()` exists to forbid (deserialized programs mishandle the
    aliasing — nondeterministic corruption); the supported combination is
    donation with freshly-compiled programs, which is what runs here."""
    from iwae_replication_project_tpu.models.iwae import ModelConfig
    from iwae_replication_project_tpu.objectives import ObjectiveSpec
    from iwae_replication_project_tpu.training import create_train_state, make_adam
    from iwae_replication_project_tpu.training.epoch import make_epoch_fn

    cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                      n_hidden_dec=(16,), n_latent_dec=(784,))
    spec = ObjectiveSpec("IWAE", k=4)
    opt = make_adam(eps=1e-4)
    n_train, bs = 96, 32
    x = (jax.random.uniform(jax.random.PRNGKey(7), (n_train, 784)) > 0.5
         ).astype(jnp.float32)

    cache_before = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        assert cc.donation_safe()  # no cache -> donation allowed, even on CPU
        fn_don = make_epoch_fn(spec, cfg, n_train, bs, optimizer=opt,
                               donate=True)
        fn_ref = make_epoch_fn(spec, cfg, n_train, bs, optimizer=opt,
                               donate=False)
        st_don = create_train_state(rng, cfg, optimizer=opt)
        st_ref = create_train_state(rng, cfg, optimizer=opt)
        for _ in range(3):
            st_don, loss_don = fn_don(st_don, x)
            st_ref, loss_ref = fn_ref(st_ref, x)
            np.testing.assert_array_equal(np.asarray(loss_don),
                                          np.asarray(loss_ref))
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_before)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), st_don.params, st_ref.params)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), st_don.opt_state, st_ref.opt_state)
    assert not cc.donation_safe()  # cache restored -> CPU driver drops it


_PARITY_CHILD = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ["IWAE_COMPILE_CACHE"] = "off"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from iwae_replication_project_tpu.experiment import run_experiment
from iwae_replication_project_tpu.utils import compile_cache as cc
from iwae_replication_project_tpu.utils.config import ExperimentConfig

tmp = sys.argv[1]

def tiny(tag, donate):
    # mirrors the parent's _tiny_cfg (n_stages=1)
    return ExperimentConfig(
        dataset="binarized_mnist", data_dir=os.path.join(tmp, "data"),
        n_hidden_encoder=(16,), n_hidden_decoder=(16,),
        n_latent_encoder=(4,), n_latent_decoder=(784,),
        loss_function="IWAE", k=4, batch_size=32, n_stages=1,
        eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
        activity_samples=8, save_figures=False,
        log_dir=os.path.join(tmp, "runs_" + tag),
        checkpoint_dir=os.path.join(tmp, "ckpt_" + tag),
        donate_buffers=donate, compile_cache_dir="off")

st_on, hist_on = run_experiment(tiny("don", True), max_batches_per_pass=2,
                                eval_subset=32)
assert jax.config.jax_compilation_cache_dir is None  # "off" really off
assert cc.donation_safe()  # -> the donate run really donated
st_off, hist_off = run_experiment(tiny("nodon", False),
                                  max_batches_per_pass=2, eval_subset=32)
jax.tree.map(lambda a, b: np.testing.assert_array_equal(
    np.asarray(a), np.asarray(b)), st_on.params, st_off.params)
assert hist_on[0][0]["NLL"] == hist_off[0][0]["NLL"]
print("PARITY_OK")
"""


def test_driver_donation_parity(tmp_path):
    """The escape hatch (donate_buffers=False) and the default produce
    identical trained parameters through the full staged driver.

    Runs in a FRESH SUBPROCESS with the compile cache hard-off: the
    corruption class this guards against (jaxlib-0.4.x XLA:CPU donation +
    cache-DESERIALIZED executables, RESULTS.md §5) is heap corruption, so
    merely isolating the AOT registry in-process is not enough — earlier
    tests in the same process have already executed cache-deserialized
    programs, and the donate run was observed to corrupt nondeterministically
    (~1 in 3 full-file runs) even with its own programs freshly compiled. A
    fresh process that never touches the persistent cache is the
    documented-stable configuration, and makes the parity deterministic."""
    r = subprocess.run([sys.executable, "-c", _PARITY_CHILD, str(tmp_path)],
                       env=dict(os.environ), cwd=REPO, capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "PARITY_OK" in r.stdout


# ---------------------------------------------------------------------------
# lint guard: every production entry point goes through the shared helper.
# The implementation moved to the static-analysis framework (the `cache-setup`
# rule, analysis/rules/entrypoints.py, policy in [tool.iwaelint]); this test
# re-points at it so the check has ONE implementation. Rule fixtures proving
# the rule fires on known-bad snippets live in tests/test_analysis.py.
# ---------------------------------------------------------------------------

def test_entry_point_cache_guard_via_lint_rule():
    """The configured entry points call setup_persistent_cache and nobody
    hand-rolls jax_compilation_cache_dir config — asserted through the
    cache-setup lint rule, the check's single implementation."""
    from iwae_replication_project_tpu.analysis import lint_paths, load_config

    config, pyproject = load_config(REPO)
    assert pyproject == os.path.join(REPO, "pyproject.toml")
    # the policy migrated intact: the pre-migration entry-point list is a
    # subset of the configured one
    assert {"iwae_replication_project_tpu/experiment.py", "bench.py",
            "scripts/dress_rehearsal.py", "scripts/warm_start_check.py",
            "__graft_entry__.py"} <= set(config.entry_points)
    config.select = ["cache-setup"]
    findings = lint_paths([os.path.join(REPO, p) for p in config.paths],
                          config, root=REPO)
    assert findings == [], [f.human() for f in findings]
