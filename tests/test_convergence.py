"""CI-sized convergence evidence on REAL data (VERDICT r2 missing #1c).

The `digits` dataset (sklearn-bundled UCI optdigits, prepared to mirror the
fixed-binarization protocol — data/loaders.py) is the one real image dataset
available offline, so these tests are the suite's ground-truth check that the
full staged pipeline *learns* on real data: NLL must fall below a recorded
threshold, must improve across stages, and IWAE must not be worse than VAE
(Burda Table 1 ordering). Full-length runs live in RESULTS.md; these are the
short-schedule proxies (SURVEY.md §7 hard part (e))."""

import json
import os

import numpy as np
import pytest

from iwae_replication_project_tpu.experiment import run_experiment
from iwae_replication_project_tpu.utils.config import ExperimentConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.filterwarnings("ignore::DeprecationWarning"),
              pytest.mark.slow]


def digits_config(tmp_path, **over):
    d = dict(
        dataset="digits", allow_synthetic=False,
        n_hidden_encoder=(64,), n_hidden_decoder=(64,),
        n_latent_encoder=(16,), n_latent_decoder=(784,),
        loss_function="IWAE", k=5, batch_size=100, n_stages=3,
        eval_k=5, nll_k=128, nll_chunk=64, eval_batch_size=99,
        activity_samples=64, save_figures=False, resume=False,
        log_dir=str(tmp_path / "runs"),
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    d.update(over)
    return ExperimentConfig(**d)


def final_nll(history):
    return history[-1][0]["NLL"]


class TestDigitsConvergence:
    def test_iwae_converges_and_beats_vae(self, tmp_path):
        """3 Burda stages (13 passes) on real digits: NLL improves stage over
        stage, lands below a recorded threshold, and the trained-IWAE NLL is
        not worse than trained-VAE (the qualitative Table 1 ordering)."""
        _, hist_iwae = run_experiment(digits_config(tmp_path))
        nlls = [res["NLL"] for res, _ in hist_iwae]
        assert all(res["synthetic_data"] is False for res, _ in hist_iwae)
        # learning happened: NLL falls monotonically across stages and lands
        # below the recorded threshold. Calibration (CPU + TPU, seeds 0/1):
        # stage trajectories ~[373-375, 329-335, 305-316]; the binarized
        # upsampled digits have a high Bernoulli entropy floor, so the
        # absolute scale is ~300, not MNIST's ~90.
        assert all(b < a for a, b in zip(nlls, nlls[1:])), nlls
        assert nlls[-1] < 330.0, nlls

        _, hist_vae = run_experiment(
            digits_config(tmp_path, loss_function="VAE"))
        # same schedule, same seed: IWAE's tighter bound must not train a
        # worse model. Calibrated gap is ~1-10 nats in IWAE's favour; the
        # +2 corridor absorbs MC noise of the k=128 NLL estimate without
        # letting a real ordering inversion pass.
        assert final_nll(hist_iwae) <= final_nll(hist_vae) + 2.0, (
            final_nll(hist_iwae), final_nll(hist_vae))


class TestExtendedEstimatorConvergence:
    """STL and PIWAE trained to convergence on real data (VERDICT r5 weak
    #2: oracles and mesh tests existed, committed training runs did not).

    Two layers of evidence: (a) a live 3-stage short-schedule run per
    estimator asserting the dynamics are healthy (NLL falls stage over stage
    to below a calibrated ceiling), and (b) the committed full scaled-
    schedule artifacts under results/ (written by
    scripts/estimator_convergence.py) are present, self-consistent, and
    converged. Thresholds calibrated from the committed runs (CPU, seed 0):
    see results/convergence_{stl,piwae}.json."""

    ARTIFACTS = {"STL": "convergence_stl.json",
                 "PIWAE": "convergence_piwae.json"}
    #: 3-stage k=50 short-proxy ceiling (same corridor logic as the IWAE k=5
    #: test above: trajectory lands ~300-320, ceiling leaves MC headroom
    #: without admitting a non-learning run, whose stage-1 NLL is ~370+)
    SHORT_CEILING = 335.0
    #: full scaled-schedule final-NLL ceiling — healthy runs land near
    #: IWAE-k50's 238.3±0.5 (RESULTS.md §2); 260 rejects any broken-gradient
    #: plateau while absorbing seed/CPU-accumulation spread
    FULL_CEILING = 260.0

    @pytest.mark.parametrize("loss,over", [("STL", {}), ("PIWAE", {"k2": 5})])
    def test_trains_on_digits(self, tmp_path, loss, over):
        _, hist = run_experiment(digits_config(
            tmp_path, loss_function=loss, k=50, **over))
        assert all(res["synthetic_data"] is False for res, _ in hist)
        nlls = [res["NLL"] for res, _ in hist]
        assert all(b < a for a, b in zip(nlls, nlls[1:])), (loss, nlls)
        assert nlls[-1] < self.SHORT_CEILING, (loss, nlls)

    @pytest.mark.parametrize("loss", ["STL", "PIWAE"])
    def test_committed_artifact_is_converged(self, loss):
        path = os.path.join(REPO, "results", self.ARTIFACTS[loss])
        with open(path) as f:
            data = json.load(f)
        assert data["estimator"] == loss
        assert data["config"]["synthetic_data"] is False
        assert data["config"]["n_stages"] == 8
        nlls = [s["NLL"] for s in data["stages"]]
        assert len(nlls) == 8
        assert data["final_NLL"] == nlls[-1]
        assert data["best_NLL"] == min(nlls)
        assert data["final_NLL"] < self.FULL_CEILING, nlls
        # scaled schedule: no best-stage selection needed — the run must not
        # have collapsed after its best stage (RESULTS.md §2 protocol)
        assert data["final_NLL"] <= data["best_NLL"] + 5.0, nlls


class TestLikelihoodNeutrality:
    def test_likelihood_modes_nll_neutral(self, tmp_path):
        """Train the same config under likelihood="clamp" (reference
        bit-parity: sigmoid + prob clamp, flexible_IWAE.py:102) and
        "logits" (exact x*l - softplus(l), the fast default) — the trained
        models' NLLs must agree within an SE-scaled corridor. This is what
        licenses defaulting ExperimentConfig.likelihood to the fast path
        (VERDICT r2 missing #3)."""
        import jax
        import jax.numpy as jnp
        from iwae_replication_project_tpu.data import load_dataset
        from iwae_replication_project_tpu.evaluation.metrics import (
            streaming_log_px)

        states, cfgs = {}, {}
        for mode in ("clamp", "logits"):
            cfg = digits_config(tmp_path, likelihood=mode, n_stages=2)
            state, _ = run_experiment(cfg)
            states[mode] = state
            cfgs[mode] = cfg.model_config()

        ds = load_dataset("digits", allow_synthetic=False)
        x = jnp.asarray(ds.x_test.reshape(len(ds.x_test), -1))
        key = jax.random.PRNGKey(7)
        # per-example log px under each trained model, SAME eval samples
        lp = {mode: np.asarray(streaming_log_px(
                  states[mode].params, cfgs[mode], key, x, k=256, chunk=64))
              for mode in ("clamp", "logits")}
        diff = lp["clamp"] - lp["logits"]
        se = diff.std(ddof=1) / np.sqrt(len(diff))
        assert abs(diff.mean()) < max(4 * se, 0.05), (
            diff.mean(), se, lp["clamp"].mean(), lp["logits"].mean())

    def test_likelihood_modes_same_params_tight(self):
        """On IDENTICAL params the two likelihood modes are the same function
        up to the 1e-6 prob clamp: per-example log px agrees to < 5e-3."""
        import jax
        import jax.numpy as jnp
        from iwae_replication_project_tpu.models import iwae as model

        cfg_c = model.ModelConfig.one_layer(likelihood="clamp")
        cfg_l = model.ModelConfig.one_layer(likelihood="logits")
        params = model.init_params(jax.random.PRNGKey(0), cfg_c)
        x = jnp.asarray((np.random.RandomState(0).rand(32, 784) > 0.5)
                        .astype(np.float32))
        key = jax.random.PRNGKey(1)
        lw_c = model.log_weights(params, cfg_c, key, x, 16)
        lw_l = model.log_weights(params, cfg_l, key, x, 16)
        np.testing.assert_allclose(np.asarray(lw_c), np.asarray(lw_l),
                                   atol=5e-3)
