"""Tests for the jaxpr-level cost analyzer (analysis/audit/cost.py).

Per ISSUE 11's acceptance bar: the FLOP accounting reconciles BIT-EXACTLY
with utils/flops.py's analytic tables on the flagship config (the two
implementations cross-check each other — one counts the traced program,
the other derives from the architecture); the live-range memory pass is
proven on seeded fixtures (a materialized outer-product blowup plus its
discharged streaming twin, donation, scan-carry reuse); the sharded score
program's collective profile is pinned to exactly ONE pmax + ONE psum
(PR 9's merge contract, machine-checked); and the real program suite
analyzes clean end-to-end — the same contract scripts/check.py gates on.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from iwae_replication_project_tpu.analysis.audit.core import (
    BARE_WAIVER,
    AuditProgram,
)
from iwae_replication_project_tpu.analysis.audit.cost import (
    DEFAULT_BLOWUP_FACTOR,
    RULE_ACCIDENTAL_GATHER,
    RULE_MEMORY_BLOWUP,
    CostAnalyzer,
    analyze_programs,
    resolve_chip,
    roofline,
)
from iwae_replication_project_tpu.analysis.audit.programs import (
    build_programs,
)
from iwae_replication_project_tpu.models.iwae import ModelConfig
from iwae_replication_project_tpu.utils import flops as F
from iwae_replication_project_tpu.utils.dtypes import aval_bytes, byte_width

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the flagship architecture every reconciliation below is stated against
CFG = ModelConfig.two_layer(likelihood="logits")


def analyze(fn, *args, name="fixture", taints=None, waivers=None,
            blowup_factor=DEFAULT_BLOWUP_FACTOR):
    prog = AuditProgram(name=name, jaxpr=jax.make_jaxpr(fn)(*args),
                        taints=taints or {}, waivers=waivers or {})
    return CostAnalyzer(blowup_factor=blowup_factor).analyze(prog)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# the shared dtype -> byte-width helper (utils/dtypes.py satellite)
# ---------------------------------------------------------------------------

class TestDtypes:
    def test_production_widths(self):
        assert byte_width(jnp.float32) == 4
        assert byte_width(jnp.bfloat16) == 2
        assert byte_width(jnp.int32) == 4
        assert byte_width(jnp.bool_) == 1

    def test_string_names_as_stored_in_signature_records(self):
        # compile_cache._abstract_signature stores str(dtype): the byte
        # width must resolve from exactly those strings
        assert byte_width("float32") == 4
        assert byte_width("bfloat16") == 2
        assert byte_width("uint32") == 4
        assert byte_width("bool") == 1

    def test_weak_typed_python_scalar_names(self):
        # x64-off promotion: python int -> i32, float -> f32
        assert byte_width("int") == 4
        assert byte_width("float") == 4

    def test_extended_prng_key_dtype(self):
        key = jax.random.key(0)  # typed key: extended dtype, 2 u32 lanes
        assert byte_width(key.dtype) == 8

    def test_aval_bytes(self):
        aval = jax.ShapeDtypeStruct((4, 2), jnp.bfloat16)
        assert aval_bytes(aval) == 16
        assert aval_bytes(jax.ShapeDtypeStruct((), jnp.float32)) == 4

    def test_unknown_dtype_raises(self):
        with pytest.raises(ValueError, match="byte width"):
            byte_width("no_such_dtype")

    def test_fused_vmem_probe_consumes_the_shared_table(self):
        # the replaced ad-hoc itemsize call site: bf16 operands must still
        # scale the streamed terms exactly as tests/test_fused_likelihood
        # pins — byte_width(bf16) is that 2
        from iwae_replication_project_tpu.ops.fused_likelihood import (
            fits_vmem)
        assert fits_vmem(8, 350, 200, 784, itemsize=byte_width(jnp.bfloat16))
        assert not fits_vmem(8, 350, 200, 784,
                             itemsize=byte_width(jnp.float32))


# ---------------------------------------------------------------------------
# pass 2: FLOP accounting — bit-exact against utils/flops.py
# ---------------------------------------------------------------------------

class TestFlopReconciliation:
    """The cross-check: iwae-cost counts the traced program, utils/flops.py
    derives from the architecture; on the flagship config they must agree
    to the FLOP. A drift in either accounting fails loudly here."""

    def test_serving_score_reconciles(self):
        # the audit builder's serve_score: bucket 8, k=4
        prog = build_programs(["serve_score"])[0]
        rec, _ = CostAnalyzer().analyze(prog)
        assert rec.matmul_flops == 8 * F.serving_score_flops_per_row(CFG, 4)

    def test_eval_scorer_reconciles(self):
        # the k=5000 chunked scorer at batch 16: the streaming-NLL term of
        # eval_suite_flops_per_image, which is that suite total minus its
        # two plain forwards (the identity pinned below)
        prog = build_programs(["eval_scorer_k5000"])[0]
        rec, _ = CostAnalyzer().analyze(prog)
        nll = (F.eval_suite_flops_per_image(CFG, 5, 5000, 250)
               - F.forward_flops(CFG, 1, 5) - F.forward_flops(CFG, 1, 1))
        assert rec.matmul_flops == 16 * nll

    def test_eval_suite_term_identity(self):
        no_k, per_k = F.per_row_macs(CFG)
        nll = 2.0 * ((5000 // 250) * no_k + 5000 * per_k)
        assert (F.eval_suite_flops_per_image(CFG, 5, 5000, 250)
                == F.forward_flops(CFG, 1, 5) + nll
                + F.forward_flops(CFG, 1, 1))

    def test_full_eval_suite_reconciles(self):
        # the WHOLE 7-scalar fused eval program (metric pass + streaming
        # NLL + 1-sample reconstruction) against eval_suite_flops_per_image
        from iwae_replication_project_tpu.evaluation.metrics import (
            dataset_scalars)
        from iwae_replication_project_tpu.training.train_step import (
            create_train_state)

        state = create_train_state(jax.random.PRNGKey(0), CFG)
        nb, B, k, nll_k, chunk = 2, 4, 3, 20, 10
        batches = jnp.zeros((nb, B, CFG.x_dim), jnp.float32)
        rec, _ = analyze(
            lambda p, key, xb: dataset_scalars(p, CFG, key, xb, k, nll_k,
                                               chunk),
            state.params, jax.random.PRNGKey(1), batches)
        assert rec.matmul_flops == \
            nb * B * F.eval_suite_flops_per_image(CFG, k, nll_k, chunk)

    def test_train_step_reconciles_with_exact_correction(self):
        # train_step_flops models backward as exactly 2x forward; the real
        # traced backward skips ONE term that model includes — dL/dx of the
        # first encoder layer (x is data, not a differentiation target):
        # 2 FLOPs/MAC * batch * (x_dim * n_hidden_enc[0]). With that
        # analytic correction the reconciliation is bit-exact.
        prog = build_programs(["train_step"])[0]
        rec, _ = CostAnalyzer().analyze(prog)
        correction = 2.0 * 16 * CFG.x_dim * CFG.n_hidden_enc[0]
        assert rec.matmul_flops == F.train_step_flops(CFG, 16, 8) - correction

    def test_train_state_bytes_reconcile_with_param_count(self):
        # the OTHER direction of the cross-check: utils/flops.param_count
        # derives the parameter count from the architecture; the traced
        # train step's input bytes must be exactly 3x that (params + both
        # Adam moments) + the batch + 40 bytes of optimizer/step/PRNG
        # scalar state — pinned so either accounting drifting fails here
        prog = build_programs(["train_step"])[0]
        rec, _ = CostAnalyzer().analyze(prog)
        assert rec.input_bytes == (3 * F.model_param_bytes(CFG)
                                   + 16 * CFG.x_dim * 4 + 40)

    def test_cond_costs_one_branch_not_the_sum(self):
        # exactly one branch executes per dispatch: a matmul present in
        # both branches must count ONCE (the branch-wise max), or a future
        # guarded-merge program would double its collective/FLOP profile
        def f(pred, x):
            return jax.lax.cond(pred, lambda v: v @ v, lambda v: v @ v, x)
        rec, _ = analyze(f, True, jnp.zeros((32, 32)), name="cond_mm")
        assert rec.matmul_flops == 2.0 * 32 ** 3

    def test_matmul_flops_dominate_the_suite(self):
        # sanity on the total-FLOPs lower bound: elementwise work rides
        # along at a few percent, never the other way around
        records, _ = analyze_programs(["train_step", "serve_score"])
        for rec in records.values():
            assert 0.9 < rec.matmul_flops / rec.flops <= 1.0


# ---------------------------------------------------------------------------
# pass 1: live-range peak memory
# ---------------------------------------------------------------------------

class TestPeakMemory:
    N = 512  # fixture row count

    def test_inputs_are_resident(self):
        x = jnp.zeros((self.N,), jnp.float32)
        rec, _ = analyze(lambda x: x * 2.0, x)
        assert rec.input_bytes == self.N * 4
        assert rec.peak_bytes >= 2 * self.N * 4  # input + output live

    def test_dead_intermediates_are_freed(self):
        # a chain of 8 same-size temps: live range is ~3 buffers (input,
        # producer, consumer), NOT all 8 — the linear scan must free at
        # last use or every chain would report its length as its footprint
        def chain(x):
            for _ in range(8):
                x = x * 2.0
            return x
        rec, _ = analyze(chain, jnp.zeros((self.N,), jnp.float32))
        assert rec.peak_bytes <= 4 * self.N * 4

    def test_donation_releases_before_output_allocates(self):
        a, b = jnp.zeros((self.N,)), jnp.zeros((self.N,))
        donating = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
        plain = jax.jit(lambda a, b: a + b)
        rec_d, _ = analyze(donating, a, b)
        rec_p, _ = analyze(plain, a, b)
        # donated: the output reuses a's buffer -> peak stays at 2 arrays
        assert rec_d.peak_bytes == 2 * self.N * 4
        assert rec_p.peak_bytes == 3 * self.N * 4

    def test_scan_carry_reuse_keeps_streaming_memory_flat(self):
        # THE k=5000 eval design fact, statically: the streaming scorer's
        # peak is O(chunk), independent of how many chunks stream through
        # (the scan carry is reused, not multiplied by length)
        from iwae_replication_project_tpu.evaluation.metrics import (
            streaming_log_px)
        from iwae_replication_project_tpu.training.train_step import (
            create_train_state)

        state = create_train_state(jax.random.PRNGKey(0), CFG)
        x = jnp.zeros((4, CFG.x_dim), jnp.float32)
        key = jax.random.PRNGKey(1)

        def scorer(k):
            rec, _ = analyze(
                lambda p, key, x: streaming_log_px(p, CFG, key, x, k=k,
                                                   chunk=100),
                state.params, key, x, name=f"scorer_k{k}")
            return rec
        short, long = scorer(200), scorer(2000)
        assert long.peak_bytes > 1_000_000  # params + a real chunk block
        # 10x the chunks moves peak only by the iota of scan indices
        assert abs(long.peak_bytes - short.peak_bytes) < 16_384
        # ...while the FLOPs scale exactly 10x (scan length multiplied)
        assert long.matmul_flops / short.matmul_flops == pytest.approx(
            10.0, rel=1e-9)

    def test_memory_blowup_fires_on_materialized_outer_product(self):
        # the seeded fixture: an [n, n] outer product materialized just to
        # be summed — n^2 bytes from 2n input bytes
        x = jnp.zeros((256, 1), jnp.float32)
        y = jnp.zeros((1, 256), jnp.float32)
        rec, findings = analyze(lambda x, y: jnp.sum(x * y), x, y,
                                name="blowup")
        assert rules_of(findings) == [RULE_MEMORY_BLOWUP]
        assert "OOM cliff" in findings[0].message
        assert rec.largest_intermediate_bytes == 256 * 256 * 4

    def test_discharged_twin_is_clean(self):
        # the streaming rewrite of the same reduction: sum(x)*sum(y)
        # computes the identical number without the [n, n] intermediate
        x = jnp.zeros((256, 1), jnp.float32)
        y = jnp.zeros((1, 256), jnp.float32)
        rec, findings = analyze(lambda x, y: jnp.sum(x) * jnp.sum(y), x, y,
                                name="streamed")
        assert findings == []
        assert rec.largest_intermediate_bytes <= 256 * 4

    def test_waiver_with_justification_silences(self):
        x = jnp.zeros((256, 1), jnp.float32)
        y = jnp.zeros((1, 256), jnp.float32)
        _, findings = analyze(
            lambda x, y: jnp.sum(x * y), x, y, name="waived",
            waivers={RULE_MEMORY_BLOWUP: "fixture: the blowup is the test"})
        assert findings == []

    def test_bare_waiver_is_its_own_finding(self):
        x = jnp.zeros((256, 1), jnp.float32)
        y = jnp.zeros((1, 256), jnp.float32)
        _, findings = analyze(lambda x, y: jnp.sum(x * y), x, y,
                              name="bare", waivers={RULE_MEMORY_BLOWUP: ""})
        got = rules_of(findings)
        assert BARE_WAIVER in got and RULE_MEMORY_BLOWUP in got


# ---------------------------------------------------------------------------
# pass 3: collective accounting
# ---------------------------------------------------------------------------

class TestCollectives:
    def test_sharded_score_merge_is_one_pmax_one_psum(self):
        """PR 9's 'ONE pmax + ONE psum' merge claim, machine-checked: the
        whole collective profile of the sharded score program is exactly
        one pmax and one psum over sp — nothing else, no all-gathers."""
        prog = build_programs(["serve_score_sharded"])[0]
        rec, findings = CostAnalyzer().analyze(prog)
        assert findings == []
        assert rec.collectives == {
            "pmax": {"sp": {"count": 1.0, "bytes": 32.0}},
            "psum": {"sp": {"count": 1.0, "bytes": 32.0}},
        }

    def test_unsharded_programs_have_no_collectives(self):
        records, _ = analyze_programs(["serve_score", "train_step"])
        for rec in records.values():
            assert rec.collectives == {}
            assert rec.collective_bytes == 0.0

    def test_accidental_all_gather_is_a_finding(self):
        from jax.sharding import PartitionSpec as P

        from iwae_replication_project_tpu.parallel.mesh import (
            AXES, make_mesh, shard_map)

        mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
        f = shard_map(lambda x: jnp.sum(jax.lax.all_gather(x, AXES.dp),
                                        axis=0),
                      mesh=mesh, in_specs=(P(AXES.dp),),
                      out_specs=P(AXES.dp), check_vma=False)
        x = jnp.zeros((4, 8), jnp.float32)
        rec, findings = analyze(f, x, name="gathery")
        assert RULE_ACCIDENTAL_GATHER in rules_of(findings)
        assert "all_gather" in rec.collectives
        assert "serving-latency cliff" in findings[0].message


# ---------------------------------------------------------------------------
# roofline verdicts
# ---------------------------------------------------------------------------

class TestRoofline:
    def test_big_matmul_is_compute_bound_on_v5e(self):
        # AI of a 2048^3 matmul ~ 341 flops/byte > v5e ridge ~ 240, even
        # with zero fusion
        a = jax.ShapeDtypeStruct((2048, 2048), jnp.float32)
        rec, _ = analyze(lambda a, b: a @ b, a, a, name="mm")
        assert roofline(rec, "v5e")["verdict"] == "compute-bound"

    def test_elementwise_is_memory_bound(self):
        x = jnp.zeros((4096,), jnp.float32)
        rec, _ = analyze(lambda x, y: x + y, x, x, name="ew")
        assert roofline(rec, "v5e")["verdict"] == "memory-bound"

    def test_unknown_chip_reports_null_not_fabricated(self):
        x = jnp.zeros((8,), jnp.float32)
        rec, _ = analyze(lambda x: x * 2, x)
        rl = roofline(rec, "mystery9000")
        assert rl["verdict"] is None and "mystery9000" in \
            rl["verdict_null_reason"]

    def test_mfu_ceiling_is_a_fraction(self):
        prog = build_programs(["serve_score"])[0]
        rec, _ = CostAnalyzer().analyze(prog)
        ceiling = roofline(rec, "v5e")["static_mfu_ceiling"]
        assert 0.0 < ceiling <= 1.0

    def test_chip_resolution_never_silent(self):
        kind, source = resolve_chip(None)
        if jax.default_backend() != "tpu":
            assert kind == "v5e" and "assuming" in source
        kind, source = resolve_chip("v4")
        assert kind == "v4" and source == "explicit --chip"


# ---------------------------------------------------------------------------
# the real program suite + registry integration
# ---------------------------------------------------------------------------

class TestRealSuite:
    def test_full_suite_analyzes_clean(self):
        """THE acceptance gate: cost records for all 10 programs, zero
        findings (scripts/check.py's cost stage contract)."""
        records, findings = analyze_programs()
        assert findings == [], "\n".join(f.human() for f in findings)
        assert len(records) == 10
        for rec in records.values():
            assert rec.peak_bytes > 0 and rec.flops > 0

    def test_eval_scorer_sits_under_the_blowup_threshold_with_margin(self):
        # the flagship suite's honest worst case (the [chunk, B, 784]
        # block) must not creep toward the 16x default silently
        records, _ = analyze_programs(["eval_scorer_k5000"])
        rec = records["eval_scorer_k5000"]
        ratio = rec.largest_intermediate_bytes / rec.input_bytes
        assert ratio < DEFAULT_BLOWUP_FACTOR * 0.75

    def test_registry_entries_gain_static_cost_records(self):
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, isolated_aot_registry, static_cost_records)
        with isolated_aot_registry():
            x = jnp.zeros((64, 32), jnp.float32)
            aot_call("cost_probe", jax.jit(lambda x: x @ x.T), (x,))
            records = static_cost_records()
        assert len(records) == 1
        name, _, _, cost = records[0]
        assert name == "cost_probe"
        assert cost is not None
        assert cost["matmul_flops"] == 2.0 * 64 * 32 * 64
        assert cost["arg_bytes"] == 64 * 32 * 4
        assert cost["peak_bytes"] > 0

    def test_static_cost_stamp_can_be_disabled(self, monkeypatch):
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, isolated_aot_registry, static_cost_records)
        monkeypatch.setenv("IWAE_STATIC_COST", "off")
        with isolated_aot_registry():
            aot_call("cost_off", jax.jit(lambda x: x * 2),
                     (jnp.zeros((4,), jnp.float32),))
            records = static_cost_records()
        assert len(records) == 1
        assert records[0][3] is None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, module, *args):
        return subprocess.run(
            [sys.executable, "-m",
             f"iwae_replication_project_tpu.analysis.audit{module}", *args],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    def test_clean_json_run(self, tmp_path):
        report = tmp_path / "cost_report.json"
        r = self._run(".cost", "--format", "json",
                      "--programs", "hot_loop_reference,hot_loop_pallas",
                      "--report", str(report))
        assert r.returncode == 0, r.stdout + r.stderr
        payload = json.loads(r.stdout)
        assert payload["total"] == 0
        assert set(payload["programs"]) == {"hot_loop_reference",
                                            "hot_loop_pallas"}
        rec = payload["programs"]["hot_loop_reference"]
        assert rec["peak_bytes"] > 0 and rec["roofline"]["chip"]
        assert json.loads(report.read_text())["programs"].keys() \
            == payload["programs"].keys()

    def test_findings_exit_1(self):
        # an absurd threshold turns an ordinary intermediate into a
        # finding: exit code 1 (findings), not 2 (crash)
        r = self._run(".cost", "--programs", "hot_loop_reference",
                      "--blowup-factor", "0.1")
        assert r.returncode == 1
        assert RULE_MEMORY_BLOWUP in r.stdout

    def test_unknown_program_exits_2_listing_valid_names(self):
        """The satellite fix, pinned at the CLI layer for BOTH consumers of
        the shared program registry: a typo'd --programs must exit 2 with
        the valid names in the error, never a bare traceback."""
        for module in (".cost", ""):
            r = self._run(module, "--programs", "no_such_program")
            assert r.returncode == 2, (module, r.stdout, r.stderr)
            assert "unknown program" in r.stderr
            assert "serve_score_sharded" in r.stderr  # the names are listed
            assert "Traceback" not in r.stderr

    def test_committed_report_matches_the_suite(self):
        """results/cost_report.json is a committed artifact: it must name
        every audited program and pin the sharded collective profile."""
        with open(os.path.join(REPO, "results", "cost_report.json"),
                  encoding="utf-8") as f:
            report = json.load(f)
        assert set(report["programs"]) == {
            "train_step", "eval_scorer_k5000", "serve_score", "serve_encode",
            "serve_decode", "serve_score_fused", "serve_score_sharded",
            "hot_loop_reference", "hot_loop_blocked_scan",
            "hot_loop_pallas"}
        assert report["total"] == 0
        sharded = report["programs"]["serve_score_sharded"]
        assert sharded["collectives"] == {
            "pmax": {"sp": {"count": 1.0, "bytes": 32.0}},
            "psum": {"sp": {"count": 1.0, "bytes": 32.0}}}
