"""Data-layer tests: loaders, binarization policies, bias init, batching."""

import numpy as np
import pytest

from iwae_replication_project_tpu.data import (
    Binarization,
    epoch_batches,
    load_dataset,
    output_bias_from_pixel_means,
)


class TestLoaders:
    @pytest.mark.parametrize("name", ["binarized_mnist", "mnist", "fashion_mnist",
                                      "omniglot"])
    def test_synthetic_fallback_shapes(self, name, tmp_path):
        ds = load_dataset(name, data_dir=str(tmp_path), allow_synthetic=True)
        assert ds.x_train.shape[1] == 784
        assert ds.x_test.shape[1] == 784
        assert ds.x_train.dtype == np.float32
        assert 0.0 <= ds.x_train.min() and ds.x_train.max() <= 1.0
        assert ds.bias_means.shape == (784,)

    def test_unknown_dataset_raises(self):
        with pytest.raises(ValueError):
            load_dataset("cifar10")

    def test_no_synthetic_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_dataset("mnist", data_dir=str(tmp_path), allow_synthetic=False)

    def test_binarization_policy(self, tmp_path):
        assert load_dataset("binarized_mnist", data_dir=str(tmp_path)).binarization == "none"
        assert load_dataset("mnist", data_dir=str(tmp_path)).binarization == "stochastic"

    def test_npz_loading(self, tmp_path):
        rs = np.random.RandomState(0)
        x_train = rs.randint(0, 256, (20, 28, 28)).astype(np.uint8)
        x_test = rs.randint(0, 256, (10, 28, 28)).astype(np.uint8)
        np.savez(tmp_path / "mnist.npz", x_train=x_train, x_test=x_test)
        ds = load_dataset("mnist", data_dir=str(tmp_path), allow_synthetic=False)
        assert ds.x_train.shape == (20, 784)
        assert ds.x_train.max() <= 1.0
        np.testing.assert_allclose(ds.bias_means, ds.x_train.mean(0))

    def test_synthetic_deterministic(self, tmp_path):
        a = load_dataset("mnist", data_dir=str(tmp_path))
        b = load_dataset("mnist", data_dir=str(tmp_path))
        np.testing.assert_array_equal(a.x_train, b.x_train)

    def test_synthetic_stable_across_processes(self, tmp_path):
        """The synthetic seed must not depend on Python's salted str hash —
        resume across interpreter restarts needs identical data."""
        import subprocess
        import sys
        code = ("import sys; sys.path.insert(0, '/root/repo'); "
                "from iwae_replication_project_tpu.data import load_dataset; "
                f"ds = load_dataset('mnist', data_dir={str(tmp_path)!r}); "
                "print(float(ds.x_train.sum()))")
        outs = set()
        for _ in range(2):
            r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                               text=True, env={"PYTHONHASHSEED": "random",
                                               "PATH": "/usr/bin:/bin",
                                               "JAX_PLATFORMS": "cpu"})
            assert r.returncode == 0, r.stderr
            outs.add(r.stdout.strip().splitlines()[-1])
        assert len(outs) == 1, outs

    def test_fashion_mnist_does_not_steal_root_mnist_files(self, tmp_path):
        """Root-level idx files belong to plain MNIST; fashion_mnist must not
        silently load them (same filenames, different dataset)."""
        from fixture_io import write_idx_gz
        img = np.zeros((3, 28, 28), np.uint8)
        for split in ("train-images-idx3-ubyte.gz", "t10k-images-idx3-ubyte.gz"):
            write_idx_gz(tmp_path / split, img)
        assert load_dataset("mnist", data_dir=str(tmp_path),
                            allow_synthetic=False).x_train.shape == (3, 784)
        with pytest.raises(FileNotFoundError):
            load_dataset("fashion_mnist", data_dir=str(tmp_path),
                         allow_synthetic=False)


class TestRealFormatLoaders:
    def test_amat_loading(self, tmp_path, capsys):
        """Larochelle-format .amat text files (the reference's
        binarized-MNIST source, README.md:10)."""
        rs = np.random.RandomState(3)
        xtr = (rs.rand(6, 784) > 0.5).astype(np.float32)
        xte = (rs.rand(4, 784) > 0.5).astype(np.float32)
        np.savetxt(tmp_path / "binarized_mnist_train.amat", xtr, fmt="%d")
        np.savetxt(tmp_path / "binarized_mnist_test.amat", xte, fmt="%d")
        ds = load_dataset("binarized_mnist", data_dir=str(tmp_path),
                          allow_synthetic=False)
        assert not ds.synthetic
        np.testing.assert_array_equal(ds.x_train, xtr)
        np.testing.assert_array_equal(ds.x_test, xte)
        assert ds.binarization == "none"
        # no raw MNIST present -> bias falls back to the binary train means,
        # and says so loudly (this is a known NLL lever, VERDICT r3 Weak #2)
        np.testing.assert_allclose(ds.bias_means, xtr.mean(0))
        assert ds.bias_source == "train"
        out = capsys.readouterr()
        assert "WITHOUT raw MNIST" in out.out
        assert "WITHOUT raw MNIST" in out.err

    def test_amat_with_raw_mnist_bias_policy(self, tmp_path):
        """With raw MNIST alongside, the fixed-bin bias must use the RAW
        means (flexible_IWAE.py:150-155 policy)."""
        rs = np.random.RandomState(4)
        xtr = (rs.rand(6, 784) > 0.5).astype(np.float32)
        xte = (rs.rand(4, 784) > 0.5).astype(np.float32)
        np.savetxt(tmp_path / "binarized_mnist_train.amat", xtr, fmt="%d")
        np.savetxt(tmp_path / "binarized_mnist_test.amat", xte, fmt="%d")
        raw_train = rs.randint(0, 256, (5, 28, 28)).astype(np.uint8)
        raw_test = rs.randint(0, 256, (2, 28, 28)).astype(np.uint8)
        np.savez(tmp_path / "mnist.npz", x_train=raw_train, x_test=raw_test)
        ds = load_dataset("binarized_mnist", data_dir=str(tmp_path),
                          allow_synthetic=False)
        np.testing.assert_allclose(
            ds.bias_means,
            (raw_train.reshape(-1, 784).astype(np.float32) / 255.0).mean(0),
            rtol=1e-6)
        assert ds.bias_source == "raw"

    def test_omniglot_chardata_mat(self, tmp_path):
        """Burda-split Omniglot chardata.mat (flexible_IWAE.py:164-165):
        columns are examples, `data`/`testdata` keys."""
        import scipy.io as sio
        rs = np.random.RandomState(5)
        xtr = rs.rand(784, 7).astype(np.float32)
        xte = rs.rand(784, 3).astype(np.float32)
        sio.savemat(tmp_path / "chardata.mat", {"data": xtr, "testdata": xte})
        ds = load_dataset("omniglot", data_dir=str(tmp_path),
                          allow_synthetic=False)
        assert not ds.synthetic
        assert ds.x_train.shape == (7, 784)
        assert ds.x_test.shape == (3, 784)
        np.testing.assert_allclose(ds.x_train, xtr.T, rtol=1e-6)
        assert ds.binarization == "stochastic"

    def test_digits_is_real_offline_data(self, tmp_path):
        """sklearn's bundled optdigits: real handwritten digits, fixed-bin
        MNIST protocol (784-dim binary, deterministic, raw-means bias)."""
        ds = load_dataset("digits", data_dir=str(tmp_path))
        assert not ds.synthetic
        assert ds.x_train.shape == (1500, 784)
        assert ds.x_test.shape == (297, 784)
        assert set(np.unique(ds.x_train)) <= {0.0, 1.0}
        assert ds.binarization == "none"
        # deterministic across loads (fixed binarization draw)
        ds2 = load_dataset("digits", data_dir=str(tmp_path))
        np.testing.assert_array_equal(ds.x_train, ds2.x_train)
        # bias comes from raw grayscale means, not the binarized pixels
        assert not np.allclose(ds.bias_means, ds.x_train.mean(0))
        assert ds.bias_source == "raw"

    def test_digits_gray_is_real_stochastic_protocol(self, tmp_path):
        """digits_gray: the same real optdigits images with grayscale
        intensities kept and the per-epoch stochastic-binarization policy
        (PDF Table 2 protocol on real data, VERDICT r3 Missing #5)."""
        ds = load_dataset("digits_gray", data_dir=str(tmp_path))
        assert not ds.synthetic
        assert ds.binarization == "stochastic"
        assert ds.x_train.shape == (1500, 784)
        # genuinely grayscale: the stochastic path must see values in (0,1),
        # else per-epoch bernoulli(p) degenerates to the identity
        interior = (ds.x_train > 0.05) & (ds.x_train < 0.95)
        assert interior.mean() > 0.05
        # same underlying images as `digits`: the fixed-bin draw of `digits`
        # has pixel means close to these intensities
        fixed = load_dataset("digits", data_dir=str(tmp_path))
        np.testing.assert_allclose(ds.x_train.mean(), fixed.x_train.mean(),
                                   atol=0.02)
        # bias = grayscale train means (the raw means for this dataset)
        np.testing.assert_allclose(ds.bias_means, ds.x_train.mean(0))

    def test_synthetic_fallback_never_claims_raw_bias(self, tmp_path):
        """Raw MNIST idx/npz present but NO .amat pair -> synthetic blobs are
        substituted; the raw means must NOT leak into the bias init (metrics
        would otherwise certify raw_means_bias=1 on a fake-data run)."""
        rs = np.random.RandomState(6)
        np.savez(tmp_path / "mnist.npz",
                 x_train=rs.randint(0, 256, (4, 28, 28)).astype(np.uint8),
                 x_test=rs.randint(0, 256, (2, 28, 28)).astype(np.uint8))
        ds = load_dataset("binarized_mnist", data_dir=str(tmp_path),
                          allow_synthetic=True)
        assert ds.synthetic
        assert ds.bias_source == "train"
        np.testing.assert_allclose(ds.bias_means, ds.x_train.mean(0))

    def test_synthetic_fallback_is_loud_and_flagged(self, tmp_path, capsys):
        ds = load_dataset("mnist", data_dir=str(tmp_path), allow_synthetic=True)
        assert ds.synthetic
        out = capsys.readouterr()
        assert "SYNTHETIC" in out.out
        assert "SYNTHETIC" in out.err
        # real data is never flagged
        rs = np.random.RandomState(0)
        np.savez(tmp_path / "mnist.npz",
                 x_train=rs.randint(0, 256, (4, 28, 28)).astype(np.uint8),
                 x_test=rs.randint(0, 256, (2, 28, 28)).astype(np.uint8))
        assert not load_dataset("mnist", data_dir=str(tmp_path)).synthetic


class TestBias:
    def test_formula(self):
        """bias = logit of clipped mean (flexible_IWAE.py:174)."""
        means = np.array([0.0, 0.5, 1.0, 0.25])
        bias = output_bias_from_pixel_means(means)
        clipped = np.clip(means, 0.001, 0.999)
        np.testing.assert_allclose(bias, np.log(clipped / (1 - clipped)), rtol=1e-5)
        # sigmoid(bias) recovers the clipped means
        np.testing.assert_allclose(1 / (1 + np.exp(-bias)), clipped, rtol=1e-4)


class TestPipeline:
    def test_batch_shapes_and_drop_remainder(self):
        x = np.random.RandomState(0).rand(105, 784).astype(np.float32)
        batches = list(epoch_batches(x, 10, epoch=0))
        assert len(batches) == 10
        assert all(b.shape == (10, 784) for b in batches)

    def test_shuffle_covers_all_and_differs_by_epoch(self):
        x = np.arange(40, dtype=np.float32).reshape(40, 1)
        b0 = np.concatenate(list(epoch_batches(x, 10, epoch=0)))
        b1 = np.concatenate(list(epoch_batches(x, 10, epoch=1)))
        assert set(b0.ravel()) == set(range(40))
        assert not np.array_equal(b0, b1)

    def test_deterministic_given_seed_epoch(self):
        x = np.random.RandomState(0).rand(40, 4).astype(np.float32)
        a = list(epoch_batches(x, 10, epoch=3, seed=7))
        b = list(epoch_batches(x, 10, epoch=3, seed=7))
        for u, v in zip(a, b):
            np.testing.assert_array_equal(u, v)

    def test_stochastic_binarization(self):
        x = np.full((20, 784), 0.5, np.float32)
        batches = list(epoch_batches(x, 10, epoch=0,
                                     binarization=Binarization.STOCHASTIC))
        vals = np.concatenate(batches)
        assert set(np.unique(vals)) <= {0.0, 1.0}
        assert 0.3 < vals.mean() < 0.7
        # fresh draws each epoch
        again = np.concatenate(list(epoch_batches(x, 10, epoch=1,
                                                  binarization=Binarization.STOCHASTIC)))
        assert not np.array_equal(vals, again)
