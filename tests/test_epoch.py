"""Whole-epoch scan tests: descent, determinism, binarization-on-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.training import create_train_state
from iwae_replication_project_tpu.training.epoch import make_epoch_fn

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)


@pytest.fixture
def x_train():
    return (jax.random.uniform(jax.random.PRNGKey(9), (64, 12)) > 0.5).astype(jnp.float32)


class TestEpochFn:
    def test_losses_shape_and_descent(self, rng, x_train):
        state = create_train_state(rng, CFG)
        epoch = make_epoch_fn(ObjectiveSpec("IWAE", k=8), CFG, 64, 16, donate=False)
        first = None
        for _ in range(15):
            state, losses = epoch(state, x_train)
            assert losses.shape == (4,)
            if first is None:
                first = float(jnp.mean(losses))
        assert float(jnp.mean(losses)) < first
        assert int(state.step) == 60

    def test_multi_epoch_call_matches_repeated_single(self, rng, x_train):
        """epochs_per_call=3 must reproduce 3 single-epoch dispatches exactly
        (same key threading, same update sequence) with concatenated losses."""
        spec = ObjectiveSpec("IWAE", k=4)
        single = make_epoch_fn(spec, CFG, 64, 16, donate=False)
        multi = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                              epochs_per_call=3)
        s_single = create_train_state(rng, CFG)
        all_losses = []
        for _ in range(3):
            s_single, losses = single(s_single, x_train)
            all_losses.append(np.asarray(losses))
        s_multi, losses_multi = multi(create_train_state(rng, CFG), x_train)
        assert losses_multi.shape == (12,)
        np.testing.assert_allclose(np.asarray(losses_multi),
                                   np.concatenate(all_losses), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            s_single.params, s_multi.params)
        assert int(s_multi.step) == int(s_single.step) == 12

    def test_deterministic_given_state(self, rng, x_train):
        s0 = create_train_state(rng, CFG)
        epoch = make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 64, 16, donate=False)
        s1, l1 = epoch(s0, x_train)
        s2, l2 = epoch(create_train_state(rng, CFG), x_train)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     s1.params, s2.params)

    def test_epochs_differ(self, rng, x_train):
        state = create_train_state(rng, CFG)
        epoch = make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 64, 16, donate=False)
        state, l1 = epoch(state, x_train)
        state, l2 = epoch(state, x_train)
        assert not np.allclose(np.asarray(l1), np.asarray(l2))

    @pytest.mark.slow
    def test_stochastic_binarization_on_device(self, rng):
        # gray 0.5 inputs: with on-device binarization the model sees binary
        # pixels, so losses differ from the no-binarization run
        x_gray = jnp.full((32, 12), 0.5)
        state = create_train_state(rng, CFG)
        e_bin = make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 32, 16,
                              stochastic_binarization=True, donate=False)
        e_raw = make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 32, 16, donate=False)
        _, l_bin = e_bin(state, x_gray)
        _, l_raw = e_raw(create_train_state(rng, CFG), x_gray)
        assert not np.allclose(np.asarray(l_bin), np.asarray(l_raw))

    def test_no_shuffle_visits_in_order(self, rng, x_train):
        state = create_train_state(rng, CFG)
        epoch = make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 64, 16,
                              shuffle=False, donate=False)
        _, losses = epoch(state, x_train)
        assert losses.shape == (4,)

    def test_batch_size_too_large_raises(self, rng):
        with pytest.raises(ValueError):
            make_epoch_fn(ObjectiveSpec("VAE", k=4), CFG, 8, 16)
