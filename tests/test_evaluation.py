"""Evaluation-suite tests: streaming NLL vs materialized, metric identities,
active units on a model with deliberately dead latents."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.evaluation import (
    active_units,
    batch_metrics,
    nll_without_inactive_units,
    posterior_mean_activity,
    pca_eigenvalues,
    reconstruction_loss,
    streaming_log_px,
    training_statistics,
)
from iwae_replication_project_tpu.models import ModelConfig, init_params, log_weights
from iwae_replication_project_tpu.ops.logsumexp import logmeanexp

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)
CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


@pytest.fixture
def setup(rng):
    params = init_params(rng, CFG)
    x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5).astype(jnp.float32)
    return params, x


class TestDatasetScalars:
    @pytest.mark.slow
    def test_fused_scan_matches_per_batch_host_loop(self, rng):
        """The single-dispatch whole-dataset program reproduces the per-batch
        kernel loop it replaced (same fold_in(key, i) + 3-way split RNG
        structure per batch), to accumulation-order rounding."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            SCALAR_NAMES, dataset_scalars)

        params = init_params(rng, CFG)
        x = (jax.random.uniform(jax.random.PRNGKey(2), (24, 12)) > 0.5
             ).astype(jnp.float32)
        key = jax.random.PRNGKey(5)
        k, nll_k, nll_chunk, bs = 4, 16, 8, 8
        batches = x.reshape(3, bs, 12)

        fused = np.asarray(dataset_scalars(params, CFG, key, batches, k,
                                           nll_k, nll_chunk))

        acc = {name: 0.0 for name in SCALAR_NAMES}
        for i in range(3):
            bkey = jax.random.fold_in(key, i)
            k1, k2, k3 = jax.random.split(bkey, 3)
            m = batch_metrics(params, CFG, k1, batches[i], k)
            nll = -float(jnp.mean(streaming_log_px(params, CFG, k2,
                                                   batches[i], k=nll_k,
                                                   chunk=nll_chunk)))
            acc["VAE"] += float(m["VAE"]) / 3
            acc["IWAE"] += float(m["IWAE"]) / 3
            acc["NLL"] += nll / 3
            acc["E_q(h|x)[log(p(x|h))]"] += float(m["E_q(h|x)[log(p(x|h))]"]) / 3
            acc["D_kl(q(h|x),p(h))"] += float(m["D_kl(q(h|x),p(h))"]) / 3
            acc["D_kl(q(h|x),p(h|x))"] += (-nll - float(m["VAE"])) / 3
            acc["reconstruction_loss"] += float(
                reconstruction_loss(params, CFG, k3, batches[i])) / 3

        for j, name in enumerate(SCALAR_NAMES):
            np.testing.assert_allclose(fused[j], acc[name], rtol=1e-5,
                                       atol=1e-5, err_msg=name)


class TestStreamingNLL:
    def test_matches_one_shot_same_keys(self, setup):
        """Chunked online logsumexp == materialized logmeanexp when the chunks
        see the same draws."""
        params, x = setup
        key = jax.random.PRNGKey(3)
        k, chunk = 40, 8
        got = streaming_log_px(params, CFG, key, x, k=k, chunk=chunk)
        # rebuild the same per-chunk weights and reduce in one shot
        lws = [log_weights(params, CFG, jax.random.fold_in(key, i), x, chunk)
               for i in range(k // chunk)]
        want = logmeanexp(jnp.concatenate(lws, axis=0), axis=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_bad_chunk_raises(self, setup):
        params, x = setup
        with pytest.raises(ValueError):
            streaming_log_px(params, CFG, jax.random.PRNGKey(0), x, k=41, chunk=8)


class TestBatchMetrics:
    def test_kl_identity(self, setup):
        """D_kl(q||p(h)) metric == recon term - VAE bound by construction."""
        params, x = setup
        m = batch_metrics(params, CFG, jax.random.PRNGKey(0), x, k=16)
        np.testing.assert_allclose(
            float(m["D_kl(q(h|x),p(h))"]),
            float(m["E_q(h|x)[log(p(x|h))]"] - m["VAE"]), rtol=1e-5)

    def test_iwae_geq_vae(self, setup):
        params, x = setup
        m = batch_metrics(params, CFG, jax.random.PRNGKey(0), x, k=16)
        assert float(m["IWAE"]) >= float(m["VAE"]) - 1e-5

    def test_reconstruction_loss_positive(self, setup):
        params, x = setup
        r = reconstruction_loss(params, CFG, jax.random.PRNGKey(0), x)
        assert float(r) > 0


class TestActiveUnits:
    @pytest.mark.slow
    def test_activity_shapes(self, rng):
        params = init_params(rng, CFG2)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (20, 12)) > 0.5).astype(jnp.float32)
        variances, eigvals = posterior_mean_activity(params, CFG2,
                                                     jax.random.PRNGKey(2), x,
                                                     n_samples=20, chunk=10)
        assert len(variances) == 2
        assert variances[0].shape == (6,) and variances[1].shape == (3,)
        assert eigvals[0].shape == (6,) and eigvals[1].shape == (3,)

    def test_pca_eigenvalues_match_numpy(self):
        data = np.random.RandomState(0).randn(50, 5).astype(np.float32)
        got = np.sort(np.asarray(pca_eigenvalues(jnp.asarray(data))))
        centered = data - data.mean(0)
        want = np.sort(np.linalg.eigvalsh(centered.T @ centered / 50))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_dead_unit_detected(self, rng):
        """A latent coordinate whose encoder weights are zeroed must read as
        inactive (variance of its posterior mean ~ 0)."""
        params = init_params(rng, CFG)
        # kill latent 0: zero its mu-head column -> posterior mean constant 0
        mu = params["enc"][0]["mu"]
        mu["w"] = mu["w"].at[:, 0].set(0.0)
        mu["b"] = mu["b"].at[0].set(0.0)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (30, 12)) > 0.5).astype(jnp.float32)
        variances, eigvals = posterior_mean_activity(params, CFG,
                                                     jax.random.PRNGKey(2), x,
                                                     n_samples=200, chunk=20)
        masks, n_active, _ = active_units(variances, eigvals, threshold=0.01)
        assert masks[0][0] == 0.0
        assert n_active[0] <= 3

    def test_pruned_nll_close_when_pruning_dead_unit(self, rng):
        """Zeroing an already-dead unit should barely move the NLL."""
        params = init_params(rng, CFG)
        mu = params["enc"][0]["mu"]
        mu["w"] = mu["w"].at[:, 0].set(0.0)
        mu["b"] = mu["b"].at[0].set(0.0)
        lstd = params["enc"][0]["lstd"]
        lstd["w"] = lstd["w"].at[:, 0].set(0.0)
        lstd["b"] = lstd["b"].at[0].set(-6.0)  # tiny posterior std for unit 0
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5).astype(jnp.float32)
        masks = (jnp.array([0.0, 1.0, 1.0, 1.0]),)
        pruned = float(nll_without_inactive_units(params, CFG, jax.random.PRNGKey(2),
                                                  x, masks, k=200, chunk=50))
        from iwae_replication_project_tpu.evaluation.metrics import streaming_nll
        full = float(streaming_nll(params, CFG, jax.random.PRNGKey(2), x,
                                   k=200, chunk=50))
        assert abs(pruned - full) < 2.0


class TestTrainingStatistics:
    def test_full_driver_schema(self, rng):
        params = init_params(rng, CFG)
        x_test = (jax.random.uniform(jax.random.PRNGKey(1), (20, 12)) > 0.5).astype(jnp.float32)
        res, res2 = training_statistics(params, CFG, jax.random.PRNGKey(2),
                                        x_test, k=8, batch_size=10, nll_k=40,
                                        nll_chunk=20, activity_samples=20)
        for key in ("VAE", "IWAE", "NLL", "E_q(h|x)[log(p(x|h))]",
                    "D_kl(q(h|x),p(h))", "D_kl(q(h|x),p(h|x))",
                    "reconstruction_loss", "LL_pruned"):
            assert key in res and np.isfinite(res[key]), key
        assert len(res2["number_of_active_units"]) == 1
        assert res2["active_units"][0].shape == (4,)
        assert res["NLL"] > 0

    def test_non_dividing_batch_size_adapts(self, rng):
        """A batch size that doesn't divide the test set falls back to the
        largest divisor instead of crashing (found driving the CLI on a
        256-image synthetic test set with the default eval batch of 100)."""
        params = init_params(rng, CFG)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (10, 12)) > 0.5).astype(jnp.float32)
        res, _ = training_statistics(params, CFG, jax.random.PRNGKey(0), x, k=4,
                                     batch_size=3, nll_k=8, nll_chunk=4,
                                     activity_samples=4, include_pruned_nll=False)
        assert np.isfinite(res["NLL"])
